"""Self-healing serving fleet: N supervised engine-replica processes.

PR 5's review settled that serving scale-out is N engine processes, not
in-engine sharding (AOT executables are lowered from bare single-device
avals); this module builds the N and keeps them healthy without operator
action. One supervisor (`Fleet`) spawns each replica as a
`deepof_tpu serve --config-json <replica-dir>/config.json` subprocess on
an ephemeral port (`serve.port=0`; the replica announces its bound port
on stdout), and a monitor thread runs every replica through a small
state machine:

    starting -> ready -> terminating -> backoff -> starting ...
                                  \\-> broken (circuit breaker)

Health gating reuses the existing serve heartbeat: each replica's
`heartbeat.json` (rewritten every obs.heartbeat_period_s, wedge-watchdog
verdict included) is the supervisor's input. A replica is evicted —
SIGTERM for graceful drain, SIGKILL after `fleet.term_grace_s` — when
its heartbeat goes stale, its watchdog marks `wedged: true`, or its
process dies outright (kill -9, OOM, crash). Respawns back off
exponentially (`fleet.backoff_s * 2^k`, capped), and a replica that
keeps dying within `fleet.healthy_after_s` of becoming ready trips the
circuit breaker after `fleet.crash_loop_threshold` consecutive fast
failures: it stays down (state `broken`), surfaced in the fleet
counters, instead of burning backoff forever while masking the defect.

The chaos sites `replica_crash` / `replica_wedge`
(resilience/faults.py) inject exactly these failures deterministically —
each replica process rebuilds the injector from the shared config and
its own `DEEPOF_TPU_REPLICA` index, so fleet chaos runs reproduce from
config alone.

Replicas inherit the supervisor's exact serve ladder INCLUDING the
precision tiers (`serve.precisions` round-trips through the replica
config.json), so every replica can serve every (bucket, tier) pair
while the router concentrates each pair's traffic on its affinity
replica (serve/router.py folds the tier into the affinity map). The
streaming-session knobs (`serve.session.*`, serve/session.py) round-trip
the same way: every replica runs the same session TTL/LRU bounds the
router's sticky map mirrors, so a session pinned to a replica expires at
the front and at the back on the same clock. Session state is
deliberately replica-local — an evicted or crashed replica takes its
sessions with it, and the router demotes those to structured
`session_lost` replies (clients re-prime on a healthy replica) instead
of migrating state across processes.

`run_fleet` is the `serve --replicas N` entry: fleet + front router
(serve/router.py) + a fleet heartbeat whose `fleet_*` counter block
(evictions, respawns, failovers, shed, per-replica states) lands in
`heartbeat.json` and the shutdown metrics record for `deepof_tpu tail`
— which exits nonzero when the block shows evictions or a broken
replica. Shutdown and SIGTERM drain gracefully: stop admission at the
router, flush in-flight requests, then SIGTERM (and if needed SIGKILL)
the replicas.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
from types import SimpleNamespace

from ..core import supervise
from ..core.config import ExperimentConfig
from ..obs import incident
from ..core.supervise import wait_for_listen  # noqa: F401 - re-export:
#   tests/conftest.py and the chaos suites import it from here; the
#   canonical definition moved to the shared supervisor core
from .server import REPLICA_ENV

#: Replica lifecycle states (Fleet._check is the transition table).
#: "spawning" is the transient claim a monitor pass holds while it runs
#: the (lock-free) process spawn for a slot. "retiring"/"retired" are
#: the autoscaler's graceful scale-down path (serve/autoscale.py):
#: routed around, drained, SIGTERMed, reaped — never counted as an
#: eviction, because nothing was sick.
STATES = ("spawning", "starting", "ready", "terminating", "backoff",
          "broken", "stopped", "retiring", "retired")


class _Replica(supervise.Child):
    """Supervisor-side record of one replica slot. All mutation happens
    under the fleet lock; the router sees only immutable snapshots."""

    def __init__(self, idx: int):
        super().__init__(idx, "stopped")
        self.port: int | None = None
        self.ready_m: float | None = None
        self.term_deadline = 0.0
        self.backoff_until = 0.0
        self.fast_failures = 0


def _serve_in_flight(hb: dict) -> bool:
    """The fleet's stall gate for the shared heartbeat verdict: the
    stall clock is meaningful only while work is in flight (submitted >
    answered — last_step_age_s only resets on beat() or the idle
    touch(), and the serve sample touch()es only when everything
    submitted is answered)."""
    return (hb.get("serve_requests", 0) - hb.get("serve_responses", 0)
            - hb.get("serve_errors", 0)) > 0


class Fleet:
    """See module docstring.

    cfg: the fleet-level experiment config; each replica gets a copy
        with its own log_dir, serve.port=0, and fleet.replicas=0
        serialized to <replica-dir>/config.json.
    replicas: replica count (overrides cfg.serve.fleet.replicas).
    """

    def __init__(self, cfg: ExperimentConfig, replicas: int | None = None):
        self.cfg = cfg
        self.fc = cfg.serve.fleet
        n = int(replicas) if replicas is not None else int(self.fc.replicas)
        n = max(n, 1)
        if self.fc.autoscale:
            lo = max(int(self.fc.min_replicas), 1)
            hi = max(int(self.fc.max_replicas), 1)
            if lo > hi:
                raise ValueError(
                    f"serve.fleet.min_replicas={self.fc.min_replicas} > "
                    f"max_replicas={self.fc.max_replicas}: the autoscale "
                    "bounds are unsatisfiable — fix the config rather "
                    "than let the pool pick a side")
            # the autoscaler owns the pool size between its bounds:
            # start inside them whatever --replicas said
            n = min(max(n, lo), hi)
        self.dir = cfg.train.log_dir
        self.host = cfg.serve.host
        self._lock = threading.RLock()
        self._replicas = [_Replica(i) for i in range(n)]
        self._counters = {k: 0 for k in (
            "spawns", "respawns", "evictions", "crashes", "clean_exits",
            "wedge_evictions", "stale_evictions", "spawn_failures",
            "kill_escalations", "broken", "retired")}
        self._stopping = False
        self._active = n  # cached non-retired slot count (see size)
        self._wake = threading.Event()
        # scale-down hook (run_fleet wires the router's map aging):
        # called with the retired slot's idx AFTER the replica is gone
        self.on_retired = None
        # incident plane (obs/incident.py): run_fleet installs the
        # supervisor's recorder. Triggers fire inside the locked state
        # machine, so they queue here and _drain_incidents captures
        # them AFTER the fleet lock is released (capture does disk I/O
        # and the lock discipline above forbids I/O under it).
        self.incidents = None
        self._pending_incidents: list[tuple[str, str, dict]] = []
        self._monitor = threading.Thread(target=self._run, daemon=True,
                                         name="fleet-monitor")

    @property
    def size(self) -> int:
        """ACTIVE replica slots (everything but retired) — the modulus
        of the router's affinity map and its sticky-cap factor. Fixed
        for a plain fleet; shrinks/grows with the autoscaler's scale
        events (slot indices stay monotonic — a retired index is never
        reused, so per-index maps can age it out unambiguously). A
        cached integer, maintained under the lock at the two mutation
        sites (scale_up append, retire_one retirement) and read without
        it — the router reads this up to three times per request, and
        iterating a monotonically-growing slot list under the fleet
        lock on the proxy hot path would contend with the monitor."""
        return self._active

    # ------------------------------------------------------------ start
    def start(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        with self._lock:
            for r in self._replicas:
                r.state = "spawning"  # claim every slot before spawning
        for r in self._replicas:
            self._spawn(r)
        self._monitor.start()

    def wait_ready(self, min_ready: int = 1, timeout_s: float = 180.0) -> None:
        """Block until `min_ready` replicas are serving (TimeoutError
        otherwise, naming each replica's state for the operator)."""
        deadline = time.monotonic() + max(float(timeout_s), 0.0)
        while True:
            # drive transitions ourselves: callers may wait before the
            # monitor's first poll tick
            self._poll_all()
            now = time.monotonic()
            with self._lock:
                ready = sum(r.state == "ready" for r in self._replicas)
                states = {f"replica-{r.idx}": r.state for r in self._replicas}
            if ready >= min_ready:
                return
            if now >= deadline:
                raise TimeoutError(
                    f"only {ready}/{min_ready} replicas ready after "
                    f"{timeout_s}s: {states}")
            time.sleep(0.05)

    # ------------------------------------------------------------ spawn
    def _replica_dir(self, r: _Replica) -> str:
        return os.path.join(self.dir, f"replica-{r.idx}")

    def _spawn(self, r: _Replica) -> None:
        """Spawn one replica process for a slot already claimed (state
        "spawning") under the lock. The filesystem work and the
        fork+exec run WITHOUT the fleet lock — the router's
        ready_replicas() must not stall behind a respawn — and only the
        field publication at the end takes it."""
        rdir = self._replica_dir(r)
        rcfg = self.cfg.replace(
            train=dataclasses.replace(self.cfg.train, log_dir=rdir),
            serve=dataclasses.replace(
                self.cfg.serve, port=0,
                # the artifact store rides the config handoff resolved
                # to an absolute path: a replica's cwd must never decide
                # which store it boots from (scale-up spawns load
                # artifacts instead of compiling — ISSUE 16)
                artifacts_dir=(os.path.abspath(
                    self.cfg.serve.artifacts_dir)
                    if self.cfg.serve.artifacts_dir else ""),
                fleet=dataclasses.replace(self.fc, replicas=0,
                                          autoscale=False)))
        try:
            cfg_path = supervise.prepare_child_dir(rdir, rcfg)
            # a fake-executor replica must never probe the accelerator
            # tunnel (import chain is jax-free; force_cpu is the backstop)
            env = supervise.child_env(
                extra={REPLICA_ENV: str(r.idx)},
                force_cpu=self.cfg.serve.fake_exec_ms is not None)
            with open(os.path.join(rdir, "stderr.log"), "ab") as stderr:
                proc = supervise.spawn_child(
                    [sys.executable, "-m", "deepof_tpu", "serve",
                     "--config-json", cfg_path],
                    env, subprocess.PIPE, stderr, text=True)
        except OSError:
            # fork/fd exhaustion or an unwritable replica dir — most
            # likely under exactly the load that triggered a scale-up.
            # The claimed slot must not stay a zombie "spawning" entry
            # (the monitor skips that state forever): count it and
            # route it through the same backoff/breaker ladder a
            # spawn_failed death takes, so the monitor retries or opens
            # the breaker.
            with self._lock:
                r.last_exit = None
                r.last_reason = "spawn_failed"
                self._counters["spawn_failures"] += 1
                self._counters["evictions"] += 1
                self._schedule_backoff(r)
                self._log_event(r, "spawn failed (OSError); "
                                   "scheduling respawn")
            return
        with self._lock:
            if self._stopping:  # lost the race with close(): don't orphan
                supervise.kill_quietly(proc)  # served nothing: no drain owed
                proc.wait()
                r.state = "stopped"
                return
            r.proc = proc
            r.incarnation += 1
            r.state = "starting"
            r.port = None
            r.ready_m = None
            r.started_m = time.monotonic()
            self._counters["spawns"] += 1
        threading.Thread(target=self._read_stdout, args=(r, proc),
                         daemon=True,
                         name=f"fleet-stdout-{r.idx}").start()

    def _read_stdout(self, r: _Replica, proc: subprocess.Popen) -> None:
        """First stdout line is the replica's announce JSON (bound port);
        the rest is teed to <replica-dir>/stdout.log so the pipe never
        fills."""
        try:
            line = proc.stdout.readline()
            port = None
            try:
                serving = json.loads(line).get("serving", "")
                port = int(str(serving).rsplit(":", 1)[1].rstrip("/"))
            except (ValueError, IndexError, json.JSONDecodeError):
                pass
            with self._lock:
                if r.proc is proc:  # not already respawned
                    r.port = port
            self._wake.set()
            with open(os.path.join(self._replica_dir(r), "stdout.log"),
                      "a") as f:
                if line:
                    f.write(line)
                for line in proc.stdout:
                    f.write(line)
        except (OSError, ValueError):
            pass

    # ---------------------------------------------------------- monitor
    def _run(self) -> None:
        while True:
            self._wake.wait(timeout=max(float(self.fc.poll_s), 0.05))
            self._wake.clear()
            if self._stopping:
                return
            self._poll_all()

    def _poll_all(self) -> None:
        """One health pass over every slot. Three phases so the fleet
        lock — which the router's per-request ready_replicas() also
        takes — is never held across blocking I/O: (1) snapshot what
        needs probing, (2) run the TCP listen probes and heartbeat file
        reads UNLOCKED, (3) apply transitions under the lock (each
        _check re-validates state, so a transition that raced the probe
        just uses slightly stale health data — one period old at
        worst). Respawns _check claimed run after the lock is
        released."""
        now = time.monotonic()
        with self._lock:
            probe_ports = {r.idx: r.port for r in self._replicas
                           if r.state == "starting" and r.port is not None}
            hb_reads = [r for r in self._replicas if r.state == "ready"]
        listening = {idx: supervise.listening(self.host, port)
                     for idx, port in probe_ports.items()}
        heartbeats = {r.idx: self._read_heartbeat(r) for r in hb_reads}
        with self._lock:
            to_spawn = [r for r in self._replicas
                        if self._check(r, now, listening, heartbeats)]
        self._drain_incidents()
        for r in to_spawn:
            self._spawn(r)

    def _check(self, r: _Replica, now: float, listening: dict,
               heartbeats: dict) -> bool:
        """One replica's state-machine step (fleet lock held; probe/
        heartbeat results gathered unlocked by _poll_all). Returns True
        when the slot was claimed for a respawn the caller must perform
        (outside the lock)."""
        if r.state in ("stopped", "broken", "spawning", "retired",
                       "retiring"):
            # "retiring" is owned end to end by retire_one (autoscale
            # scale-down): already out of rotation, being drained —
            # the health machine must not evict or respawn it
            return False
        alive = r.proc is not None and r.proc.poll() is None
        if r.state == "starting":
            if not alive:
                self._on_death(r, "spawn_failed")
            elif r.port is not None and listening.get(r.idx):
                r.state = "ready"
                r.ready_m = now
            elif now - r.started_m > float(self.fc.spawn_timeout_s):
                self._evict(r, "spawn_timeout", now)
        elif r.state == "ready":
            if not alive:
                self._on_death(r, "crashed")
                return False
            if now - r.ready_m >= float(self.fc.healthy_after_s):
                r.fast_failures = 0  # proved healthy: crash-loop reset
            # shared pid-gated verdict (core/supervise.py): wedged is
            # the replica's own watchdog, stalled the supervisor-side
            # detector (requests in flight, nothing completing, before
            # the replica's watchdog — which needs 3 flushes — arms)
            verdict = supervise.heartbeat_verdict(
                heartbeats.get(r.idx), r.proc.pid, time.time(),
                self.fc.stale_after_s, self.fc.stall_after_s,
                stall_gate=_serve_in_flight)
            if verdict == "wedged":
                self._evict(r, "wedged", now)
            elif verdict == "stalled":
                self._evict(r, "stalled", now)
            elif verdict == "stale":
                self._evict(r, "stale", now)
            elif verdict in ("no_heartbeat", "foreign_pid"):
                # no current-incarnation file yet: grace from ready
                if now - (r.ready_m or now) > float(self.fc.stale_after_s):
                    self._evict(r, "stale", now)
        elif r.state == "terminating":
            if not alive:
                self._to_backoff(r, now)
            elif now >= r.term_deadline:
                supervise.kill_quietly(r.proc)  # SIGTERM grace expired
                self._counters["kill_escalations"] += 1
                r.term_deadline = now + 3600.0  # kill once; reap next poll
        elif r.state == "backoff":
            if now >= r.backoff_until:
                if supervise.breaker_open(r.fast_failures,
                                          self.fc.crash_loop_threshold):
                    r.state = "broken"
                    self._counters["broken"] += 1
                    self._queue_incident(
                        "fleet_broken", "critical",
                        {"replica": r.idx,
                         "fast_failures": r.fast_failures})
                    self._log_event(r, "circuit breaker OPEN: "
                                       f"{r.fast_failures} consecutive fast "
                                       "failures, not respawning")
                else:
                    r.state = "spawning"  # claim; caller spawns unlocked
                    self._counters["respawns"] += 1
                    return True
        return False

    def _read_heartbeat(self, r: _Replica) -> dict | None:
        return supervise.read_heartbeat(self._replica_dir(r))

    # --------------------------------------------------- state changes
    def _queue_incident(self, kind: str, severity: str,
                        trigger: dict) -> None:
        """Stage a trigger while the fleet lock is held; _poll_all /
        close capture it unlocked (the recorder writes a disk bundle)."""
        if self.incidents is not None:
            self._pending_incidents.append((kind, severity, trigger))

    def _drain_incidents(self) -> None:
        """Capture staged triggers (fleet lock NOT held), then sweep
        replica-recorded bundles into the run root so one `tail
        --fleet` / `incidents list` at the run dir sees the whole
        fleet — including bundles a SIGKILLed replica left behind."""
        rec = self.incidents
        if rec is None:
            return
        with self._lock:
            pending, self._pending_incidents = self._pending_incidents, []
        for kind, severity, trigger in pending:
            rec.record(kind, severity, trigger=trigger)
        rec.note_collected(incident.collect_from_children(self.dir))

    def _evict(self, r: _Replica, reason: str, now: float) -> None:
        """Sick replica out of rotation: SIGTERM (graceful drain),
        SIGKILL after term_grace_s (the terminating-state poll)."""
        self._queue_incident("fleet_eviction", "critical",
                            {"replica": r.idx, "reason": reason})
        self._counters["evictions"] += 1
        if reason in ("wedged", "stalled"):  # both are stuck dispatches
            self._counters["wedge_evictions"] += 1
        elif reason == "stale":
            self._counters["stale_evictions"] += 1
        elif reason in ("spawn_timeout", "spawn_failed"):
            self._counters["spawn_failures"] += 1
        r.last_reason = reason
        r.port = None  # router stops picking it immediately
        self._log_event(r, f"evicting ({reason}): SIGTERM, SIGKILL after "
                           f"{self.fc.term_grace_s}s")
        supervise.terminate_quietly(r.proc)
        r.state = "terminating"
        r.term_deadline = now + max(float(self.fc.term_grace_s), 0.0)

    def _on_death(self, r: _Replica, reason: str) -> None:
        """Process found dead on its own (kill -9, OOM, crash, clean
        exit): reap, count, schedule the respawn."""
        rc = None
        if r.proc is not None:
            rc = r.proc.wait()
        r.last_exit = rc
        clean = False
        if reason == "crashed" and rc == 0:
            reason = "exited"  # clean exit (external rolling restart)
            clean = True
            self._counters["clean_exits"] += 1
        elif reason == "spawn_failed":
            self._counters["spawn_failures"] += 1
            self._counters["evictions"] += 1
        else:
            self._counters["crashes"] += 1
            self._counters["evictions"] += 1
            self._queue_incident("fleet_replica_crash", "critical",
                                {"replica": r.idx, "rc": rc})
        r.last_reason = reason
        self._log_event(r, f"died ({reason}, rc={rc}); scheduling respawn")
        self._schedule_backoff(r, clean=clean)

    def _to_backoff(self, r: _Replica, now: float) -> None:
        rc = r.proc.wait() if r.proc is not None else None
        r.last_exit = rc
        self._schedule_backoff(r)

    def _schedule_backoff(self, r: _Replica, clean: bool = False) -> None:
        now = time.monotonic()
        fast = (r.ready_m is None
                or now - r.ready_m < float(self.fc.healthy_after_s))
        # breaker arithmetic shared with every supervisor
        # (core/supervise.py): only a FAST non-clean death counts — a
        # slow death resets, a clean rc=0 exit (rolling restart) never
        # counts either way
        r.fast_failures = supervise.crash_loop_update(r.fast_failures,
                                                      fast, clean=clean)
        delay = supervise.backoff_delay(self.fc.backoff_s,
                                        self.fc.backoff_max_s,
                                        r.fast_failures)
        r.state = "backoff"
        r.port = None
        r.backoff_until = now + delay
        r.proc = None

    def _log_event(self, r: _Replica, message: str) -> None:
        """One kind="warn" line per lifecycle event into the FLEET's
        metrics.jsonl (the replica's own logs live in its subdir)."""
        try:
            rec = {"kind": "warn", "step": 0, "time": time.time(),
                   "message": f"fleet replica-{r.idx} "
                              f"(incarnation {r.incarnation}): {message}"}
            with open(os.path.join(self.dir, "metrics.jsonl"), "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            pass

    # -------------------------------------------------- artifact GC
    def _live_fingerprints(self) -> set[str]:
        """Every fingerprint any replica's ledger recorded this run —
        the live lattice, read stdlib-only off the replica dirs'
        ledger.jsonl (the supervisor never lowers anything itself)."""
        from ..obs.ledger import load_ledger

        with self._lock:
            dirs = [self._replica_dir(r) for r in self._replicas]
        fps: set[str] = set()
        for d in dirs:
            try:
                rows = load_ledger(d)
            except OSError:
                continue
            for row in rows:
                fp = row.get("fingerprint")
                if isinstance(fp, str) and fp:
                    fps.add(fp)
        return fps

    def _artifacts_gc(self, trigger: str) -> None:
        """Bounded store GC on the retirement path (ROADMAP item 5b):
        graceful retirement / fleet close sweeps corrupt entries and
        orphaned tmp staging, plus (fleet.artifacts_gc_days > 0)
        unpinned entries older than the bound. The live lattice's
        fingerprints (every replica ledger's rows) are passed as roots
        and gc_store itself pins the index's targets, so the sweep can
        never collect an executable a replica is serving or the next
        boot would index-resolve. Best-effort: a GC failure never
        blocks retirement."""
        root = getattr(self.cfg.serve, "artifacts_dir", "")
        if not root:
            return
        try:
            from .artifacts import gc_store

            days = float(getattr(self.fc, "artifacts_gc_days", 0.0))
            rep = gc_store(os.path.abspath(os.path.expanduser(root)),
                           older_than_days=(days if days > 0 else None),
                           roots=self._live_fingerprints())
            if rep["removed"] or rep["tmp_removed"]:
                rec = {"kind": "warn", "step": 0, "time": time.time(),
                       "message": (f"fleet artifacts gc ({trigger}): "
                                   f"removed {len(rep['removed'])} "
                                   f"entries, {len(rep['tmp_removed'])} "
                                   f"tmp, kept {len(rep['kept'])}")}
                with open(os.path.join(self.dir, "metrics.jsonl"),
                          "a") as f:
                    f.write(json.dumps(rec) + "\n")
        except Exception:  # noqa: BLE001 - gc must not block retirement
            pass

    # ------------------------------------------------------- router API
    def ready_replicas(self) -> list:
        """Immutable (idx, port) snapshots of replicas safe to route to."""
        with self._lock:
            return [SimpleNamespace(idx=r.idx, port=r.port)
                    for r in self._replicas
                    if r.state == "ready" and r.port is not None]

    def note_failure(self, idx: int) -> None:
        """Router hint: a proxy attempt to this replica just failed —
        poll now instead of waiting out the period (a crashed process is
        discovered on the next monitor pass)."""
        self._wake.set()

    # ------------------------------------------------------ autoscaling
    def scale_up(self) -> int | None:
        """Add one replica slot and spawn it (the autoscaler's scale-up
        primitive). The new slot gets the next monotonic index — retired
        indices are never reused, so the router's per-index maps stay
        unambiguous across any number of scale events. Returns the new
        index, or None when the fleet is stopping."""
        with self._lock:
            if self._stopping:
                return None
            r = _Replica(len(self._replicas))
            r.state = "spawning"  # claimed; spawned below, unlocked
            self._replicas.append(r)
            self._active += 1
        self._spawn(r)
        if r.state != "backoff":  # spawn failure logs its own event
            self._log_event(r, "scale-up: new replica slot spawned")
        return r.idx

    def begin_retire(self) -> _Replica | None:
        """Claim the scale-down victim: the highest-index ready replica
        leaves rotation IMMEDIATELY (state "retiring" — ready_replicas()
        stops offering it, so the router admits nothing new there) but
        keeps running so in-flight requests finish. None when no replica
        is ready or the fleet is stopping."""
        with self._lock:
            if self._stopping:
                return None
            ready = [x for x in self._replicas if x.state == "ready"]
            if not ready:
                return None
            victim = max(ready, key=lambda x: x.idx)
            victim.state = "retiring"
            victim.last_reason = "scale_down"
            return victim

    def retire_one(self, router=None) -> int | None:
        """Graceful scale-down of ONE healthy replica — the eviction
        ladder's drain half applied to a replica that did nothing
        wrong: stop admission (begin_retire), wait out the router's
        in-flight count for the slot (bounded by drain_timeout_s),
        SIGTERM (the replica's own drain hook flushes any racing
        request and exits 0), reap with SIGKILL escalation after
        term_grace_s. Zero silent drops by construction: requests the
        router already proxied complete inside the replica's drain, and
        a request racing the SIGTERM fails transport and REPLAYS on a
        sibling (the existing failover contract). Counted as `retired`,
        never as an eviction — `tail`'s rc-4 contract stays about
        sickness. Blocks (the autoscaler's thread); returns the retired
        index or None."""
        r = self.begin_retire()
        if r is None:
            return None
        self._log_event(r, "scale-down: draining, then SIGTERM")
        deadline = time.monotonic() + max(float(self.fc.drain_timeout_s),
                                          0.0)
        while (router is not None and router.in_flight_of(r.idx) > 0
               and time.monotonic() < deadline):
            time.sleep(0.05)
        supervise.terminate_quietly(r.proc)
        rc = supervise.reap_within(
            r.proc, time.monotonic() + max(float(self.fc.term_grace_s), 0.1)
            + max(float(self.fc.drain_timeout_s), 0.0))
        with self._lock:
            r.last_exit = rc
            r.state = "retired"
            r.port = None
            r.proc = None
            self._active -= 1
            self._counters["retired"] += 1
            if rc not in (0, None):
                # SIGKILL escalation (wedged drain) or a crash that
                # raced the retirement — the capacity was leaving either
                # way, but the escalation stays visible
                self._counters["kill_escalations"] += 1
        self._log_event(r, f"retired (scale-down, rc={rc})")
        hook = self.on_retired
        if hook is not None:
            try:
                hook(r.idx)  # router ages out the slot's maps
            except Exception:  # noqa: BLE001 - aging must not kill scaling
                pass
        self._artifacts_gc("retire")
        return r.idx

    # ------------------------------------------------------------ stats
    def describe(self) -> list[dict]:
        """ACTIVE slots only, like stats()'s states map — retired slots
        would otherwise grow the /healthz payload by one permanent
        entry per scale-up for the life of an oscillating fleet; the
        `fleet_retired` counter accounts for them instead."""
        with self._lock:
            return [{"replica": r.idx, "state": r.state, "port": r.port,
                     "pid": r.proc.pid if r.proc is not None else None,
                     "incarnation": r.incarnation,
                     "fast_failures": r.fast_failures,
                     "last_exit": r.last_exit,
                     "last_reason": r.last_reason}
                    for r in self._replicas if r.state != "retired"]

    def stats(self) -> dict:
        """The supervisor's half of the fleet_* counter block. The
        states map covers ACTIVE slots only — retired slots leave it
        (bounded however many scale events a long-lived fleet sees) and
        are accounted by the `fleet_retired` counter instead."""
        with self._lock:
            c = dict(self._counters)
            states = {f"replica-{r.idx}": r.state for r in self._replicas
                      if r.state != "retired"}
            ready = sum(r.state == "ready" for r in self._replicas)
            size = self._active  # the one non-retired count (see size)
        return {
            "fleet_replicas": size,
            "fleet_ready": ready,
            "fleet_retired": c["retired"],
            "fleet_states": states,
            "fleet_evictions": c["evictions"],
            "fleet_crashes": c["crashes"],
            "fleet_clean_exits": c["clean_exits"],
            "fleet_wedge_evictions": c["wedge_evictions"],
            "fleet_stale_evictions": c["stale_evictions"],
            "fleet_spawn_failures": c["spawn_failures"],
            "fleet_respawns": c["respawns"],
            "fleet_broken": c["broken"],
            "fleet_kill_escalations": c["kill_escalations"],
        }

    # ------------------------------------------------------------ close
    def close(self) -> None:
        """Graceful fleet teardown: stop the monitor, SIGTERM every live
        replica (each drains in-flight work per serve/server.py's
        SIGTERM hook), SIGKILL stragglers after the drain+grace window.
        Idempotent."""
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
        self._wake.set()
        if self._monitor.ident is not None:  # started
            self._monitor.join(timeout=max(float(self.fc.poll_s), 0.05) + 5.0)
        with self._lock:
            live = [(r, r.proc) for r in self._replicas
                    if r.proc is not None and r.proc.poll() is None]
            for r, proc in live:
                supervise.terminate_quietly(proc)
        deadline = time.monotonic() + (float(self.fc.drain_timeout_s)
                                       + float(self.fc.term_grace_s))
        for r, proc in live:
            rc = supervise.reap_within(proc, deadline)
            with self._lock:
                r.last_exit = rc
        with self._lock:
            for r in self._replicas:
                if r.state != "retired":
                    r.state = "stopped"
                r.port = None
        # after every replica is down: the close-time store sweep (same
        # roots discipline as retire-time GC; replicas' ledgers are
        # complete now, so the pin set is the whole run's lattice)
        self._artifacts_gc("close")
        # final incident pass: staged triggers captured, every
        # replica-recorded bundle collected before the run dir is read
        self._drain_incidents()

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ------------------------------------------------------------ CLI entry


def run_fleet(cfg: ExperimentConfig, replicas: int | None = None) -> int:
    """`deepof_tpu serve --replicas N`: fleet + router + fleet heartbeat,
    serving until SIGINT/SIGTERM, then graceful drain (stop admission,
    flush in-flight, reap replicas). Blocks; returns the exit code."""
    from ..obs import trace as obs_trace

    # router-side span tracer: every admitted request's `route` span
    # (request_id-stamped) lands in <log_dir>/trace.json, the half
    # obs/aggregate.py joins with the replicas' serve_* spans into one
    # fleet timeline. obs_trace.installed() makes uninstall + flush
    # structural on EVERY exit, including a failed start() or an
    # EADDRINUSE bind below.
    tracer = None
    if cfg.obs.trace:
        tracer = obs_trace.Tracer(
            path=os.path.join(cfg.train.log_dir, "trace.json"),
            ring_size=cfg.obs.trace_ring, role="router")
    with obs_trace.installed(tracer):
        return _run_fleet(cfg, replicas)


def _run_fleet(cfg: ExperimentConfig, replicas: int | None) -> int:
    from ..obs.heartbeat import Heartbeat
    from .router import Router, build_router_server

    fleet = Fleet(cfg, replicas)
    # supervisor-process flight recorder (obs/incident.py): evictions /
    # broken replicas / crashes and the router's SLO verdict all record
    # into the RUN ROOT's incidents/, where the monitor also collects
    # each replica's own bundles. None when obs.incidents is off.
    fleet.incidents = incident.install(cfg, cfg.train.log_dir, "fleet")
    router = None
    httpd = None
    hb = None
    scaler = None
    degr = None
    # one teardown path for EVERY exit — replicas are detached
    # (start_new_session), so any escape without fleet.close() would
    # orphan serving processes: a partway-failed start() (EMFILE on
    # replica k), Ctrl-C during the spawns, or the router port already
    # bound raising EADDRINUSE after the replicas spawned
    try:
        fleet.start()
        try:
            fleet.wait_ready(
                min_ready=1,
                timeout_s=float(cfg.serve.fleet.spawn_timeout_s))
        except TimeoutError as e:
            print(f"fleet: no replica became ready: {e}", file=sys.stderr)
            return 1
        router = Router(cfg, fleet)
        router.incidents = fleet.incidents
        # scale-down aging: a retired slot leaves the router's
        # per-replica maps; its pinned sessions demote to session_lost
        fleet.on_retired = router.retire_slot
        httpd = build_router_server(cfg, router)
        host, port = httpd.server_address[:2]

        if cfg.serve.fleet.autoscale:
            from .autoscale import Autoscaler

            scaler = Autoscaler(cfg, fleet, router)
            # scale counters ride router.stats(): /healthz, /metrics,
            # the heartbeat sample and the shutdown record all see them
            router.autoscale_stats = scaler.stats
            scaler.start()

        if cfg.serve.degrade.enabled:
            from .degrade import DegradeController

            # the brownout plane (serve/degrade.py): degrades QUALITY
            # within ~a second while the autoscaler (above) adds
            # capacity over minutes — the two watch the same signals,
            # so the level walks back down when the capacity lands
            degr = DegradeController(cfg, fleet, router)
            degr.incidents = fleet.incidents  # L3 entry -> critical bundle
            router.degrade_stats = degr.stats
            router.degrade_level = degr.level
            degr.start()

        hb_ref: dict = {}

        def sample() -> dict:
            s = {**fleet.stats(), **router.stats()}
            # idle fleet is healthy, not wedged (same contract as serve)
            if s.get("fleet_in_flight", 0) <= 0 and "hb" in hb_ref:
                hb_ref["hb"].touch()
            return s

        sample_fn = sample
        if fleet.incidents is not None:
            # alert rules + heartbeat ring on the sample cadence; a
            # wedged SUPERVISOR is itself a critical incident
            sample_fn = fleet.incidents.wrap_sample(sample)
        hb = Heartbeat(os.path.join(cfg.train.log_dir, "heartbeat.json"),
                       period_s=cfg.obs.heartbeat_period_s,
                       watchdog_factor=cfg.obs.watchdog_factor,
                       watchdog_min_s=cfg.obs.watchdog_min_s,
                       sample=sample_fn,
                       on_wedge=(None if fleet.incidents is None else
                                 lambda dump: fleet.incidents.record(
                                     "watchdog_wedge", "critical",
                                     text_files={"stacks.txt": dump})),
                       devmem=False)  # supervisor: jax-free
        hb_ref["hb"] = hb
        router.beat_hook = hb.beat

        if threading.current_thread() is threading.main_thread():
            def _on_term(signum, frame):
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                threading.Thread(target=httpd.shutdown, daemon=True,
                                 name="fleet-drain").start()

            signal.signal(signal.SIGTERM, _on_term)

        print(json.dumps({"serving": f"http://{host}:{port}",
                          "mode": "fleet",
                          "replicas": fleet.size, "pid": os.getpid(),
                          "replica_ports": [s.port for s
                                            in fleet.ready_replicas()]}),
              flush=True)
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        return 0
    finally:
        if degr is not None:
            degr.close()  # no level transitions during teardown
        if scaler is not None:
            scaler.close()  # no scale events during teardown
        if router is not None:
            router.draining = True  # stop admission
        if httpd is not None:
            httpd.server_close()
            deadline = (time.monotonic()
                        + float(cfg.serve.fleet.drain_timeout_s))
            while (router.in_flight_total() > 0
                   and time.monotonic() < deadline):
                time.sleep(0.05)  # flush in-flight through the replicas
        fleet.close()  # then reap
        if router is not None:
            _log_fleet_summary(cfg, fleet, router)
        if hb is not None:
            hb.close()


def _log_fleet_summary(cfg: ExperimentConfig, fleet: Fleet,
                       router) -> None:
    """One kind="serve" record with the final fleet_* block so
    `deepof_tpu analyze`/`tail` surface fleet activity after exit.
    router.stats() already folds in the autoscaler's block through the
    autoscale_stats hook — one merge path, never two to drift."""
    try:
        os.makedirs(cfg.train.log_dir, exist_ok=True)
        rec = {"kind": "serve", "step": 0, "time": time.time(),
               **fleet.stats(), **router.stats()}
        with open(os.path.join(cfg.train.log_dir, "metrics.jsonl"),
                  "a") as f:
            f.write(json.dumps(rec, allow_nan=False) + "\n")
    except OSError:
        pass
