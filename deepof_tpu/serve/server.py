"""Zero-dependency flow-serving frontends over the InferenceEngine.

Two modes behind the `deepof_tpu serve` CLI verb:

  HTTP server — stdlib `http.server.ThreadingHTTPServer` (each request
  handled on its own thread, so concurrent clients genuinely coalesce
  in the engine's micro-batcher). JSON in, JSON/.flo/PNG out; no web
  framework, no new dependency. A serve heartbeat (obs/heartbeat.py)
  rewrites `<log_dir>/heartbeat.json` with the engine's serve_* block —
  queue depth, batch occupancy, p50/p99 latency, requests/s — and its
  watchdog dumps thread stacks if the batcher wedges; `deepof_tpu tail
  --log-dir` reads both.

  Offline mode — high-throughput directory/video inference: frame
  pairs are decoded+preprocessed concurrently by the existing
  `data/pipeline.py` worker pool (in-order delivery, serve.workers
  threads), staged through a `data/prefetch.py` Prefetcher so the
  submit loop never waits on decode, and streamed through the engine
  while `.flo`/png writes overlap the next batch's inference.

API:
  GET  /healthz           -> 200, the serve_* counter JSON
  POST /v1/flow           -> body {"prev": <b64 image>, "next": <b64>,
                             "format": "json"|"flo"|"png",
                             "precision": "f32"|"bf16"|"int8" (optional;
                             must be in serve.precisions, default = its
                             first entry)}
    json: {"flow_b64": <b64 raw float32 (H,W,2) little-endian>,
           "shape": [H, W, 2], "bucket": [h, w], "precision": tier,
           "latency_ms": ...}
    flo:  application/octet-stream Middlebury .flo bytes
    png:  image/png flow-color rendering
  POST /v1/flow/stream    -> body {"session": <id>, "frame": <b64 image>,
                             "format"/"precision" as above}: the
                             streaming video-session API
                             (serve/session.py) — ONE frame per request.
    202 {"primed": true, "session", "bucket", "frames"}: the frame
        opened (or re-opened) the session; no pair yet.
    200 the same payload as /v1/flow for the (previous, this) pair,
        plus {"session", "frame_index"} — one decode per frame — and,
        when serve.session.warm_start is on, {"warm": bool}: whether
        the step rode the refinement-only warm executable.
    410 {"error": "session_expired"}: the session was TTL-expired or
        LRU-evicted; resend the frame to re-prime.
  DELETE /v1/flow/stream/<id> -> 200 {"session", "deleted": true} |
                             404 {"error": "session_unknown"}
  Errors are structured: 4xx/5xx with a ServeError payload
  ({"error": code, "message": ...}); one bad request never affects its
  batchmates or the engine.
"""

from __future__ import annotations

import base64
import json
import os
import signal
import threading
import time

import numpy as np

# pre-3.11 concurrent.futures.TimeoutError is not the builtin
from concurrent.futures import TimeoutError as _FuturesTimeout

from ..core.config import ExperimentConfig
from ..io.flo import flo_bytes
from ..obs import incident
from ..obs import trace as obs_trace
from ..obs.export import PROM_CONTENT_TYPE, render_prometheus
from .engine import InferenceEngine, ServeError

#: Replica identity exported by the fleet supervisor (serve/fleet.py) to
#: each spawned serving subprocess — the index the replica-level fault
#: sites key on, and the tag in the replica's announce line.
REPLICA_ENV = "DEEPOF_TPU_REPLICA"


def replica_index() -> int:
    """This serving process's replica index (0 outside a fleet)."""
    try:
        return int(os.environ.get(REPLICA_ENV, "0"))
    except ValueError:
        return 0


def install_replica_faults(engine: InferenceEngine,
                           cfg: ExperimentConfig) -> None:
    """Arm the replica-level chaos sites (resilience/faults.py) inside
    THIS serving process: once the engine has completed
    `replica_fault_after` responses, a scheduled `replica_crash` SIGKILLs
    the process mid-load and a scheduled `replica_wedge` blocks the next
    dispatch forever (a hung device call — the serve watchdog's target).
    The site index is the replica index, so one fleet-wide fault config
    deterministically picks which replicas get sick. No-op (and zero
    overhead) when injection is disabled."""
    from ..resilience.faults import build_injector

    inj = build_injector(cfg.resilience.faults)
    if inj is None:
        return
    idx = replica_index()
    after = max(int(cfg.resilience.faults.replica_fault_after), 0)
    # replica_degrade is a PERSISTENT condition, not a one-shot event:
    # once armed, every later dispatch returns corrupted flow — silently
    # damaged weights as a steady state. Scheduled-ness is read once
    # (pure in config); the consume-once hit() only counts the arming.
    degrade = inj.scheduled("replica_degrade", idx)
    inner = engine._forward

    def forward(key, x, *args, **kw):
        # signature-transparent: the engine calls _forward(key, x) on
        # the cold path and _forward(key, x, prior=...) on the warm one
        with engine._stats_lock:
            done = engine._responses
        if done >= after:
            if inj.hit("replica_crash", idx):
                os.kill(os.getpid(), signal.SIGKILL)
            if inj.hit("replica_wedge", idx):
                threading.Event().wait()  # never returns: wedged dispatch
        out = inner(key, x, *args, **kw)
        if degrade and done >= after:
            inj.hit("replica_degrade", idx)  # count the arming, once
            # a large constant flow offset: latency/SLO axes stay
            # perfectly healthy, only the label-free quality proxies
            # (obs/quality.py) can see it — the drift-verdict target
            out = np.asarray(out) + np.float32(25.0)
        return out

    engine._forward = forward

_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".ppm", ".bmp")
_VIDEO_EXTS = (".mp4", ".avi", ".mov", ".mkv", ".webm")


# --------------------------------------------------------------- HTTP


def _decode_b64_image(b64: str, field: str) -> np.ndarray:
    import cv2

    try:
        raw = base64.b64decode(b64, validate=True)
    except Exception as e:  # noqa: BLE001 - client error, structured reply
        raise ServeError("bad_request", f"{field}: invalid base64: {e}")
    img = cv2.imdecode(np.frombuffer(raw, np.uint8), cv2.IMREAD_COLOR)
    if img is None:
        raise ServeError("bad_input", f"{field}: undecodable image bytes")
    return img


def build_server(cfg: ExperimentConfig, engine: InferenceEngine):
    """A ThreadingHTTPServer bound to cfg.serve.host:port serving the
    engine. Returned unstarted (call serve_forever / run in a thread) so
    tests drive it on an ephemeral port."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    timeout_s = max(float(cfg.serve.request_timeout_s), 0.1)

    class Server(ThreadingHTTPServer):
        daemon_threads = True  # a stuck client never blocks shutdown

        def handle_error(self, request, client_address):
            # client disconnects (reset/broken pipe mid-response) are
            # routine on a public endpoint, not stack-trace material;
            # everything else keeps the default diagnostic dump
            import sys

            exc = sys.exc_info()[1]
            if isinstance(exc, (ConnectionError, TimeoutError)):
                return
            super().handle_error(request, client_address)

    class Handler(BaseHTTPRequestHandler):
        # the engine is shared; per-request state stays on the stack
        protocol_version = "HTTP/1.1"  # keep-alive (Content-Length always set)

        def log_message(self, fmt, *args):  # quiet: obs owns visibility
            pass

        def _reply(self, status: int, body: bytes,
                   ctype: str = "application/json") -> None:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, status: int, payload: dict) -> None:
            self._reply(status, json.dumps(payload).encode())

        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
            if self.path in ("/healthz", "/stats"):
                self._reply_json(200, engine.stats())
            elif self.path == "/metrics":
                # Prometheus text exposition of the live serve_* block
                # (counters, fixed-bucket latency histogram, SLO state)
                self._reply(200, render_prometheus(engine.stats()).encode(),
                            PROM_CONTENT_TYPE)
            else:
                self._reply_json(404, {"error": "not_found",
                                       "message": self.path})

        def do_POST(self):  # noqa: N802
            stream = self.path in ("/v1/flow/stream", "/flow/stream")
            if not stream and self.path not in ("/v1/flow", "/flow"):
                self._reply_json(404, {"error": "not_found",
                                       "message": self.path})
                return
            # the router's correlation id: stamped on this request's
            # engine spans and echoed back, so the merged fleet trace
            # chains router -> replica for this request
            request_id = self.headers.get("X-Request-Id")
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length) or b"{}")
                fmt = req.get("format", "json")
                if fmt not in ("json", "flo", "png"):
                    raise ServeError("bad_request",
                                     f"format must be json|flo|png, "
                                     f"got {fmt!r}")
                precision = req.get("precision")  # None = default tier
                # the propagated deadline (router admission re-stamps
                # the REMAINING budget in X-Deadline-Ms; direct callers
                # may also set body "deadline_ms"): strict parse — a
                # malformed budget is a client error, not "no deadline"
                raw_deadline = self.headers.get("X-Deadline-Ms",
                                                req.get("deadline_ms"))
                deadline_s = None
                if raw_deadline is not None:
                    try:
                        deadline_s = float(raw_deadline) / 1e3
                    except (TypeError, ValueError):
                        raise ServeError(
                            "bad_request",
                            f"deadline_ms must be a number, "
                            f"got {raw_deadline!r}")
                # the live brownout level the router folded in
                # (serve/degrade.py); lenient — a replica hit directly
                # simply serves at L0
                try:
                    degrade_level = int(
                        self.headers.get("X-Degrade-Level", 0))
                except (TypeError, ValueError):
                    degrade_level = 0
                if stream:
                    sid = req.get("session")
                    if not isinstance(sid, str) or not sid:
                        raise ServeError(
                            "bad_request",
                            "stream body needs a non-empty string "
                            "\"session\" id")
                    if "/" in sid:
                        # ids ride in the DELETE URL path: a slash would
                        # make the id unaddressable (and router/replica
                        # would parse it differently)
                        raise ServeError(
                            "bad_request",
                            f"session id {sid!r} must not contain '/'")
                    frame = _decode_b64_image(req.get("frame", ""), "frame")
                else:
                    prev = _decode_b64_image(req.get("prev", ""), "prev")
                    nxt = _decode_b64_image(req.get("next", ""), "next")
            except ServeError as e:
                self._reply_json(400, e.payload())
                return
            except Exception as e:  # noqa: BLE001 - malformed body
                self._reply_json(400, {"error": "bad_request",
                                       "message": f"{type(e).__name__}: {e}"})
                return
            if stream:
                fut = engine.submit_next(sid, frame, precision=precision,
                                         request_id=request_id,
                                         deadline_s=deadline_s,
                                         degrade_level=degrade_level)
            else:
                fut = engine.submit(prev, nxt, precision=precision,
                                    request_id=request_id,
                                    deadline_s=deadline_s,
                                    degrade_level=degrade_level)
            # wait on min(blanket timeout, the caller's own budget): a
            # doomed request must release this handler thread (and the
            # caller) when ITS deadline lapses, not at the blanket cap
            wait_s = timeout_s
            if deadline_s is not None:
                wait_s = min(timeout_s, max(deadline_s, 0.0))
            try:
                res = fut.result(timeout=wait_s)
            except ServeError as e:
                status = (400 if e.code in ("bad_input", "bad_request")
                          else 410 if e.code == "session_expired"
                          else 504 if e.code == "deadline_exceeded"
                          else 500)
                self._reply_json(status, e.payload())
                return
            except _FuturesTimeout:
                if wait_s < timeout_s:
                    # the caller's budget lapsed first — same structured
                    # verdict the engine's own gates emit, ledgered so
                    # the deadline story is complete across stages
                    engine.note_wait_expired()
                    self._reply_json(504, {
                        "error": "deadline_exceeded",
                        "message": f"deadline lapsed after {wait_s}s "
                                   f"waiting for dispatch",
                        **({"request_id": request_id}
                           if request_id is not None else {})})
                    return
                self._reply_json(504, {"error": "timeout",
                                       "message": f"no response within "
                                                  f"{timeout_s}s"})
                return
            if stream and res.get("primed"):
                # 202: accepted, session primed — no pair to answer yet
                self._reply_json(202, {
                    "primed": True, "session": res["session"],
                    "bucket": list(res["bucket"]),
                    "native_hw": list(res["native_hw"]),
                    "frames": res["frames"],
                    "request_id": res["request_id"]})
                return
            flow = res["flow"]
            if fmt == "flo":
                self._reply(200, flo_bytes(flow), "application/octet-stream")
            elif fmt == "png":
                import cv2

                from ..utils.flowviz import flow_to_color

                ok, png = cv2.imencode(".png", flow_to_color(flow))
                if not ok:
                    self._reply_json(500, {"error": "encode_failed",
                                           "message": "png encode failed"})
                    return
                self._reply(200, png.tobytes(), "image/png")
            else:
                payload = {
                    "shape": list(flow.shape),
                    "bucket": list(res["bucket"]),
                    "precision": res["precision"],
                    "native_hw": list(res["native_hw"]),
                    "latency_ms": round(res["latency_s"] * 1e3, 3),
                    "request_id": res["request_id"],
                    "flow_b64": base64.b64encode(
                        np.ascontiguousarray(flow, "<f4").tobytes()).decode(),
                }
                if stream:
                    payload["session"] = res["session"]
                    payload["frame_index"] = res["frame_index"]
                    if "warm" in res:
                        # temporal warm-start provenance (present only
                        # when serve.session.warm_start is on): whether
                        # this step rode the refinement-only executable
                        payload["warm"] = res["warm"]
                self._reply_json(200, payload)

        def do_DELETE(self):  # noqa: N802
            for prefix in ("/v1/flow/stream/", "/flow/stream/"):
                if self.path.startswith(prefix):
                    sid = self.path[len(prefix):]
                    break
            else:
                self._reply_json(404, {"error": "not_found",
                                       "message": self.path})
                return
            if engine.sessions.delete(sid):
                self._reply_json(200, {"session": sid, "deleted": True})
            else:
                self._reply_json(404, {"error": "session_unknown",
                                       "session": sid})

    return Server((cfg.serve.host, cfg.serve.port), Handler)


def drain_engine(engine: InferenceEngine, timeout_s: float) -> bool:
    """Wait (bounded) until every submitted request has resolved to a
    response or an error — the flush-in-flight half of graceful drain
    (admission already stopped: the listener is closed). True when the
    engine drained, False on timeout (a wedged batcher: the caller's
    escalation — fleet SIGKILL — takes it from there)."""
    deadline = time.monotonic() + max(float(timeout_s), 0.0)
    while True:
        s = engine.stats()
        if s["serve_requests"] <= s["serve_responses"] + s["serve_errors"]:
            return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.05)


def run_server(cfg: ExperimentConfig, engine: InferenceEngine | None = None,
               model_params=None) -> int:
    """`deepof_tpu serve` (HTTP mode): engine + heartbeat + serve_forever
    until SIGINT/SIGTERM. Blocks; returns the exit code.

    SIGTERM is the graceful-drain hook (the fleet supervisor's rolling
    restart / eviction path): stop admission (shut the listener down),
    flush in-flight requests through the engine, then exit 0. A second
    SIGTERM — or the supervisor's SIGKILL escalation — remains the
    hard stop for a wedged drain."""
    from ..obs.heartbeat import Heartbeat

    # span tracer BEFORE the engine so the warm() compile spans land on
    # the timeline; (role, index) stamp the trace for obs/aggregate.py's
    # fleet merge. obs_trace.installed() makes the teardown structural:
    # uninstall + flush on ANY exit — clean drain, ^C, or a startup
    # failure anywhere below (restore/compile raising, the bind failing
    # with EADDRINUSE) — so the spans leading into a failure are never
    # lost and the process-global tracer never outlives this serve run.
    # (The watchdog additionally flushes mid-run on a wedge, so the
    # timeline into a stall survives even a SIGKILL eviction.)
    tracer = None
    if cfg.obs.trace:
        tracer = obs_trace.Tracer(
            path=os.path.join(cfg.train.log_dir, "trace.json"),
            ring_size=cfg.obs.trace_ring,
            role="replica" if os.environ.get(REPLICA_ENV) else "serve",
            index=replica_index())
    own_engine = engine is None
    with obs_trace.installed(tracer):
        if own_engine:
            engine = InferenceEngine(cfg, model_params=model_params)
        install_replica_faults(engine, cfg)
        # incident plane (obs/incident.py): the replica's flight
        # recorder. The engine raises its own triggers (SLO/quality
        # exhaustion, deep-verify demote) through this handle; the
        # watchdog wedge is wired below; None (obs.incidents off) keeps
        # every site a structural no-op.
        incidents = incident.install(
            cfg, cfg.train.log_dir,
            "replica" if os.environ.get(REPLICA_ENV) else "serve")
        engine.incidents = incidents
        warm = engine.warm()

        # serve heartbeat: flushes are the "steps"; with NO work in
        # flight (every submitted request answered — not merely an empty
        # queue, which would also mask a dispatch hung inside the device
        # call) the clock is touch()ed so an idle endpoint is never
        # declared wedged — only pending-but-stalled requests are
        hb_ref: dict = {}

        def sample() -> dict:
            s = engine.heartbeat_sample()
            in_flight = (s.get("serve_requests", 0)
                         - s.get("serve_responses", 0)
                         - s.get("serve_errors", 0))
            if in_flight <= 0 and "hb" in hb_ref:
                hb_ref["hb"].touch()
            return s

        if incidents is not None:
            # alert rules + heartbeat ring ride the sample cadence; the
            # watchdog wedge becomes a critical incident carrying the
            # firing-time stack dump
            sample = incidents.wrap_sample(sample)
        hb = Heartbeat(os.path.join(cfg.train.log_dir, "heartbeat.json"),
                       period_s=cfg.obs.heartbeat_period_s,
                       watchdog_factor=cfg.obs.watchdog_factor,
                       watchdog_min_s=cfg.obs.watchdog_min_s,
                       sample=sample,
                       on_wedge=(None if incidents is None else
                                 lambda dump: incidents.record(
                                     "watchdog_wedge", "critical",
                                     text_files={"stacks.txt": dump})),
                       # a fake-executor replica stays jax-free end to end
                       devmem=cfg.serve.fake_exec_ms is None)
        hb_ref["hb"] = hb
        engine.flush_hook = hb.beat
        try:
            httpd = build_server(cfg, engine)
        except BaseException:
            hb.close()  # bind failure: the heartbeat thread must not leak
            raise
        host, port = httpd.server_address[:2]

        # graceful drain on SIGTERM (main thread only — tests drive
        # build_server directly): first signal stops admission; the
        # finally block below flushes in-flight work before exiting.
        # Restoring the default action afterwards lets a second SIGTERM
        # kill a wedged drain outright (the train loop's two-step
        # convention).
        if threading.current_thread() is threading.main_thread():
            def _on_term(signum, frame):
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                # shutdown() blocks until serve_forever returns; hop
                # threads so the handler itself never deadlocks the
                # serve loop
                threading.Thread(target=httpd.shutdown, daemon=True,
                                 name="serve-drain").start()

            signal.signal(signal.SIGTERM, _on_term)

        print(json.dumps({"serving": f"http://{host}:{port}",
                          "pid": os.getpid(),
                          "replica": replica_index(),
                          "buckets": [list(b) for b in engine.buckets],
                          "precisions": list(engine.tiers),
                          "max_batch": engine.max_batch,
                          "warm": warm.get("cache")}), flush=True)
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.server_close()  # admission stopped: no new connections
            # flush in-flight: handler threads are still parked on
            # futures; give the batcher a bounded window to resolve them
            drain_engine(engine, cfg.serve.fleet.drain_timeout_s)
            if own_engine:
                engine.close()
            _log_serve_summary(cfg, engine)
            hb.close()
    return 0


def _log_serve_summary(cfg: ExperimentConfig, engine: InferenceEngine) -> None:
    """Append one kind="serve" record (the final serve_* counters) to the
    run's metrics.jsonl so `deepof_tpu analyze` surfaces serving
    activity alongside training history."""
    try:
        os.makedirs(cfg.train.log_dir, exist_ok=True)
        rec = {"kind": "serve", "step": 0, "time": time.time()}
        rec.update(engine.stats())
        with open(os.path.join(cfg.train.log_dir, "metrics.jsonl"), "a") as f:
            f.write(json.dumps(rec, allow_nan=False) + "\n")
    except OSError:
        pass  # a read-only log tree must not fail the serve exit path


# ------------------------------------------------------------- offline


def _enumerate_pairs(input_path: str) -> list[tuple[str, str]]:
    """Consecutive frame pairs from a directory of images (sorted) —
    the directory half of offline mode."""
    names = sorted(n for n in os.listdir(input_path)
                   if n.lower().endswith(_IMAGE_EXTS))
    paths = [os.path.join(input_path, n) for n in names]
    if len(paths) < 2:
        raise SystemExit(f"offline serve: need >= 2 frames in {input_path!r}, "
                         f"found {len(paths)}")
    return list(zip(paths, paths[1:]))


def _video_rows(path: str, engine: InferenceEngine):
    """Decoded consecutive-pair rows from a video file. Decode is
    inherently sequential (cv2.VideoCapture), so rows stream from the
    caller's thread; the engine still batches dispatches behind it."""
    import cv2

    from .buckets import pick_bucket, prepare_pair

    cap = cv2.VideoCapture(path)
    if not cap.isOpened():
        raise SystemExit(f"offline serve: cannot open video {path!r}")
    try:
        ok, prev = cap.read()
        idx = 0
        while ok:
            ok, nxt = cap.read()
            if not ok:
                break
            native_hw = (prev.shape[0], prev.shape[1])
            bucket = pick_bucket(native_hw, engine.buckets)
            yield idx, prepare_pair(prev, nxt, bucket, engine.mean), \
                bucket, native_hw
            prev = nxt
            idx += 1
    finally:
        cap.release()


def run_offline(cfg: ExperimentConfig, input_path: str, out_dir: str,
                write_png: bool = True, engine: InferenceEngine | None = None,
                model_params=None) -> dict:
    """High-throughput offline inference over a frame directory or video
    file: decode/preprocess on the data/pipeline.py worker pool
    (directories), stage through prefetch.py, batch through the engine,
    overlap output writes with in-flight inference. Returns the summary
    dict the CLI prints."""
    from collections import deque

    from ..predict import write_outputs

    os.makedirs(out_dir, exist_ok=True)
    own_engine = engine is None
    if own_engine:
        engine = InferenceEngine(cfg, model_params=model_params)
    t0 = time.perf_counter()
    written: list[str] = []
    n_pairs = n_err = 0
    try:
        engine.warm()
        if os.path.isfile(input_path) \
                and input_path.lower().endswith(_VIDEO_EXTS):
            submissions = ((f"frame{idx:06d}",
                            engine.submit_prepared(x, bucket, native_hw))
                           for idx, x, bucket, native_hw
                           in _video_rows(input_path, engine))
        else:
            submissions = _submit_directory(
                cfg, engine, _enumerate_pairs(input_path))
        # bounded outstanding-futures window (resolved futures hold full
        # native-resolution flows): writes overlap in-flight inference,
        # host memory stays O(window) however long the sweep is
        window = max(4 * engine.max_batch, 16)
        buf: deque = deque()

        def drain_one() -> None:
            nonlocal n_err
            stem, fut = buf.popleft()
            try:
                flow = fut.result()["flow"]
            except ServeError as e:
                n_err += 1
                print(json.dumps({"request": stem, **e.payload()}),
                      flush=True)
                return
            written.extend(write_outputs(out_dir, stem, flow,
                                         write_png=write_png))

        try:
            for sub in submissions:
                n_pairs += 1
                buf.append(sub)
                if len(buf) >= window:
                    drain_one()
            while buf:
                drain_one()
        finally:
            close = getattr(submissions, "close", None)
            if close is not None:  # release the generator's pipeline
                close()
    finally:
        if own_engine:
            engine.close()
        _log_serve_summary(cfg, engine)
    wall = time.perf_counter() - t0
    stats = engine.stats()
    return {"pairs": n_pairs, "errors": n_err, "written": len(written),
            "wall_s": round(wall, 3),
            "pairs_per_s": round((n_pairs - n_err) / wall, 3)
            if wall > 0 else None,
            **{k: stats[k] for k in ("serve_batches", "serve_occupancy_mean",
                                     "serve_latency_p50_ms",
                                     "serve_latency_p99_ms")}}


def _submit_directory(cfg: ExperimentConfig, engine: InferenceEngine,
                      pairs: list[tuple[str, str]]):
    """Yield (stem, future) for a directory's pairs through the parallel
    host input path: `data/pipeline.py` workers decode+preprocess rows
    out-of-order (delivered in order), a `data/prefetch.py` Prefetcher
    keeps a bounded ready-queue ahead of the submit loop, and the engine
    batches behind both. A pair whose decode fails becomes a per-index
    structured error row — one corrupt frame fails one request, never
    the sweep. Lazy by design: the consumer's bounded window, not the
    pair count, bounds in-flight memory."""
    from ..data.datasets import _imread_bgr
    from ..data.pipeline import InputPipeline
    from ..data.prefetch import Prefetcher
    from ..predict import output_stem
    from .buckets import pick_bucket, prepare_pair

    def make_row(i: int) -> dict:
        # the pipeline's index stream is unbounded (workers run ahead of
        # the delivery cursor); indices past the work list are cheap
        # padding rows that are prefetched but never consumed
        if i >= len(pairs):
            return {"pad": True}
        src, tgt = pairs[i]
        try:
            prev = _imread_bgr(src)
            nxt = _imread_bgr(tgt)
            native_hw = (prev.shape[0], prev.shape[1])
            bucket = pick_bucket(native_hw, engine.buckets)
            return {"x": prepare_pair(prev, nxt, bucket, engine.mean),
                    "bucket": bucket, "native_hw": native_hw}
        except Exception as e:  # noqa: BLE001 - contained per-index
            return {"error": f"{type(e).__name__}: {e}"}

    workers = max(int(cfg.serve.workers), 0)
    pipeline = InputPipeline(make_row, num_workers=workers,
                             retries=cfg.resilience.pipeline_retries)
    it = iter(pipeline)
    prefetch = Prefetcher(lambda: next(it), depth=max(cfg.data.prefetch, 1))
    try:
        for i, (src, _) in enumerate(pairs):
            row = prefetch.get()
            stem = output_stem(src, i, True)
            if "error" in row:
                yield (stem, _failed_future(
                    ServeError("bad_input", row["error"], i)))
                continue
            yield (stem, engine.submit_prepared(
                row["x"], row["bucket"], row["native_hw"]))
    finally:
        prefetch.close()
        pipeline.close()


def _failed_future(err: ServeError):
    from concurrent.futures import Future

    fut: Future = Future()
    fut.set_exception(err)
    return fut
