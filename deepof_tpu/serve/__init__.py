"""Inference serving subsystem (DESIGN.md "Serving").

`engine.InferenceEngine` — restored+verified params behind a dynamic
micro-batcher (request queue -> coalesced padded batches -> one AOT
executable per shape bucket -> per-request futures).
`buckets` — the shape-bucket ladder mapping arbitrary native inputs to
a fixed, warmable executable set.
`server` — the stdlib-only HTTP frontend and the offline
directory/video high-throughput mode (`deepof_tpu serve`).

Importing this package pulls in numpy only — jax and cv2 load lazily
inside the engine paths that need them (the CLI imports this package
before deciding whether it needs a backend at all).
"""

from .engine import InferenceEngine, ServeError  # noqa: F401
from .buckets import pick_bucket, resolve_buckets  # noqa: F401
