"""Streaming video sessions: the stateful half of the serving stack.

Every request to `/v1/flow` ships and decodes TWO frames, so a client
walking a video pays 2x decode/preprocess/upload — the dominant
real-world workload pays double host work for no reason: frame t was
already decoded when it served as the "next" of pair (t-1, t). This
module keeps, per session id, the last frame's decoded +
bucket-preprocessed half-row (serve/buckets.py prepare_frame), so
`engine.submit_next(session, frame)` forms the (prev, next) pair
server-side from ONE new frame — one decode and one preprocess per
frame, halving host work for video — plus, since r11, the last step's
RESOLVED FLOW (raw dispatch output at the bucket's finest-head grid,
stored verbatim): the temporal warm-start prior
(FlowNet 2.0 lineage, PAPERS.md) the engine's refinement-only
executable consumes when `serve.session.warm_start` is on. The prior
is engine-written (set_flow, guarded on liveness + bucket match) and
dropped on EVERY re-prime/rebucket, so a warm step can never refine
against a stale or mis-sized flow.

Contract decisions that matter:

  - Parity by construction. prepare_pair == concat(prepare_frame x 2)
    (per-frame independent preprocess), so a streamed step's network
    input is BITWISE the pair the client would have submitted pairwise —
    pinned in tests/test_session.py. The cache holds preprocessed
    float32 half-rows (~H*W*12 bytes each), never raw frames.
  - Bounded, never silent. The store is an LRU capped at
    `serve.session.max_sessions` with an idle TTL
    (`serve.session.ttl_s`) enforced by a sweeper thread AND exactly on
    access. Every eviction leaves a tombstone: the session's next use is
    a structured `session_expired` error the client re-primes from — a
    session can end, but it cannot vanish silently.
  - Sessions are engine-local state; requests stay pure at the fleet
    level. The router (serve/router.py) pins a session to one replica
    (sticky map) so its cached frame is where its frames land; replica
    loss demotes to a structured `session_lost` reply — there is no
    cross-replica state migration, the client re-primes.
  - A frame ADVANCES the session at submit time, before its flow
    resolves: frame t+1 pairs with frame t whether or not pair (t-1, t)
    dispatched cleanly, exactly like the pairwise walk would.
  - A mid-session resolution change (a new frame mapping to a different
    bucket) re-primes in place: the cached half-row is at the old bucket
    resolution, so the pair cannot be formed — the caller gets a fresh
    `primed` reply (counted, visible) instead of a resize surprise.

Observability: the engine surfaces the `serve_sessions_*` counter block
(active/created/expired/evicted/resumed/deleted/rebucketed, frames,
steps, decode savings) and a per-session-frame latency histogram
(`serve_session_latency_hist`, obs/export.py fixed buckets — merges
exactly at the router) through stats()/heartbeat/metrics/analyze/tail;
`session_prime`/`session_step` trace spans carry the session id next to
X-Request-Id.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

import numpy as np

#: Tombstones retained after eviction/expiry so the NEXT use of a dead
#: session id is a structured `session_expired`, not an accidental fresh
#: prime. Bounded independently of max_sessions (tombstones are a few
#: bytes each; the bound only exists so the map cannot grow forever).
TOMBSTONE_CAP = 4096


class _Session:
    __slots__ = ("sid", "row", "bucket", "native_hw", "tier", "frames",
                 "last_m", "flow", "epoch")

    def __init__(self, sid, row, bucket, native_hw, tier, now):
        self.sid = sid
        self.row = row              # prepare_frame half-row (H, W, 3) f32
        self.bucket = bucket
        self.native_hw = native_hw
        self.tier = tier            # default precision for this session's steps
        self.frames = 1
        self.last_m = now
        # the newest resolved flow for this session — the raw dispatch
        # output at the bucket's finest-head grid (h, w, 2) f32, stored
        # VERBATIM — the temporal warm-start prior (set by engine
        # set_flow after a step's dispatch resolves; None until the
        # first step's flow lands). Dropped to None on every
        # re-prime/rebucket: a stale or wrong-resolution flow can never
        # leak into a refinement input.
        self.flow = None
        # prime-generation id (store-wide monotonic, assigned by the
        # store at every prime/re-prime/rebucket): the set_flow guard's
        # identity token. A dispatch captures the epoch inside advance()
        # and its writeback is dropped unless the session is STILL that
        # generation — a tombstone-resume at the same sid + bucket
        # cannot accept a pre-eviction flow.
        self.epoch = 0


class SessionExpired(KeyError):
    """The session id names a session that was evicted (LRU pressure) or
    expired (idle TTL) — the structured `session_expired` trigger. The
    tombstone survives this raise, so the client's re-prime of the same
    id is counted as `resumed`."""

    def __init__(self, sid: str, reason: str):
        super().__init__(sid)
        self.sid = sid
        self.reason = reason  # "expired" (TTL) | "evicted" (LRU)


class SessionStore:
    """Bounded, thread-safe session cache (see module docstring).

    max_sessions / ttl_s / sweep_s: ServeConfig.session knobs (the
    engine passes cfg.serve.session through). A sweeper thread runs only
    when both ttl_s and sweep_s are > 0; TTL is additionally enforced
    exactly on access, so correctness never depends on sweep cadence.
    """

    def __init__(self, max_sessions: int = 256, ttl_s: float = 120.0,
                 sweep_s: float = 5.0):
        self.max_sessions = max(int(max_sessions), 1)
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._sessions: OrderedDict[str, _Session] = OrderedDict()
        self._tombstones: OrderedDict[str, str] = OrderedDict()
        # --- counters (read via stats(); guarded by _lock) ---
        self._created = 0
        self._resumed = 0     # re-primes of a tombstoned (dead) id
        self._expired = 0     # TTL
        self._evicted = 0     # LRU pressure
        self._deleted = 0     # explicit DELETE
        self._rebucketed = 0  # mid-session resolution change re-primes
        self._frames = 0      # every accepted frame (primes + steps)
        self._steps = 0       # frames that formed a pair from the cache
        self._epoch = 0       # prime-generation counter (_Session.epoch)
        self._stop = threading.Event()
        self._sweeper = None
        if self.ttl_s > 0 and float(sweep_s) > 0:
            self._sweeper = threading.Thread(
                target=self._sweep_loop, args=(float(sweep_s),),
                daemon=True, name="serve-session-sweeper")
            self._sweeper.start()

    # ------------------------------------------------------------- core
    def _expire_locked(self, sid: str, reason: str) -> None:
        self._sessions.pop(sid, None)
        self._tombstones[sid] = reason
        self._tombstones.move_to_end(sid)
        while len(self._tombstones) > TOMBSTONE_CAP:
            self._tombstones.popitem(last=False)
        if reason == "expired":
            self._expired += 1
        else:
            self._evicted += 1

    def _fresh_locked(self, s: _Session, now: float) -> bool:
        return self.ttl_s <= 0 or now - s.last_m <= self.ttl_s

    def contains(self, sid: str) -> bool:
        """Live-and-fresh probe (no LRU touch) — the span-naming hint;
        advance() is the authority."""
        now = time.monotonic()
        with self._lock:
            s = self._sessions.get(sid)
            return s is not None and self._fresh_locked(s, now)

    def advance(self, sid: str, row: np.ndarray, bucket: tuple[int, int],
                native_hw: tuple[int, int], tier: str):
        """Accept one frame for `sid`, atomically.

        Returns ("primed", session) when the frame opens (or re-opens)
        the session — no pair to dispatch — or ("step", prev_row,
        prior_flow, epoch, session) with the PREVIOUS frame's half-row
        (the caller forms the (prev, next) network input by channel
        concat), the session's cached flow (None until a step's flow
        has landed via set_flow — the temporal warm-start prior; a None
        prior means the caller dispatches cold), and the session's
        prime-generation epoch (the token set_flow requires). The
        stored frame advances to `row` either way; a RE-PRIME (fresh,
        resumed, or rebucketed) always drops the cached flow. Raises
        SessionExpired when `sid` is tombstoned (evicted/TTL-expired):
        the structured `session_expired` path — the client re-primes,
        and that re-prime clears the tombstone and counts as `resumed`.
        """
        now = time.monotonic()
        with self._lock:
            s = self._sessions.get(sid)
            if s is not None and not self._fresh_locked(s, now):
                # lazy TTL: exact even if the sweeper has not run yet;
                # the rejected frame is NOT counted — the client's
                # re-prime of this id is, as `resumed`
                self._expire_locked(sid, "expired")
                self._tombstones[sid] = "notified"  # this raise notifies
                raise SessionExpired(sid, "expired")
            if s is None:
                reason = self._tombstones.get(sid)
                if reason is not None and reason != "notified":
                    # first use of a dead id: the structured error the
                    # client re-primes from (the RETRY is the resume)
                    self._tombstones[sid] = "notified"
                    self._tombstones.move_to_end(sid)
                    raise SessionExpired(sid, reason)
            self._frames += 1
            if s is None:
                if self._tombstones.pop(sid, None) is not None:
                    self._resumed += 1
                else:
                    self._created += 1
                s = _Session(sid, row, bucket, native_hw, tier, now)
                self._epoch += 1
                s.epoch = self._epoch
                self._sessions[sid] = s
                self._sessions.move_to_end(sid)
                while len(self._sessions) > self.max_sessions:
                    old_sid, _ = next(iter(self._sessions.items()))
                    self._expire_locked(old_sid, "evicted")
                return ("primed", s)
            if s.bucket != tuple(bucket):
                # resolution changed mid-session: the cached half-row is
                # at the old bucket shape — re-prime in place, loudly.
                # The cached flow is at the old bucket's resolution too:
                # drop it, or a later warm step would refine against a
                # mis-sized prior.
                self._rebucketed += 1
                s.row, s.bucket = row, tuple(bucket)
                s.native_hw, s.tier = tuple(native_hw), tier
                s.flow = None
                self._epoch += 1
                s.epoch = self._epoch  # new generation: in-flight
                # writebacks from before the rebucket are now orphans
                s.frames += 1
                s.last_m = now
                self._sessions.move_to_end(sid)
                return ("primed", s)
            prev = s.row
            prior = s.flow
            s.row = row
            s.native_hw = tuple(native_hw)
            s.tier = tier
            s.frames += 1
            s.last_m = now
            self._steps += 1
            self._sessions.move_to_end(sid)
            return ("step", prev, prior, s.epoch, s)

    def set_flow(self, sid: str, flow: np.ndarray,
                 bucket: tuple[int, int], epoch: int) -> bool:
        """Record a resolved step's raw flow output (the bucket's
        finest-head grid) as the session's warm-start prior. Guarded:
        the session must still be live, still at `bucket`, AND still
        the same prime generation (`epoch`, captured inside the
        advance() that formed the step) — a session that was re-primed,
        rebucketed, evicted, or tombstone-RESUMED while the dispatch
        was in flight silently drops the write (False), so a stale or
        wrong-resolution flow can never become a refinement input. No
        LRU/TTL touch: this is engine bookkeeping, not client activity."""
        with self._lock:
            s = self._sessions.get(sid)
            if (s is None or s.bucket != tuple(bucket)
                    or s.epoch != int(epoch)):
                return False
            s.flow = flow
            return True

    def delete(self, sid: str) -> bool:
        """Explicit session end (DELETE /v1/flow/stream/<id>). No
        tombstone: the id's next frame is a fresh prime, not an error.
        False when the id names nothing live."""
        with self._lock:
            self._tombstones.pop(sid, None)  # a deleted id starts clean
            s = self._sessions.pop(sid, None)
            if s is None:
                return False
            self._deleted += 1
            return True

    # ---------------------------------------------------------- sweeper
    def sweep(self) -> int:
        """Expire every session idle past ttl_s; returns how many. The
        sweeper thread calls this every sweep_s; tests call it directly."""
        if self.ttl_s <= 0:
            return 0
        now = time.monotonic()
        with self._lock:
            dead = [sid for sid, s in self._sessions.items()
                    if not self._fresh_locked(s, now)]
            for sid in dead:
                self._expire_locked(sid, "expired")
        return len(dead)

    def _sweep_loop(self, sweep_s: float) -> None:
        while not self._stop.wait(sweep_s):
            self.sweep()

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        """The serve_sessions_* counter block (the engine merges it into
        its stats()). decode_saved == steps: every step decoded and
        preprocessed ONE frame where the pairwise walk would have paid
        two for the same (prev, next) output."""
        with self._lock:
            return {
                "serve_sessions_active": len(self._sessions),
                "serve_sessions_created": self._created,
                "serve_sessions_resumed": self._resumed,
                "serve_sessions_expired": self._expired,
                "serve_sessions_evicted": self._evicted,
                "serve_sessions_deleted": self._deleted,
                "serve_sessions_rebucketed": self._rebucketed,
                "serve_sessions_frames": self._frames,
                "serve_sessions_steps": self._steps,
                "serve_sessions_decode_saved": self._steps,
            }

    def close(self) -> None:
        self._stop.set()
        if self._sweeper is not None:
            self._sweeper.join(timeout=5.0)
