"""SLO-driven fleet autoscaler: the fixed replica pool becomes a
load-follower.

`serve --replicas N` (PR 6) is a fixed pool: under burst it sheds
structured 503s, at idle it holds N warm replicas doing nothing — and
the observability plane (PR 9) already exports exactly the signals a
load-follower needs. This module closes that loop. One control thread
evaluates the LIVE router/fleet counters every
`fleet.autoscale_period_s` and drives the pool between
`fleet.min_replicas` and `fleet.max_replicas`:

  Pressure (scale up): a tick counts as pressure when NEW shed/
  unavailable rejections landed since the previous tick (refused work
  is the hardest evidence of under-capacity), when pool occupancy
  (router in-flight over ready * max_in_flight) reaches
  `autoscale_up_occupancy`, or when NEW SLO latency breaches landed
  while the error-budget burn is past `autoscale_up_slo_burn` (capacity
  arrives while the budget still has headroom). Pressure sustained for
  `autoscale_up_after_s` adds ONE replica (`Fleet.scale_up` — a new
  monotonic slot index, spawned through the same supervisor state
  machine every replica lives in).

  Idle (scale down): a tick counts as idle when occupancy is at or
  below `autoscale_down_occupancy` AND nothing was shed. Idle sustained
  for `autoscale_down_after_s` retires ONE replica via
  `Fleet.retire_one`: out of rotation immediately, router in-flight
  drained, SIGTERM (the replica's own drain hook flushes any racing
  request), reap — zero silent drops by construction, counted as
  `retired`, never as an eviction.

  Hysteresis + cooldown: the wide gap between the up and down
  occupancy thresholds is the band where the pool holds steady; ticks
  in the band reset both streaks. `autoscale_up_cooldown_s` keeps one
  burst from spawning the whole ladder before the first new replica
  has compiled; `autoscale_down_cooldown_s` (measured from ANY scale
  event) keeps a fresh replica's warm-up idle from immediately
  retiring its sibling. Respawn-compile cost cannot flap the pool.

The decision core (`evaluate`) is a pure function of (clock, signals,
accumulated streak state) — unit-testable without threads, subprocesses
or sleeps. Scale events are first-class observability: the
`fleet_autoscale_*` counter block (obs/registry.py-declared) rides the
fleet heartbeat, `/metrics` and `analyze`/`tail`, and every scale event
appends one `kind="fleet"` record to the fleet's metrics.jsonl — the
pool-size timeline is auditable from the run dir alone.

Stdlib-only at import (the supervisor discipline, core/supervise.py).
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..core.config import ExperimentConfig


class Autoscaler:
    """See module docstring.

    cfg: the fleet-level experiment config (fleet.autoscale knobs).
    fleet: the live Fleet (scale_up / retire_one / stats).
    router: the live Router (stats — shed/SLO/in-flight signals — and
        in_flight_of, which retire_one drains against).
    """

    def __init__(self, cfg: ExperimentConfig, fleet, router):
        self.cfg = cfg
        self.fc = cfg.serve.fleet
        self.fleet = fleet
        self.router = router
        self.min = max(int(self.fc.min_replicas), 1)
        self.max = max(int(self.fc.max_replicas), 1)
        if self.min > self.max:
            # Fleet.__init__ rejects this too; repeated here so a
            # standalone Autoscaler can never scale past the ceiling
            raise ValueError(
                f"serve.fleet.min_replicas={self.fc.min_replicas} > "
                f"max_replicas={self.fc.max_replicas}: unsatisfiable "
                "autoscale bounds")
        self.period_s = max(float(self.fc.autoscale_period_s), 0.05)
        self._lock = threading.Lock()
        self._counters = {k: 0 for k in (
            "up", "down", "blocked_max", "pressure_ticks", "idle_ticks",
            "slope_ticks")}
        # streak clocks: monotonic time the current pressure/idle run
        # started (None = the condition does not currently hold)
        self._pressure_since: float | None = None
        self._idle_since: float | None = None
        self._last_up_m: float | None = None
        self._last_event_m: float | None = None
        # previous tick's cumulative rejection/breach counts — the
        # deltas are the "NEW refused work this tick" pressure signal
        self._prev_bad = 0
        self._prev_breaches = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-autoscaler")

    # ---------------------------------------------------------- signals
    def signals(self) -> dict:
        """One tick's inputs from the live fleet/router counters."""
        fs = self.fleet.stats()
        rs = self.router.stats()
        ready = int(fs.get("fleet_ready") or 0)
        cap = max(ready, 1) * max(int(self.fc.max_in_flight), 1)
        slo = rs.get("fleet_slo") or {}
        # broken slots (breaker open — terminal, no process, never
        # respawned) must not count toward the pool size: against the
        # max gate they would block scale-up FOREVER while the one
        # surviving replica sheds; backoff slots DO count (they hold
        # resources and respawn into capacity on their own)
        states = fs.get("fleet_states") or {}
        broken = sum(1 for v in states.values() if v == "broken")
        return {
            "size": max(int(fs.get("fleet_replicas") or 0) - broken, 0),
            "ready": ready,
            "bad_total": (int(rs.get("fleet_shed") or 0)
                          + int(rs.get("fleet_unavailable") or 0)),
            "occupancy": float(rs.get("fleet_in_flight") or 0) / cap,
            "slo_breaches": int(slo.get("breaches") or 0),
            "slo_burn": float(slo.get("burn") or 0.0),
            # requests/s growth from the router's per-second completion
            # buckets (router._load_trend) — the PREDICTIVE signal:
            # positive slope means the load is still climbing toward
            # whatever will shed, so capacity can start booting now
            "load_slope": float(rs.get("fleet_load_slope") or 0.0),
        }

    # --------------------------------------------------------- decision
    def evaluate(self, now_m: float, sig: dict) -> tuple[str | None, str]:
        """One control-loop decision from (clock, signals): ("up"|"down"
        |None, reason). Pure in the streak state this object
        accumulates — tests drive it with fabricated clocks and signals,
        no threads or sleeps. Cooldowns and the min/max bounds are
        enforced HERE so a unit test of the policy is a test of the
        shipped behavior."""
        bad_delta = sig["bad_total"] - self._prev_bad
        breach_delta = sig["slo_breaches"] - self._prev_breaches
        self._prev_bad = sig["bad_total"]
        self._prev_breaches = sig["slo_breaches"]

        shed_pressure = bad_delta > 0
        occ_pressure = sig["occupancy"] >= float(self.fc.autoscale_up_occupancy)
        slo_pressure = (breach_delta > 0 and sig["slo_burn"]
                        >= float(self.fc.autoscale_up_slo_burn))
        # predictive pressure (ISSUE 16): the load TREND crossed
        # autoscale_up_slope req/s-per-s — scale while the ramp is still
        # climbing, before occupancy saturates or the first shed lands.
        # Disabled (<= 0) keeps the reactive-only r14 policy bit-exact.
        slope_pressure = (float(self.fc.autoscale_up_slope) > 0
                          and sig.get("load_slope", 0.0)
                          >= float(self.fc.autoscale_up_slope))
        pressure = (shed_pressure or occ_pressure or slo_pressure
                    or slope_pressure)
        idle = (bad_delta == 0 and sig["occupancy"]
                <= float(self.fc.autoscale_down_occupancy))

        with self._lock:
            if pressure:
                self._counters["pressure_ticks"] += 1
                if slope_pressure and not (shed_pressure or occ_pressure
                                           or slo_pressure):
                    # the slope ALONE saw it coming: the tick the pool
                    # moved ahead of the load instead of behind it
                    self._counters["slope_ticks"] += 1
                self._idle_since = None
                if self._pressure_since is None:
                    self._pressure_since = now_m
            elif idle:
                self._counters["idle_ticks"] += 1
                self._pressure_since = None
                if self._idle_since is None:
                    self._idle_since = now_m
            else:
                # the hysteresis band between the thresholds: hold, and
                # require any future decision to re-earn its full window
                self._pressure_since = None
                self._idle_since = None

            if (self._pressure_since is not None
                    and now_m - self._pressure_since
                    >= float(self.fc.autoscale_up_after_s)):
                # reactive causes outrank the predictive one in the
                # label: "load_slope" on a scale record means the pool
                # grew BEFORE any shed/breach/saturation existed
                why = ("shed" if shed_pressure
                       else "slo_burn" if slo_pressure
                       else "occupancy" if occ_pressure else "load_slope")
                if sig["size"] >= self.max:
                    self._counters["blocked_max"] += 1
                    return None, f"pressure ({why}) but at max_replicas"
                if (self._last_up_m is not None
                        and now_m - self._last_up_m
                        < float(self.fc.autoscale_up_cooldown_s)):
                    return None, "up cooldown"
                return "up", why
            if (self._idle_since is not None
                    and now_m - self._idle_since
                    >= float(self.fc.autoscale_down_after_s)):
                # floor on BOTH counts: size (slots) keeps the pool's
                # footprint at min, ready keeps its serving capacity
                # there — a broken/backoff slot counts toward size but
                # serves nothing, and retiring the last READY replica
                # because a dead sibling pads the count would leave the
                # pool serving nothing at all
                if sig["size"] <= self.min or sig["ready"] <= self.min:
                    return None, "idle but at min_replicas"
                if (self._last_event_m is not None
                        and now_m - self._last_event_m
                        < float(self.fc.autoscale_down_cooldown_s)):
                    return None, "down cooldown"
                return "down", "sustained idle"
        return None, "holding"

    # ------------------------------------------------------------- act
    def _tick(self) -> None:
        now_m = time.monotonic()
        sig = self.signals()
        action, reason = self.evaluate(now_m, sig)
        if action == "up":
            idx = self.fleet.scale_up()
            if idx is None:
                return  # fleet stopping: no event
            with self._lock:
                self._counters["up"] += 1
                self._last_up_m = now_m
                self._last_event_m = now_m
                self._pressure_since = None  # re-earn the next window
            self._record("scale_up", reason, sig, replica=idx)
        elif action == "down":
            idx = self.fleet.retire_one(self.router)  # blocks: drains
            if idx is None:
                return
            with self._lock:
                self._counters["down"] += 1
                self._last_event_m = time.monotonic()
                self._idle_since = None
            self._record("scale_down", reason, sig, replica=idx)

    def _record(self, event: str, reason: str, sig: dict,
                replica: int) -> None:
        """One kind="fleet" scale record into the fleet's metrics.jsonl:
        the pool-size timeline `analyze`/`tail` surface."""
        try:
            after = self.fleet.size
            before = after + (1 if event == "scale_down" else -1)
            rec = {"kind": "fleet", "step": 0, "time": time.time(),
                   "event": event, "reason": reason, "replica": replica,
                   "replicas_before": before, "replicas_after": after,
                   "occupancy": round(sig["occupancy"], 4),
                   **self.stats()}
            os.makedirs(self.cfg.train.log_dir, exist_ok=True)
            with open(os.path.join(self.cfg.train.log_dir,
                                   "metrics.jsonl"), "a") as f:
                f.write(json.dumps(rec, allow_nan=False) + "\n")
        except OSError:
            pass

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        """The fleet_autoscale_* counter block (obs/registry.py-declared;
        rides the fleet heartbeat, /metrics, and the shutdown
        kind="serve" record)."""
        with self._lock:
            c = dict(self._counters)
            last = self._last_event_m
        return {
            "fleet_autoscale_enabled": True,
            "fleet_autoscale_min": self.min,
            "fleet_autoscale_max": self.max,
            "fleet_autoscale_up": c["up"],
            "fleet_autoscale_down": c["down"],
            "fleet_autoscale_blocked_max": c["blocked_max"],
            "fleet_autoscale_pressure_ticks": c["pressure_ticks"],
            "fleet_autoscale_idle_ticks": c["idle_ticks"],
            "fleet_autoscale_slope_ticks": c["slope_ticks"],
            "fleet_autoscale_last_event_s": (
                round(time.monotonic() - last, 1)
                if last is not None else None),
        }

    # --------------------------------------------------------- lifecycle
    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(timeout=self.period_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - scaling must not die mid-run
                pass  # next tick re-reads live state; fleet health owns
                #       replica failures, this loop only sizes the pool

    def close(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:
            # worst case: a tick is inside retire_one — router drain
            # (drain_timeout_s) then reap with a term_grace_s +
            # drain_timeout_s deadline before the SIGKILL escalation
            self._thread.join(timeout=self.period_s
                              + 2.0 * float(self.fc.drain_timeout_s)
                              + float(self.fc.term_grace_s) + 5.0)

    def __enter__(self) -> "Autoscaler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
