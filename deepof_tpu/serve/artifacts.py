"""Executable artifact plane: fingerprint-keyed AOT executables on disk
(DESIGN.md "Artifact plane").

Scale-up latency is the fleet's dominant tax: every respawn and
autoscale-up pays the full (bucket x tier x mode) lattice compile,
because the r06 heap-corruption finding forces the persistent XLA cache
OFF for concurrent cpu children. This module replaces the *compile*
with a *fetch*: ``warmup --serve`` (the single writer) serializes each
AOT-compiled executable (``jax.experimental.serialize_executable``)
under ``artifacts/exec/<stablehlo-fingerprint>/`` next to an
atomic-rename manifest, and every engine/replica start deserializes
instead of compiling.

Integrity model — the fingerprint IS the gate:

  - The store key is the StableHLO fingerprint of the *local* lowering
    (obs/ledger.py ``fingerprint_text``), recomputed by every consumer
    at fetch time. Drifted code lowers to different StableHLO, which
    hashes to a different key, which is a store MISS — a stale artifact
    can never load against changed code.
  - The manifest must agree with its own directory name, the payload's
    size/crc32, the backend, and the jax version; any mismatch is a
    loud ``reject:*`` (stderr warn + ledger counter) and the caller
    falls back to the ordinary compile path.
  - Publish stages into a ``.tmp-<pid>-*`` sibling and ``os.rename``s
    the whole directory into place: readers never observe a torn entry,
    and concurrent publishers resolve first-writer-wins (renaming onto
    an existing entry fails, which is "exists", not an error).

Executable index — trace-free resolution (this file's second plane):

  Recomputing the fingerprint means re-tracing and re-lowering every
  lattice executable at boot, which is the dominant cold-boot cost
  (lowering is host-bound even on real devices). ``index.json`` in the
  store root maps a pure, jax-free **resolution key** — sha256-16 over
  (exec name, config digest, aval signature, backend, jax version) —
  to the fingerprint the single writer lowered for that key. A
  consumer that resolves through the index performs zero trace/lower
  calls: key lookup, structural gates, ``fetch`` (manifest + crc), and
  deserialize. What the index loses relative to the fingerprint path
  is the drifted-code guarantee: code drift changes the fingerprint
  but not the key, so a stale index can serve a stale executable whose
  bytes are intact. That gap is closed by the **deferred deep-verify
  plane** (serve/engine.py): a background verifier re-lowers each
  index-resolved entry *after* serving starts and loudly demotes on
  fingerprint mismatch (counter + warn record + recompile swap-in).
  Forged or torn index state never resolves silently: every entry
  must hash back to its own key, name-match its target's manifest, and
  pass the same backend/jax/crc gates as a fingerprint fetch —
  anything else is a counted ``index_reject`` and the caller falls
  back to the compile path.

Single-writer publish + read-only consumers is exactly the discipline
the cross-process persistent-cache corruption violated; the artifact
plane gets warm starts on this host without reopening that wound.

Import discipline: module import is stdlib-only so the ``deepof_tpu
artifacts`` CLI verb (list/verify/gc) stays jax-free; jax is imported
inside the serialize/deserialize helpers only.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import sys
import time
import zlib

#: manifest schema version — bumped on any layout change so old entries
#: reject (schema mismatch) instead of deserializing garbage
SCHEMA = 1

#: index schema version — bumped when resolution-key composition or the
#: entry layout changes, so old indexes miss loudly instead of mapping
#: keys built one way to fingerprints recorded another
INDEX_SCHEMA = 1

MANIFEST = "manifest.json"
BLOB = "exec.bin"
INDEX = "index.json"

#: default store root: lives under artifacts/ with the other
#: cross-session state (hostmesh.COMPILE_CACHE_DIR convention) so one
#: rsync of artifacts/ carries warm executables to a fresh host
DEFAULT_STORE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "artifacts", "exec")


def _is_fingerprint(name: str) -> bool:
    return (len(name) == 16
            and all(c in "0123456789abcdef" for c in name))


def store_entries(root: str) -> list[str]:
    """Fingerprint directory names present in the store (sorted); tmp
    staging dirs and strangers are not entries."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    return sorted(n for n in names
                  if _is_fingerprint(n)
                  and os.path.isdir(os.path.join(root, n)))


def verify_entry(root: str, fingerprint: str) -> dict:
    """Structural verdict for one entry — manifest parses, fingerprint
    agrees with the directory name, payload size/crc32 agree with the
    manifest. jax-free: deserialization is NOT attempted here (that is
    the consumer's job, behind the same gates plus backend/version)."""
    entry = {"fingerprint": fingerprint, "ok": False, "why": None,
             "name": None, "backend": None, "size": None, "crc32": None,
             "created": None}
    d = os.path.join(root, fingerprint)
    man_path = os.path.join(d, MANIFEST)
    try:
        with open(man_path) as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        entry["why"] = f"manifest_unreadable: {e}"
        return entry
    entry["name"] = man.get("name")
    entry["backend"] = man.get("backend")
    entry["created"] = man.get("created")
    if man.get("schema") != SCHEMA:
        entry["why"] = f"schema_mismatch: {man.get('schema')!r}"
        return entry
    if man.get("fingerprint") != fingerprint:
        entry["why"] = (f"fingerprint_mismatch: manifest says "
                        f"{man.get('fingerprint')!r}")
        return entry
    payload = man.get("payload") or {}
    blob_path = os.path.join(d, payload.get("file") or BLOB)
    try:
        blob = open(blob_path, "rb").read()
    except OSError as e:
        entry["why"] = f"payload_unreadable: {e}"
        return entry
    entry["size"] = len(blob)
    entry["crc32"] = zlib.crc32(blob)
    if payload.get("size") != len(blob):
        entry["why"] = (f"size_mismatch: manifest {payload.get('size')} "
                        f"!= {len(blob)}")
        return entry
    if payload.get("crc32") != entry["crc32"]:
        entry["why"] = "crc_mismatch"
        return entry
    entry["ok"] = True
    return entry


def verify_store(root: str) -> dict:
    """Whole-store structural report: every entry's verdict plus the
    corrupt/ok split and leftover tmp staging dirs (a publisher that
    died mid-stage)."""
    fps = store_entries(root)
    entries = [verify_entry(root, fp) for fp in fps]
    tmp = []
    try:
        tmp = sorted(n for n in os.listdir(root) if n.startswith(".tmp-"))
    except OSError:
        pass
    return {
        "dir": root,
        "entries": entries,
        "total": len(entries),
        "ok": sum(1 for e in entries if e["ok"]),
        "corrupt": [e["fingerprint"] for e in entries if not e["ok"]],
        "tmp_dirs": tmp,
    }


def gc_store(root: str, older_than_days: float | None = None,
             roots: set[str] | frozenset[str] | None = None) -> dict:
    """Garbage-collect the store: corrupt entries and orphaned tmp
    staging always go; with ``older_than_days`` set, structurally
    valid entries whose manifest ``created`` stamp is older also go
    (code churn strands entries forever — their fingerprints never
    recur — so age is the only useful liveness signal).

    ``roots`` pins fingerprints against *age-based* removal (corrupt
    entries are removed regardless — they cannot serve). The index's
    own targets are always added to the root set, so a GC triggered by
    supervisor retirement can never collect an executable the next
    replica boot would index-resolve. Index entries whose target no
    longer exists after the sweep are pruned from ``index.json``."""
    report = verify_store(root)
    pinned = set(roots or ())
    pinned |= index_targets(root)
    removed, kept = [], []
    now = time.time()
    for e in report["entries"]:
        drop = not e["ok"]
        if (not drop and older_than_days is not None
                and e["fingerprint"] not in pinned
                and isinstance(e["created"], (int, float))
                and now - e["created"] > older_than_days * 86400.0):
            drop = True
        if drop:
            shutil.rmtree(os.path.join(root, e["fingerprint"]),
                          ignore_errors=True)
            removed.append(e["fingerprint"])
        else:
            kept.append(e["fingerprint"])
    for t in report["tmp_dirs"]:
        p = os.path.join(root, t)
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        else:
            try:
                os.remove(p)
            except OSError:
                pass
    pruned = _prune_index(root, set(removed))
    return {"dir": root, "removed": removed, "kept": kept,
            "tmp_removed": report["tmp_dirs"], "index_pruned": pruned}


# ------------------------------------------------- executable index


def resolution_key(name: str, config_digest: str, aval_sig: str,
                   backend: str, jax_version: str) -> str:
    """The pure, jax-free index key: sha256-16 over the canonical JSON
    of the five components. Deterministic across processes (sorted
    keys, no whitespace variance), recomputable by any consumer that
    knows its own config + concrete param shapes — no tracing."""
    payload = json.dumps(
        [name, config_digest, aval_sig, backend, jax_version],
        sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


#: config fields that flow into a serve lowering — anything else
#: (ports, log dirs, fleet knobs) varies per replica without changing
#: the StableHLO, and must NOT invalidate the index
def serve_config_digest(cfg) -> str:
    """Digest of the lowering-relevant config subset. Jax-free: reads
    dataclass fields only. A change to any field that shapes the
    lattice (model topology, buckets, batch, tiers, warm session) flips
    the digest, so the index misses loudly and the consumer falls back
    to the compile path."""
    sub = {
        "model": cfg.model,
        "width_mult": cfg.width_mult,
        "corr_max_disp": cfg.corr_max_disp,
        "corr_stride": cfg.corr_stride,
        "time_step": cfg.data.time_step,
        "image_size": list(cfg.data.image_size),
        "max_batch": cfg.serve.max_batch,
        "buckets": [list(b) for b in (cfg.serve.buckets or ())],
        "precisions": list(cfg.serve.precisions or ()),
        "warm_start": cfg.serve.session.warm_start,
        "warm_width": cfg.serve.session.warm_width,
    }
    payload = json.dumps(sub, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _empty_index() -> dict:
    return {"schema": INDEX_SCHEMA, "updated": None, "entries": {}}


def load_index(root: str) -> dict:
    """Read ``index.json`` tolerantly: absent, torn, or wrong-schema
    index reads as empty (= every resolve is an index miss, never an
    exception on the boot path)."""
    path = os.path.join(root, INDEX)
    try:
        with open(path) as f:
            idx = json.load(f)
    except (OSError, ValueError):
        return _empty_index()
    if (not isinstance(idx, dict)
            or idx.get("schema") != INDEX_SCHEMA
            or not isinstance(idx.get("entries"), dict)):
        return _empty_index()
    return idx


def write_index(root: str, entries: dict) -> dict:
    """Single-writer atomic index publish: merge ``entries`` (key ->
    entry dict) over the existing index, stage to a ``.tmp-`` sibling
    file, ``os.rename`` over ``index.json``. Readers observe either
    the old or the new index, never a torn one."""
    idx = load_index(root)
    idx["entries"].update(entries)
    idx["updated"] = time.time()
    os.makedirs(root, exist_ok=True)
    tmp = os.path.join(root, f".tmp-{os.getpid()}-index.json")
    with open(tmp, "w") as f:
        json.dump(idx, f, indent=2, sort_keys=True)
    os.rename(tmp, os.path.join(root, INDEX))
    return idx


def index_targets(root: str) -> set[str]:
    """Fingerprints the index maps to (jax-free) — the GC root set a
    supervisor pins before sweeping the store."""
    idx = load_index(root)
    out = set()
    for ent in idx["entries"].values():
        fp = (ent or {}).get("fingerprint")
        if isinstance(fp, str) and _is_fingerprint(fp):
            out.add(fp)
    return out


def _prune_index(root: str, removed_fps: set[str]) -> list[str]:
    """Drop index entries whose target fingerprint was just GC'd, so a
    later boot takes a clean index MISS instead of a stale-target
    reject. No-op when there is no index or nothing points at the
    removed set."""
    if not removed_fps or not os.path.isfile(os.path.join(root, INDEX)):
        return []
    idx = load_index(root)
    stale = [k for k, ent in idx["entries"].items()
             if (ent or {}).get("fingerprint") in removed_fps]
    if not stale:
        return []
    for k in stale:
        del idx["entries"][k]
    idx["updated"] = time.time()
    tmp = os.path.join(root, f".tmp-{os.getpid()}-index.json")
    with open(tmp, "w") as f:
        json.dump(idx, f, indent=2, sort_keys=True)
    os.rename(tmp, os.path.join(root, INDEX))
    return sorted(stale)


# --------------------------------------------------------- jax half


def _serialize_compiled(compiled) -> bytes:
    """One self-contained blob per executable: the PJRT payload plus the
    pickled in/out tree defs ``deserialize_and_load`` needs. Local
    trusted store (single writer = this repo's own warmup), so pickle
    for the tree defs is acceptable."""
    import pickle

    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree))


def _deserialize_compiled(blob: bytes):
    import pickle

    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = pickle.loads(blob)
    return se.deserialize_and_load(payload, in_tree, out_tree)


def params_aval_sig(params, extra: tuple = ()) -> str:
    """Aval signature over a params tree plus explicit extra avals —
    sha256-16 of the sorted (tree path, shape, dtype) list. Reading
    ``.shape``/``.dtype`` off concrete arrays (engine side) or
    ShapeDtypeStructs (warmup side) is NOT a trace, so both sides
    compute the identical signature trace-free. A checkpoint whose
    shapes disagree with the published lattice (width drift, dtype
    drift) flips the signature and the index misses instead of serving
    an executable lowered for different avals.

    ``extra`` is a tuple of (label, shape-tuple, dtype-str) triples for
    non-params inputs (the batched frame-pair aval)."""
    from jax.tree_util import keystr, tree_flatten_with_path

    flat, _ = tree_flatten_with_path(params)
    rows = [[keystr(p), list(v.shape), str(v.dtype)] for p, v in flat]
    rows += [[label, list(shape), str(dtype)]
             for label, shape, dtype in extra]
    rows.sort()
    payload = json.dumps(rows, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


class ArtifactStore:
    """Fingerprint-keyed executable store bound to one backend.

    ``fetch(fingerprint)`` -> ``(compiled | None, verdict)`` where
    verdict is ``"hit"``, ``"miss"`` (no entry — including every
    drifted-code case, because the caller keys by its OWN lowering's
    fingerprint), or ``"reject:<why>"`` (entry exists but failed an
    integrity gate; warned loudly on stderr). ``publish`` is the single
    writer's atomic-rename path and returns ``"published"`` /
    ``"exists"`` / ``"error:<why>"`` — never raises into the warmup.
    """

    def __init__(self, root: str, backend: str | None = None):
        self.root = str(root)
        self.backend = backend
        self._index = None  # lazy; one load per store instance

    # consumers ------------------------------------------------------

    def resolve(self, key: str):
        """Trace-free resolution: index key -> ``(compiled | None,
        fingerprint | None, verdict)`` with verdict one of
        ``"index_hit"`` / ``"index_miss"`` / ``"index_reject:<why>"``.
        Zero trace/lower calls on every path — lookup, structural
        gates, manifest/crc fetch, deserialize. Every reject falls back
        loudly (stderr + counted by the ledger); a forged entry (does
        not hash back to its own key), a cross-wired entry (target
        manifest name disagrees), or a stale target (entry GC'd) never
        resolves silently."""
        if self._index is None:
            self._index = load_index(self.root)
        ent = self._index["entries"].get(key)
        if ent is None:
            return None, None, "index_miss"
        try:
            want = resolution_key(ent["name"], ent["config_digest"],
                                  ent["aval_sig"], ent["backend"],
                                  ent["jax"])
        except (KeyError, TypeError):
            return None, None, self._index_reject(key, "entry_malformed")
        if want != key:
            return None, None, self._index_reject(
                key, f"entry_forged: components hash to {want}")
        fp = ent.get("fingerprint")
        if not (isinstance(fp, str) and _is_fingerprint(fp)):
            return None, None, self._index_reject(
                key, f"bad_fingerprint: {fp!r}")
        import jax
        if self.backend and ent["backend"] != self.backend:
            return None, fp, self._index_reject(
                key, f"backend_mismatch: index entry is for "
                     f"{ent['backend']!r}, we run {self.backend!r}")
        if ent["jax"] != jax.__version__:
            return None, fp, self._index_reject(
                key, f"jax_version_mismatch: index entry from "
                     f"{ent['jax']!r}, we run {jax.__version__!r}")
        if not os.path.isfile(os.path.join(self.root, fp, MANIFEST)):
            return None, fp, self._index_reject(
                key, f"stale_target: {fp} not in store")
        try:
            with open(os.path.join(self.root, fp, MANIFEST)) as f:
                man = json.load(f)
        except (OSError, ValueError) as e:
            return None, fp, self._index_reject(
                key, f"target_manifest_unreadable: {e}")
        if man.get("name") != ent["name"]:
            return None, fp, self._index_reject(
                key, f"name_mismatch: target manifest says "
                     f"{man.get('name')!r}, index entry is "
                     f"{ent['name']!r}")
        compiled, verdict = self.fetch(fp)
        if compiled is None:
            why = verdict.split(":", 1)[1] if ":" in verdict else verdict
            return None, fp, self._index_reject(key, f"target_{why}")
        return compiled, fp, "index_hit"

    def index_entry(self, key: str) -> dict | None:
        """The raw index entry for a key (for deep-verify metadata like
        ``prior_hw``), or None. Uses the same lazily-loaded snapshot as
        ``resolve``."""
        if self._index is None:
            self._index = load_index(self.root)
        return self._index["entries"].get(key)

    @staticmethod
    def _index_reject(key: str, why: str) -> str:
        print(f"artifacts: INDEX REJECT {key}: {why} — falling back to "
              f"the lowering path", file=sys.stderr)
        return f"index_reject:{why.split(':', 1)[0]}"

    def fetch(self, fingerprint: str):
        d = os.path.join(self.root, fingerprint)
        if not os.path.isfile(os.path.join(d, MANIFEST)):
            return None, "miss"
        entry = verify_entry(self.root, fingerprint)
        if not entry["ok"]:
            return None, self._reject(fingerprint, entry["why"])
        with open(os.path.join(d, MANIFEST)) as f:
            man = json.load(f)
        import jax
        if self.backend and man.get("backend") != self.backend:
            return None, self._reject(
                fingerprint, f"backend_mismatch: artifact is for "
                             f"{man.get('backend')!r}, we run "
                             f"{self.backend!r}")
        if man.get("jax") != jax.__version__:
            return None, self._reject(
                fingerprint, f"jax_version_mismatch: artifact from "
                             f"{man.get('jax')!r}, we run "
                             f"{jax.__version__!r}")
        blob_path = os.path.join(d, (man.get("payload") or {}).get("file")
                                 or BLOB)
        try:
            with open(blob_path, "rb") as f:
                compiled = _deserialize_compiled(f.read())
        except Exception as e:  # noqa: BLE001 - any failure = fall back
            return None, self._reject(fingerprint,
                                      f"deserialize_failed: {e}")
        return compiled, "hit"

    @staticmethod
    def _reject(fingerprint: str, why: str) -> str:
        print(f"artifacts: REJECT {fingerprint}: {why} — falling back "
              f"to compile", file=sys.stderr)
        return f"reject:{why.split(':', 1)[0]}"

    # the single writer ----------------------------------------------

    def publish(self, fingerprint: str, compiled, *, name: str = "",
                compile_s: float | None = None, meta: dict | None = None
                ) -> str:
        final = os.path.join(self.root, fingerprint)
        if os.path.isfile(os.path.join(final, MANIFEST)):
            return "exists"
        try:
            import jax

            blob = _serialize_compiled(compiled)
            man = {
                "schema": SCHEMA,
                "fingerprint": fingerprint,
                "name": name,
                "backend": self.backend or jax.default_backend(),
                "jax": jax.__version__,
                "compile_s": compile_s,
                "created": time.time(),
                "payload": {"file": BLOB, "size": len(blob),
                            "crc32": zlib.crc32(blob)},
            }
            if meta:
                man.update(meta)
            tmp = os.path.join(self.root,
                               f".tmp-{os.getpid()}-{fingerprint}")
            os.makedirs(tmp, exist_ok=True)
            with open(os.path.join(tmp, BLOB), "wb") as f:
                f.write(blob)
            with open(os.path.join(tmp, MANIFEST), "w") as f:
                json.dump(man, f, indent=2, sort_keys=True)
            try:
                os.rename(tmp, final)
            except OSError:
                # someone else won the rename race; their entry stands
                shutil.rmtree(tmp, ignore_errors=True)
                return "exists"
            return "published"
        except Exception as e:  # noqa: BLE001 - publish is best-effort
            print(f"artifacts: publish {fingerprint} failed: {e}",
                  file=sys.stderr)
            return f"error:{type(e).__name__}"


def store_for_config(cfg) -> ArtifactStore | None:
    """The store a serve config asks for, or None when the plane is off
    (``serve.artifacts_dir`` empty). Resolved to an absolute path so
    replica subprocesses (their own cwd) read the same store."""
    root = getattr(cfg.serve, "artifacts_dir", "")
    if not root:
        return None
    import jax
    return ArtifactStore(os.path.abspath(os.path.expanduser(root)),
                         backend=jax.default_backend())
