"""Executable artifact plane: fingerprint-keyed AOT executables on disk
(DESIGN.md "Artifact plane").

Scale-up latency is the fleet's dominant tax: every respawn and
autoscale-up pays the full (bucket x tier x mode) lattice compile,
because the r06 heap-corruption finding forces the persistent XLA cache
OFF for concurrent cpu children. This module replaces the *compile*
with a *fetch*: ``warmup --serve`` (the single writer) serializes each
AOT-compiled executable (``jax.experimental.serialize_executable``)
under ``artifacts/exec/<stablehlo-fingerprint>/`` next to an
atomic-rename manifest, and every engine/replica start deserializes
instead of compiling.

Integrity model — the fingerprint IS the gate:

  - The store key is the StableHLO fingerprint of the *local* lowering
    (obs/ledger.py ``fingerprint_text``), recomputed by every consumer
    at fetch time. Drifted code lowers to different StableHLO, which
    hashes to a different key, which is a store MISS — a stale artifact
    can never load against changed code.
  - The manifest must agree with its own directory name, the payload's
    size/crc32, the backend, and the jax version; any mismatch is a
    loud ``reject:*`` (stderr warn + ledger counter) and the caller
    falls back to the ordinary compile path.
  - Publish stages into a ``.tmp-<pid>-*`` sibling and ``os.rename``s
    the whole directory into place: readers never observe a torn entry,
    and concurrent publishers resolve first-writer-wins (renaming onto
    an existing entry fails, which is "exists", not an error).

Single-writer publish + read-only consumers is exactly the discipline
the cross-process persistent-cache corruption violated; the artifact
plane gets warm starts on this host without reopening that wound.

Import discipline: module import is stdlib-only so the ``deepof_tpu
artifacts`` CLI verb (list/verify/gc) stays jax-free; jax is imported
inside the serialize/deserialize helpers only.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time
import zlib

#: manifest schema version — bumped on any layout change so old entries
#: reject (schema mismatch) instead of deserializing garbage
SCHEMA = 1

MANIFEST = "manifest.json"
BLOB = "exec.bin"

#: default store root: lives under artifacts/ with the other
#: cross-session state (hostmesh.COMPILE_CACHE_DIR convention) so one
#: rsync of artifacts/ carries warm executables to a fresh host
DEFAULT_STORE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "artifacts", "exec")


def _is_fingerprint(name: str) -> bool:
    return (len(name) == 16
            and all(c in "0123456789abcdef" for c in name))


def store_entries(root: str) -> list[str]:
    """Fingerprint directory names present in the store (sorted); tmp
    staging dirs and strangers are not entries."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    return sorted(n for n in names
                  if _is_fingerprint(n)
                  and os.path.isdir(os.path.join(root, n)))


def verify_entry(root: str, fingerprint: str) -> dict:
    """Structural verdict for one entry — manifest parses, fingerprint
    agrees with the directory name, payload size/crc32 agree with the
    manifest. jax-free: deserialization is NOT attempted here (that is
    the consumer's job, behind the same gates plus backend/version)."""
    entry = {"fingerprint": fingerprint, "ok": False, "why": None,
             "name": None, "backend": None, "size": None, "crc32": None,
             "created": None}
    d = os.path.join(root, fingerprint)
    man_path = os.path.join(d, MANIFEST)
    try:
        with open(man_path) as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        entry["why"] = f"manifest_unreadable: {e}"
        return entry
    entry["name"] = man.get("name")
    entry["backend"] = man.get("backend")
    entry["created"] = man.get("created")
    if man.get("schema") != SCHEMA:
        entry["why"] = f"schema_mismatch: {man.get('schema')!r}"
        return entry
    if man.get("fingerprint") != fingerprint:
        entry["why"] = (f"fingerprint_mismatch: manifest says "
                        f"{man.get('fingerprint')!r}")
        return entry
    payload = man.get("payload") or {}
    blob_path = os.path.join(d, payload.get("file") or BLOB)
    try:
        blob = open(blob_path, "rb").read()
    except OSError as e:
        entry["why"] = f"payload_unreadable: {e}"
        return entry
    entry["size"] = len(blob)
    entry["crc32"] = zlib.crc32(blob)
    if payload.get("size") != len(blob):
        entry["why"] = (f"size_mismatch: manifest {payload.get('size')} "
                        f"!= {len(blob)}")
        return entry
    if payload.get("crc32") != entry["crc32"]:
        entry["why"] = "crc_mismatch"
        return entry
    entry["ok"] = True
    return entry


def verify_store(root: str) -> dict:
    """Whole-store structural report: every entry's verdict plus the
    corrupt/ok split and leftover tmp staging dirs (a publisher that
    died mid-stage)."""
    fps = store_entries(root)
    entries = [verify_entry(root, fp) for fp in fps]
    tmp = []
    try:
        tmp = sorted(n for n in os.listdir(root) if n.startswith(".tmp-"))
    except OSError:
        pass
    return {
        "dir": root,
        "entries": entries,
        "total": len(entries),
        "ok": sum(1 for e in entries if e["ok"]),
        "corrupt": [e["fingerprint"] for e in entries if not e["ok"]],
        "tmp_dirs": tmp,
    }


def gc_store(root: str, older_than_days: float | None = None) -> dict:
    """Garbage-collect the store: corrupt entries and orphaned tmp
    staging dirs always go; with ``older_than_days`` set, structurally
    valid entries whose manifest ``created`` stamp is older also go
    (code churn strands entries forever — their fingerprints never
    recur — so age is the only useful liveness signal)."""
    report = verify_store(root)
    removed, kept = [], []
    now = time.time()
    for e in report["entries"]:
        drop = not e["ok"]
        if (not drop and older_than_days is not None
                and isinstance(e["created"], (int, float))
                and now - e["created"] > older_than_days * 86400.0):
            drop = True
        if drop:
            shutil.rmtree(os.path.join(root, e["fingerprint"]),
                          ignore_errors=True)
            removed.append(e["fingerprint"])
        else:
            kept.append(e["fingerprint"])
    for t in report["tmp_dirs"]:
        shutil.rmtree(os.path.join(root, t), ignore_errors=True)
    return {"dir": root, "removed": removed, "kept": kept,
            "tmp_removed": report["tmp_dirs"]}


# --------------------------------------------------------- jax half


def _serialize_compiled(compiled) -> bytes:
    """One self-contained blob per executable: the PJRT payload plus the
    pickled in/out tree defs ``deserialize_and_load`` needs. Local
    trusted store (single writer = this repo's own warmup), so pickle
    for the tree defs is acceptable."""
    import pickle

    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree))


def _deserialize_compiled(blob: bytes):
    import pickle

    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = pickle.loads(blob)
    return se.deserialize_and_load(payload, in_tree, out_tree)


class ArtifactStore:
    """Fingerprint-keyed executable store bound to one backend.

    ``fetch(fingerprint)`` -> ``(compiled | None, verdict)`` where
    verdict is ``"hit"``, ``"miss"`` (no entry — including every
    drifted-code case, because the caller keys by its OWN lowering's
    fingerprint), or ``"reject:<why>"`` (entry exists but failed an
    integrity gate; warned loudly on stderr). ``publish`` is the single
    writer's atomic-rename path and returns ``"published"`` /
    ``"exists"`` / ``"error:<why>"`` — never raises into the warmup.
    """

    def __init__(self, root: str, backend: str | None = None):
        self.root = str(root)
        self.backend = backend

    # consumers ------------------------------------------------------

    def fetch(self, fingerprint: str):
        d = os.path.join(self.root, fingerprint)
        if not os.path.isfile(os.path.join(d, MANIFEST)):
            return None, "miss"
        entry = verify_entry(self.root, fingerprint)
        if not entry["ok"]:
            return None, self._reject(fingerprint, entry["why"])
        with open(os.path.join(d, MANIFEST)) as f:
            man = json.load(f)
        import jax
        if self.backend and man.get("backend") != self.backend:
            return None, self._reject(
                fingerprint, f"backend_mismatch: artifact is for "
                             f"{man.get('backend')!r}, we run "
                             f"{self.backend!r}")
        if man.get("jax") != jax.__version__:
            return None, self._reject(
                fingerprint, f"jax_version_mismatch: artifact from "
                             f"{man.get('jax')!r}, we run "
                             f"{jax.__version__!r}")
        blob_path = os.path.join(d, (man.get("payload") or {}).get("file")
                                 or BLOB)
        try:
            with open(blob_path, "rb") as f:
                compiled = _deserialize_compiled(f.read())
        except Exception as e:  # noqa: BLE001 - any failure = fall back
            return None, self._reject(fingerprint,
                                      f"deserialize_failed: {e}")
        return compiled, "hit"

    @staticmethod
    def _reject(fingerprint: str, why: str) -> str:
        print(f"artifacts: REJECT {fingerprint}: {why} — falling back "
              f"to compile", file=sys.stderr)
        return f"reject:{why.split(':', 1)[0]}"

    # the single writer ----------------------------------------------

    def publish(self, fingerprint: str, compiled, *, name: str = "",
                compile_s: float | None = None, meta: dict | None = None
                ) -> str:
        final = os.path.join(self.root, fingerprint)
        if os.path.isfile(os.path.join(final, MANIFEST)):
            return "exists"
        try:
            import jax

            blob = _serialize_compiled(compiled)
            man = {
                "schema": SCHEMA,
                "fingerprint": fingerprint,
                "name": name,
                "backend": self.backend or jax.default_backend(),
                "jax": jax.__version__,
                "compile_s": compile_s,
                "created": time.time(),
                "payload": {"file": BLOB, "size": len(blob),
                            "crc32": zlib.crc32(blob)},
            }
            if meta:
                man.update(meta)
            tmp = os.path.join(self.root,
                               f".tmp-{os.getpid()}-{fingerprint}")
            os.makedirs(tmp, exist_ok=True)
            with open(os.path.join(tmp, BLOB), "wb") as f:
                f.write(blob)
            with open(os.path.join(tmp, MANIFEST), "w") as f:
                json.dump(man, f, indent=2, sort_keys=True)
            try:
                os.rename(tmp, final)
            except OSError:
                # someone else won the rename race; their entry stands
                shutil.rmtree(tmp, ignore_errors=True)
                return "exists"
            return "published"
        except Exception as e:  # noqa: BLE001 - publish is best-effort
            print(f"artifacts: publish {fingerprint} failed: {e}",
                  file=sys.stderr)
            return f"error:{type(e).__name__}"


def store_for_config(cfg) -> ArtifactStore | None:
    """The store a serve config asks for, or None when the plane is off
    (``serve.artifacts_dir`` empty). Resolved to an absolute path so
    replica subprocesses (their own cwd) read the same store."""
    root = getattr(cfg.serve, "artifacts_dir", "")
    if not root:
        return None
    import jax
    return ArtifactStore(os.path.abspath(os.path.expanduser(root)),
                         backend=jax.default_backend())
