"""Brownout controller: recompile-free quality degradation under
overload.

The autoscaler (serve/autoscale.py) answers overload with CAPACITY —
but a replica takes seconds-to-minutes to spawn, warm and join the
pool, and until it does the router's only moves are spill then
structured 503 shed. This module adds the missing fast axis: QUALITY.
One control thread evaluates the live router/fleet counters every
`serve.degrade.period_s` (deliberately faster than the autoscaler's
cadence) and walks a declared brownout ladder:

  L0  normal — every request serves at its asked-for operating point.
  L1  downgrade the DEFAULT precision tier: requests that name no
      `precision` serve at the cheapest configured tier
      (serve.precisions' last entry — bf16/int8); an explicit
      `precision` is always honored.
  L2  additionally route to the next-smaller shape bucket on the
      resolution ladder: the resize protocol already rescales flow to
      native pixel units from ANY bucket, so only accuracy drops.
  L3  additionally shed low-priority requests (X-Priority: low) at
      router admission with a structured 503 — default-priority work
      keeps serving on the degraded operating point.

Degradation NEVER compiles: every (bucket, tier) pair the ladder can
reach is an AOT-resolved lattice entry (`warmup --serve` / the
artifact index), so a level transition is a pure routing decision —
provable from the executable ledger (`ledger_diff` shows zero
recompiles across transitions; the acceptance drill pins it).

Escalation/recovery is the autoscaler's hysteresis/cooldown pattern
with a symmetric DOWN ladder: pressure (new shed/unavailable
rejections, occupancy >= up_occupancy, or SLO burn >= up_slo_burn)
sustained for `escalate_after_s` raises the level by ONE; calm (zero
new rejections AND occupancy <= down_occupancy AND burn under the
threshold) sustained for `recover_after_s` lowers it by one. Ticks in
the band between the thresholds reset both streaks, and the cooldowns
keep an oscillating load from flapping the level. The decision core
(`evaluate`) is a pure function of (clock, signals, accumulated streak
state) — unit-testable without threads or sleeps, same contract as
`Autoscaler.evaluate` and the `core/supervise` verdict functions.

Interplay with the autoscaler: both watch the same signals, so
overload degrades within ~a second AND starts a scale-up; when the new
replica lands, occupancy falls, the calm streak accrues, and the level
walks back down — degrade instantly, scale up slowly, restore when
capacity arrives. Every transition is first-class observability: the
`degrade_*` counter block rides router.stats() -> /healthz, /metrics,
the fleet heartbeat and analyze/tail; each transition appends one
kind="serve" record to the fleet's metrics.jsonl; and ENTERING L3
commits a critical `brownout_l3` incident bundle. Sustained L3
(`l3_sustained_s`) is `tail`'s rc 10.

Stdlib-only at import (the supervisor discipline, core/supervise.py).
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..core.config import ExperimentConfig

#: Human labels for the ladder, indexed by level (stats/records/docs).
LEVELS: tuple[str, ...] = ("normal", "tier_downgrade", "bucket_downgrade",
                           "shed_low_priority")


class DegradeController:
    """See module docstring.

    cfg: the fleet-level experiment config (serve.degrade knobs).
    fleet: the live Fleet (stats — ready count).
    router: the live Router (stats — shed/occupancy/SLO signals).
    """

    def __init__(self, cfg: ExperimentConfig, fleet, router):
        self.cfg = cfg
        self.dc = cfg.serve.degrade
        self.fc = cfg.serve.fleet
        self.fleet = fleet
        self.router = router
        self.max_level = min(max(int(self.dc.max_level), 0), 3)
        self.period_s = max(float(self.dc.period_s), 0.05)
        # incident plane handle (run_fleet wires the supervisor's
        # recorder): entering L3 commits a critical bundle; None keeps
        # the site a structural no-op
        self.incidents = None
        self._lock = threading.Lock()
        self._level = 0
        self._counters = {k: 0 for k in (
            "transitions", "escalations", "recoveries", "l3_entries")}
        # streak clocks: monotonic time the current pressure/calm run
        # started (None = the condition does not currently hold)
        self._pressure_since: float | None = None
        self._calm_since: float | None = None
        self._last_escalate_m: float | None = None
        self._last_event_m: float | None = None
        # monotonic time the fleet entered L3 (None below L3): the
        # l3_sustained_s clock behind `tail`'s rc 10
        self._l3_since: float | None = None
        # previous tick's cumulative rejection count — the delta is the
        # "NEW refused work this tick" pressure signal (deliberately
        # EXCLUDES the L3 low-priority sheds this controller causes:
        # its own shedding must not hold it at L3 forever)
        self._prev_bad = 0
        self._last_reason = "init"
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-degrade")

    # ---------------------------------------------------------- signals
    def signals(self) -> dict:
        """One tick's inputs from the live fleet/router counters."""
        fs = self.fleet.stats()
        rs = self.router.stats()
        ready = int(fs.get("fleet_ready") or 0)
        cap = max(ready, 1) * max(int(self.fc.max_in_flight), 1)
        slo = rs.get("fleet_slo") or {}
        return {
            "ready": ready,
            # saturation sheds only — degrade_shed_low is this
            # controller's own output, never its input
            "bad_total": (int(rs.get("fleet_shed") or 0)
                          + int(rs.get("fleet_unavailable") or 0)),
            # router in-flight over pool capacity: the fleet-wide
            # queue-depth signal (every queued request is in-flight at
            # the router until its reply lands)
            "occupancy": float(rs.get("fleet_in_flight") or 0) / cap,
            "slo_burn": float((slo.get("burn") or 0.0)),
        }

    # --------------------------------------------------------- decision
    def evaluate(self, now_m: float, sig: dict) -> tuple[str | None, str]:
        """One control-loop decision from (clock, signals):
        ("escalate"|"recover"|None, reason). Pure in the streak state
        this object accumulates — tests drive it with fabricated clocks
        and signals, no threads or sleeps. Cooldowns and the level
        bounds are enforced HERE so a unit test of the policy is a test
        of the shipped behavior."""
        bad_delta = sig["bad_total"] - self._prev_bad
        shed_pressure = bad_delta > 0
        occ_pressure = sig["occupancy"] >= float(self.dc.up_occupancy)
        burn_pressure = sig["slo_burn"] >= float(self.dc.up_slo_burn)
        pressure = shed_pressure or occ_pressure or burn_pressure
        calm = (bad_delta == 0
                and sig["occupancy"] <= float(self.dc.down_occupancy)
                and not burn_pressure)
        with self._lock:
            self._prev_bad = sig["bad_total"]
            if pressure:
                self._calm_since = None
                if self._pressure_since is None:
                    self._pressure_since = now_m
            elif calm:
                self._pressure_since = None
                if self._calm_since is None:
                    self._calm_since = now_m
            else:
                # the hysteresis band between the thresholds: hold, and
                # require any future transition to re-earn its window
                self._pressure_since = None
                self._calm_since = None

            if (self._pressure_since is not None
                    and now_m - self._pressure_since
                    >= float(self.dc.escalate_after_s)):
                why = ("shed" if shed_pressure
                       else "slo_burn" if burn_pressure else "occupancy")
                if self._level >= self.max_level:
                    return None, f"pressure ({why}) but at max_level"
                if (self._last_escalate_m is not None
                        and now_m - self._last_escalate_m
                        < float(self.dc.escalate_cooldown_s)):
                    return None, "escalate cooldown"
                return "escalate", why
            if (self._calm_since is not None
                    and now_m - self._calm_since
                    >= float(self.dc.recover_after_s)):
                if self._level <= 0:
                    return None, "calm at L0"
                if (self._last_event_m is not None
                        and now_m - self._last_event_m
                        < float(self.dc.recover_cooldown_s)):
                    return None, "recover cooldown"
                return "recover", "sustained calm"
        return None, "holding"

    # ------------------------------------------------------------- act
    def level(self) -> int:
        """The live brownout level — the router's per-request hook."""
        with self._lock:
            return self._level

    def _tick(self) -> None:
        now_m = time.monotonic()
        sig = self.signals()
        action, reason = self.evaluate(now_m, sig)
        if action is None:
            return
        with self._lock:
            before = self._level
            if action == "escalate":
                self._level = min(before + 1, self.max_level)
                self._counters["escalations"] += 1
                self._last_escalate_m = now_m
                # re-earn the next window: one sustained burst walks
                # the ladder one deliberate step per window, not all at
                # once
                self._pressure_since = None
                if self._level == 3 and before < 3:
                    self._counters["l3_entries"] += 1
                    self._l3_since = now_m
            else:
                self._level = max(before - 1, 0)
                self._counters["recoveries"] += 1
                self._calm_since = None
                if before == 3:
                    self._l3_since = None
            self._counters["transitions"] += 1
            self._last_event_m = now_m
            self._last_reason = reason
            after = self._level
        event = ("degrade_escalate" if action == "escalate"
                 else "degrade_recover")
        self._record(event, reason, sig, before, after)
        if action == "escalate" and after == 3 and self.incidents is not None:
            # the fleet is now REFUSING work (low-priority sheds): the
            # flight recorder captures the verdict + the counters that
            # drove it. Dedup absorbs re-entries within the window.
            self.incidents.record(
                "brownout_l3", "critical",
                trigger={"reason": reason, "level": after,
                         "occupancy": round(sig["occupancy"], 4),
                         "slo_burn": round(sig["slo_burn"], 4)})

    def _record(self, event: str, reason: str, sig: dict,
                before: int, after: int) -> None:
        """One kind="serve" transition record into the fleet's
        metrics.jsonl — the brownout-level timeline analyze/tail
        surface next to the autoscaler's kind="fleet" scale records."""
        try:
            rec = {"kind": "serve", "step": 0, "time": time.time(),
                   "event": event, "reason": reason,
                   "level_before": before, "level_after": after,
                   "level_name": LEVELS[after],
                   "occupancy": round(sig["occupancy"], 4),
                   **self.stats()}
            os.makedirs(self.cfg.train.log_dir, exist_ok=True)
            with open(os.path.join(self.cfg.train.log_dir,
                                   "metrics.jsonl"), "a") as f:
                f.write(json.dumps(rec, allow_nan=False) + "\n")
        except OSError:
            pass

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        """The degrade_* counter block (obs/registry.py-declared; rides
        router.stats() -> /healthz, /metrics, the fleet heartbeat and
        the shutdown kind="serve" record)."""
        now_m = time.monotonic()
        with self._lock:
            c = dict(self._counters)
            level = self._level
            l3_since = self._l3_since
            reason = self._last_reason
        l3_age = (now_m - l3_since) if l3_since is not None else None
        return {
            "degrade_enabled": True,
            "degrade_level": level,
            "degrade_level_name": LEVELS[level],
            "degrade_transitions": c["transitions"],
            "degrade_escalations": c["escalations"],
            "degrade_recoveries": c["recoveries"],
            "degrade_l3_entries": c["l3_entries"],
            "degrade_l3_age_s": (round(l3_age, 1)
                                 if l3_age is not None else None),
            # the rc-10 verdict: L3 held continuously past the
            # configured budget — brownout as a steady state means the
            # autoscaler's capacity never arrived
            "degrade_l3_sustained": bool(
                l3_age is not None
                and l3_age >= float(self.dc.l3_sustained_s)),
            "degrade_last_reason": reason,
        }

    # --------------------------------------------------------- lifecycle
    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(timeout=self.period_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - brownout must not die mid-run
                pass  # next tick re-reads live state

    def close(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout=self.period_s + 5.0)

    def __enter__(self) -> "DegradeController":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
