"""Front-of-fleet router: health-gated, bucket-affine, failover-retrying.

The fleet's public face (`deepof_tpu serve --replicas N`) is one stdlib
HTTP endpoint with the same API as a single replica (`POST /v1/flow`,
`GET /healthz`); behind it, `serve/fleet.py` supervises N engine-replica
subprocesses and this router decides, per request, which of them serves.
Three policies, in order:

  Bucket affinity. Each replica keeps one AOT executable hot per shape
  bucket; scattering a bucket's requests across replicas evicts those
  executables from every replica's working set and splits its batches.
  The router probes the request's image dimensions (header-only PNG/
  JPEG/BMP parse — no full decode at the front), maps them to the
  resolution ladder's bucket, and prefers replica `ladder_index % N` —
  a fixed affinity map, so bucket b's traffic concentrates on one
  replica while every replica can still serve any bucket. Precision
  tiers (serve/quant.py) fold into the same map: the ladder is the
  FLATTENED (bucket x tier) grid and the body's `precision` field
  joins the image-dimension probe, so each replica's hot executables
  cover its (bucket, tier) slice.

  Load spill + shedding. Affinity yields when the preferred replica
  already has `fleet.spill_in_flight` requests in flight (default: one
  full batch) — below that bound affinity keeps executables hot, above
  it spreading wins. When EVERY healthy replica is at
  `fleet.max_in_flight`, the request is shed with a structured 503
  (`overloaded`) instead of queuing unboundedly at the front; no ready
  replica at all is a 503 `unavailable`. Shedding is the router-side
  face of the engine's queue backpressure: the per-replica in-flight
  caps bound what a replica's bounded queue would otherwise absorb.

  Failover replay. Engine requests are pure functions of their payload,
  so replaying one is idempotent by construction. A transport error
  (crashed replica: connection refused/reset), a proxy timeout (wedged
  replica), or a replica-side 5xx replays the request on the next
  healthy sibling, up to `fleet.failover_retries` times; transport
  failures also poke the supervisor so eviction doesn't wait out a full
  poll period. A request that exhausts its candidates gets a structured
  502 — every admitted request resolves to a response or a structured
  error, never silence.

  Session affinity (the one deliberate exception to statelessness).
  `POST /v1/flow/stream` frames (serve/session.py) are pinned: a sticky
  session -> replica map routes every frame of a session to the replica
  holding its cached previous frame (new sessions fall back to the
  bucket-affinity ladder, probing the body's "frame" image, and are
  pinned where their first frame lands). Sticky steps do NOT failover —
  a sibling has no cached frame, so replaying there would silently
  re-prime mid-stream. Instead, a lost pinned replica (transport error
  or 5xx) demotes to a structured 410 `session_lost` the client
  re-primes from: requests stay pure at the fleet level, there is no
  cross-replica session-state migration. The sticky map is bounded
  (serve.session.max_sessions x fleet size, LRU) and TTL-aged like the
  replica stores it mirrors; `fleet_session_*` counters surface the
  whole axis.
"""

from __future__ import annotations

import base64
import http.client
import itertools
import json
import os
import struct
import threading
import time
from collections import OrderedDict, defaultdict
from typing import Callable

from ..core.config import ExperimentConfig
from ..obs import trace as obs_trace
from ..obs.export import (LatencyHistogram, render_prometheus, slo_state,
                          validate_slo)
from ..obs.registry import merge_stats_blocks
from .buckets import next_smaller_bucket, pick_bucket, resolve_buckets
from .quant import resolve_precisions

#: load-trend window: how many FULL seconds of per-second completion
#: buckets feed fleet_load_rps / fleet_load_slope (the predictive
#: autoscaler's signal) — long enough for a least-squares slope to ride
#: out one noisy second, short enough to see a burst inside the
#: autoscaler's up_after_s sustain window
LOAD_WINDOW_S = 10

#: JPEG start-of-frame markers that carry the image dimensions (all SOF
#: variants; C4/C8/CC are huffman/arithmetic tables, not frames).
_JPEG_SOF = frozenset(range(0xC0, 0xD0)) - {0xC4, 0xC8, 0xCC}
#: JPEG markers with no length field.
_JPEG_BARE = frozenset(range(0xD0, 0xD9)) | {0x01}


def probe_image_hw(data: bytes) -> tuple[int, int] | None:
    """(H, W) from PNG/JPEG/BMP header bytes — no decoder, no cv2. None
    when the format is unknown or the header is short/torn: affinity is
    an optimization, so the caller falls back to unaffinitized routing
    and lets the replica produce the real decode error."""
    try:
        if data[:8] == b"\x89PNG\r\n\x1a\n" and len(data) >= 24:
            w, h = struct.unpack(">II", data[16:24])
            return (int(h), int(w))
        if data[:2] == b"\xff\xd8":  # JPEG: scan segments for a SOF
            i = 2
            while i + 9 < len(data):
                if data[i] != 0xFF:
                    return None  # lost sync: not a segment boundary
                marker = data[i + 1]
                if marker == 0xFF:  # fill byte
                    i += 1
                    continue
                if marker in _JPEG_BARE:
                    i += 2
                    continue
                if marker in _JPEG_SOF:
                    h, w = struct.unpack(">HH", data[i + 5:i + 9])
                    return (int(h), int(w))
                (seg_len,) = struct.unpack(">H", data[i + 2:i + 4])
                i += 2 + seg_len
            return None
        if data[:2] == b"BM" and len(data) >= 26:
            w, h = struct.unpack("<ii", data[18:26])
            return (abs(int(h)), abs(int(w)))  # h < 0 = top-down rows
    except (struct.error, IndexError):
        return None
    return None


class Router:
    """See module docstring. Thread-safe: every HTTP handler thread
    routes through one Router; the fleet's monitor mutates replica
    state under the fleet lock and the router reads immutable
    (idx, port) snapshots."""

    def __init__(self, cfg: ExperimentConfig, fleet):
        fc = cfg.serve.fleet
        self.cfg = cfg
        self.fleet = fleet
        self.buckets = resolve_buckets(cfg)
        # precision tiers fold into the affinity map: the ladder the
        # replicas keep hot is (bucket, tier) pairs, so the router
        # spreads that FLATTENED ladder across the fleet — bucket b at
        # tier t concentrates on replica (b_idx * n_tiers + t_idx) % N
        self.tiers = resolve_precisions(cfg)
        if float(cfg.obs.slo_latency_ms) > 0:
            validate_slo(cfg.obs)  # an unmeasurable SLO target fails HERE
        self.retries = max(int(fc.failover_retries), 0)
        self.max_in_flight = max(int(fc.max_in_flight), 1)
        # spill is a preference bound INSIDE the hard cap — past the cap
        # the only correct answer is shedding, never admission
        self.spill = min(int(fc.spill_in_flight)
                         or max(int(cfg.serve.max_batch), 1),
                         self.max_in_flight)
        self.timeout_s = max(float(fc.proxy_timeout_s), 0.1)
        self.draining = False
        # called with the cumulative response count after each success —
        # the fleet heartbeat's beat() (run_fleet wires it)
        self.beat_hook: Callable[[int], None] | None = None
        # incident plane (obs/incident.py): run_fleet installs the
        # supervisor-process recorder here; stats() raises the
        # fleet-SLO exhaustion trigger and carries the incident_*
        # block to /healthz, /metrics and the fleet heartbeat
        self.incidents = None
        # the autoscaler's fleet_autoscale_* block (run_fleet wires
        # Autoscaler.stats when fleet.autoscale): merged into stats()
        # so scale counters ride /healthz, /metrics and the heartbeat
        # exactly like every other fleet_* counter
        self.autoscale_stats: Callable[[], dict] | None = None
        # brownout plane (serve/degrade.py; run_fleet wires both when
        # serve.degrade.enabled): degrade_level is the live level the
        # router folds into every routing decision, degrade_stats the
        # controller's degrade_* block merged into stats()
        self.degrade_stats: Callable[[], dict] | None = None
        self.degrade_level: Callable[[], int] | None = None
        self._lock = threading.Lock()
        self._in_flight: dict[int, int] = defaultdict(int)
        self._routed: dict[int, int] = defaultdict(int)
        # per-replica routed counts folded here when a slot retires
        # (autoscale scale-down, Fleet.on_retired -> retire_slot): the
        # per-index map stays bounded by the ACTIVE pool however many
        # scale events a long-lived fleet sees, and the total stays
        # monotonic
        self._routed_retired = 0
        # per-second completion buckets (unix second -> 200s landed that
        # second), the load-trend source for fleet_load_rps /
        # fleet_load_slope — the predictive autoscaler's slope signal
        # (serve/autoscale.py, fleet.autoscale_up_slope). Bounded: pruned
        # past LOAD_WINDOW_S on every insert.
        self._done_per_s: dict[int, int] = defaultdict(int)
        self._requests = 0
        self._responses = 0
        self._errors = 0
        self._failovers = 0   # replays that ultimately produced a reply
        self._retries = 0     # individual replay attempts
        self._shed = 0        # 503 overloaded (all replicas saturated)
        self._unavailable = 0  # 503 no ready replica at all
        # requests the FLEET failed (shed + unavailable + exhausted
        # failover): the SLO error budget's failure count — relayed
        # client 4xx deliberately excluded
        self._server_errors = 0
        self._rr = itertools.count()  # unaffinitized round-robin cursor
        # front-door latency histogram (obs/export.py fixed buckets):
        # admission -> reply, including failover replays — the number a
        # client actually experiences, distinct from the per-replica
        # engine histograms /metrics aggregates alongside it
        self._hist = LatencyHistogram()
        # X-Request-Id sequence: globally unique enough (router pid +
        # counter) to chain one request's spans across processes in the
        # merged fleet trace
        self._rid_seq = itertools.count(1)
        # sticky session -> (replica idx, last monotonic) map
        # (serve/session.py): bounded LRU mirroring the replicas' own
        # session stores — per-replica capacity x CURRENT fleet size
        # (recomputed per put: the autoscaler changes the pool), aged by
        # the same TTL, so the front can never pin more sessions than
        # the fleet can hold
        self._sticky: OrderedDict[str, tuple[int, float]] = OrderedDict()
        self._session_cap = max(int(cfg.serve.session.max_sessions), 1)
        self._sticky_ttl = float(cfg.serve.session.ttl_s)
        self._session_primes = 0   # sessions pinned (first frame routed)
        self._session_steps = 0    # frames routed via the sticky map
        self._sessions_lost = 0    # pinned replica gone -> 410 session_lost
        self._session_evicted = 0  # sticky-map LRU drops
        self._session_expired = 0  # sticky-map TTL drops
        # deadline/brownout admission ledger: budgets that expired
        # before any replica was tried (the caller's fault, counted
        # apart from fleet_server_errors), and low-priority requests
        # shed at L3 (deliberate brownout refusals, counted apart from
        # fleet_shed so saturation sheds stay a clean overload signal)
        self._deadline_admission_expired = 0
        self._degrade_shed_low = 0

    # ---------------------------------------------------------- routing
    def _preferred(self, key) -> int:
        """Affinity replica for a (bucket, tier) key: the flattened
        (bucket x tier) ladder index modulo the CURRENT fleet size, so
        each replica's hot AOT executables cover its slice of the full
        ladder. With one tier this reduces to the pre-tier bucket map.
        Under autoscale the modulus tracks the live pool and slot
        indices are monotonic (a retired index is never reused), so the
        preferred index may not name a live slot — _acquire's
        ring-distance sort over the READY set still concentrates each
        key on one deterministic replica; affinity is an optimization,
        never a correctness dependency."""
        bucket, tier = key if key is not None else (None, None)
        if bucket is None or bucket not in self.buckets:
            # probe failed / unknown shape: round-robin, not replica 0 —
            # an unprobeable workload must still spread across the fleet
            return next(self._rr) % max(self.fleet.size, 1)
        t_idx = self.tiers.index(tier) if tier in self.tiers else 0
        flat = self.buckets.index(bucket) * len(self.tiers) + t_idx
        return flat % max(self.fleet.size, 1)

    def _acquire(self, key, tried: set):
        """Reserve an in-flight slot on the best candidate for a
        (bucket, tier) key. Returns (replica_snapshot, None) or
        (None, reason) where reason is 'unavailable' (no ready
        replica), 'overloaded' (all ready ones saturated), or
        'exhausted' (every ready replica already tried — failover has
        nowhere left to replay)."""
        ready = self.fleet.ready_replicas()
        if not ready:
            return None, "unavailable"
        cand = [r for r in ready if r.idx not in tried]
        if not cand:
            return None, "exhausted"
        pref = self._preferred(key)
        n = max(self.fleet.size, 1)
        cand.sort(key=lambda r: (r.idx - pref) % n)
        with self._lock:
            pick = None
            for r in cand:  # affinity order while under the spill bound
                if self._in_flight[r.idx] < self.spill:
                    pick = r
                    break
            if pick is None:  # all past spill: least-loaded wins
                pick = min(cand, key=lambda r: self._in_flight[r.idx])
                if self._in_flight[pick.idx] >= self.max_in_flight:
                    return None, "overloaded"
            self._in_flight[pick.idx] += 1
            self._routed[pick.idx] += 1
        return pick, None

    def _release(self, idx: int) -> None:
        with self._lock:
            if idx in self._in_flight:  # retire_slot may have aged it out
                self._in_flight[idx] -= 1

    def _proxy(self, replica, path: str, body: bytes, ctype: str,
               request_id: str | None = None, method: str = "POST",
               deadline: float | None = None, level: int = 0):
        conn = http.client.HTTPConnection(self.fleet.host, replica.port,
                                          timeout=self.timeout_s)
        headers = {"Content-Type": ctype or "application/json"}
        if request_id is not None:
            # the replica stamps this id on its engine spans: the merged
            # fleet trace chains router -> replica per request
            headers["X-Request-Id"] = request_id
        if deadline is not None:
            # propagate the REMAINING budget (not the original): queue
            # and failover time already spent at the front is gone —
            # the replica's enqueue/flush/wait gates see the truth
            rem_ms = max((deadline - time.monotonic()) * 1e3, 0.0)
            headers["X-Deadline-Ms"] = f"{rem_ms:.3f}"
        if level > 0:
            # the live brownout level rides per-request: the replica
            # folds it at submit (tier/bucket downgrade), keeping every
            # degradation decision on the pre-warmed lattice
            headers["X-Degrade-Level"] = str(int(level))
        try:
            conn.request(method, path, body, headers)
            resp = conn.getresponse()
            return (resp.status, resp.read(),
                    resp.getheader("Content-Type") or "application/json")
        finally:
            conn.close()

    # ---------------------------------------------------- sticky sessions
    def _sticky_get(self, sid: str) -> int | None:
        """The session's pinned replica index, refreshing its LRU/TTL
        standing; None when unpinned (or aged out — counted)."""
        now = time.monotonic()
        with self._lock:
            entry = self._sticky.get(sid)
            if entry is None:
                return None
            idx, last = entry
            if self._sticky_ttl > 0 and now - last > self._sticky_ttl:
                # the replica's own store expired it too (same TTL):
                # route fresh, let the replica answer with its tombstone
                del self._sticky[sid]
                self._session_expired += 1
                return None
            self._sticky[sid] = (idx, now)
            self._sticky.move_to_end(sid)
            return idx

    def _sticky_put(self, sid: str, idx: int) -> None:
        # cap from the CURRENT pool size — a lock-free cached counter
        # on the fleet, read before our lock only to keep the critical
        # section minimal (no lock-ordering concern either way)
        cap = self._session_cap * max(self.fleet.size, 1)
        with self._lock:
            fresh = sid not in self._sticky
            self._sticky[sid] = (idx, time.monotonic())
            self._sticky.move_to_end(sid)
            if fresh:
                self._session_primes += 1
            while len(self._sticky) > cap:
                self._sticky.popitem(last=False)
                self._session_evicted += 1

    def _sticky_drop(self, sid: str) -> None:
        with self._lock:
            self._sticky.pop(sid, None)

    @staticmethod
    def _is_stream(path: str) -> bool:
        return path.rstrip("/").endswith("/stream")

    @staticmethod
    def _body_json(body: bytes) -> dict | None:
        try:
            req = json.loads(body)
        except Exception:  # noqa: BLE001 - the replica owns the 400
            return None
        return req if isinstance(req, dict) else None

    def _key_from(self, req: dict | None, image_field: str = "prev",
                  level: int = 0):
        """Best-effort affinity (bucket, tier) from a parsed body:
        header-probe the image's dimensions without decoding it, and
        read the declared `precision` (an unknown tier routes as the
        default — the replica produces the structured 400, not the
        front). The live brownout level folds in the SAME downgrades the
        replica engine will apply (L1+: default tier -> cheapest; L2+:
        one bucket down the ladder), so affinity keeps pointing at the
        replica that holds the degraded executable hot."""
        if req is None:
            return None
        bucket = None
        tier = self.tiers[0]
        try:
            p = req.get("precision")
            if p in self.tiers:
                tier = p
            elif level >= 1 and len(self.tiers) > 1:
                tier = self.tiers[-1]  # mirror engine._resolve_tier
            img_b64 = req.get(image_field, "")
            if img_b64:
                # the first ~KB of image bytes holds every header we
                # parse; 4096 is 4-aligned, so a truncated prefix still
                # decodes
                raw = base64.b64decode(img_b64[:4096])
                hw = probe_image_hw(raw)
                if hw:
                    bucket = pick_bucket(hw, self.buckets)
                    if level >= 2:
                        bucket = next_smaller_bucket(bucket, self.buckets)
        except Exception:  # noqa: BLE001 - affinity is best-effort
            return None
        return (bucket, tier) if bucket is not None else None

    def _level(self) -> int:
        """The live brownout level (0 with no controller wired)."""
        hook = self.degrade_level
        if hook is None:
            return 0
        try:
            return max(int(hook()), 0)
        except Exception:  # noqa: BLE001 - degrade never kills routing
            return 0

    @staticmethod
    def _request_meta(req: dict | None, headers,
                      t0: float) -> tuple[float | None, str]:
        """(absolute monotonic deadline | None, priority) from the
        request's headers/body: `X-Deadline-Ms` (header wins) or body
        `deadline_ms` = the caller's REMAINING budget in ms;
        `X-Priority` or body `priority` in {default, low}. Malformed
        values raise ValueError — admission answers 400, not "ignored".
        """
        raw = None
        if headers is not None:
            raw = headers.get("X-Deadline-Ms")
        if raw is None and req is not None:
            raw = req.get("deadline_ms")
        deadline = None
        if raw is not None:
            try:
                deadline = t0 + float(raw) / 1e3
            except (TypeError, ValueError):
                raise ValueError(f"deadline_ms must be a number, "
                                 f"got {raw!r}")
        prio = None
        if headers is not None:
            prio = headers.get("X-Priority")
        if prio is None and req is not None:
            prio = req.get("priority")
        if prio is None:
            prio = "default"
        if prio not in ("default", "low"):
            raise ValueError(f"priority must be default|low, got {prio!r}")
        return deadline, prio

    def route_key(self, body: bytes):
        """Best-effort affinity (bucket, tier) for a /v1/flow body (the
        pre-session entry point; _route parses once and calls _key_from
        directly)."""
        return self._key_from(self._body_json(body))

    def handle_flow(self, path: str, body: bytes, ctype: str,
                    headers=None) -> tuple[int, bytes, str]:
        """Route one POST /v1/flow or /v1/flow/stream: returns (status,
        payload, ctype) — always; a request admitted here cannot be
        silently dropped. Stream frames with a pinned session route
        sticky (no failover — see _route_pinned); everything else walks
        the affinity ladder with failover replay.
        Every admitted request gets an X-Request-Id (router pid + seq)
        stamped downstream, a `route` span on the router's tracer, and
        a front-door latency observation on success. `headers` (the
        inbound request headers, when the frontend passes them) carries
        the deadline/priority plane: X-Deadline-Ms and X-Priority."""
        rid = f"r{os.getpid():x}-{next(self._rid_seq)}"
        t0 = time.monotonic()
        with self._lock:
            self._requests += 1
        with obs_trace.span("route", request_id=rid) as span:
            status, payload, rtype = self._route(path, body, ctype, rid,
                                                 t0, span, headers)
        return status, payload, rtype

    def _route(self, path: str, body: bytes, ctype: str, rid: str,
               t0: float, span, headers=None) -> tuple[int, bytes, str]:
        req = self._body_json(body)
        try:
            deadline, priority = self._request_meta(req, headers, t0)
        except ValueError as e:
            with self._lock:
                self._errors += 1  # client error: no SLO budget burned
            span.set(outcome="bad_request")
            return (400, json.dumps({"error": "bad_request",
                                     "message": str(e),
                                     "request_id": rid}).encode(),
                    "application/json")
        # admission gates, BEFORE any replica slot is considered: an
        # already-expired budget fails fast (the caller abandoned the
        # reply), and at L3 the brownout controller sheds low-priority
        # work so remaining capacity serves the default class
        if deadline is not None and deadline <= time.monotonic():
            with self._lock:
                self._errors += 1
                self._deadline_admission_expired += 1
            span.set(outcome="deadline_exceeded")
            return (504, json.dumps({
                "error": "deadline_exceeded",
                "message": "deadline expired at admission",
                "request_id": rid}).encode(), "application/json")
        level = self._level()
        if level >= 3 and priority == "low":
            with self._lock:
                self._errors += 1
                self._server_errors += 1
                self._degrade_shed_low += 1
            span.set(outcome="shed_low_priority")
            return (503, json.dumps({
                "error": "shed_low_priority",
                "message": "brownout L3: low-priority requests are shed "
                           "— retry later or raise priority",
                "request_id": rid}).encode(), "application/json")
        sid = None
        if self._is_stream(path) and req is not None:
            s = req.get("session")
            if isinstance(s, str) and s:
                sid = s
                pinned = self._sticky_get(sid)
                if pinned is not None:
                    # a pinned session's cached frame lives on exactly
                    # one replica: route there or demote to session_lost
                    # — never replay on a sibling (it has no state)
                    return self._route_pinned(path, body, ctype, rid, t0,
                                              span, sid, pinned,
                                              deadline, level)
        key = self._key_from(req, "frame" if sid is not None else "prev",
                             level=level)
        tried: set[int] = set()
        last_error = None
        for attempt in range(self.retries + 1):
            if deadline is not None and deadline <= time.monotonic():
                # the budget died between attempts: stop burning
                # sibling replicas on a reply nobody is waiting for
                with self._lock:
                    self._errors += 1
                    self._deadline_admission_expired += 1
                span.set(outcome="deadline_exceeded", attempts=attempt)
                return (504, json.dumps({
                    "error": "deadline_exceeded",
                    "message": "deadline expired during failover",
                    "request_id": rid}).encode(), "application/json")
            replica, reason = self._acquire(key, tried)
            if replica is None:
                if reason == "exhausted":
                    break  # fall through to the structured 502
                with self._lock:
                    self._errors += 1
                    self._server_errors += 1
                    if reason == "overloaded":
                        self._shed += 1
                    else:
                        self._unavailable += 1
                span.set(outcome=reason)
                msg = ("every replica is saturated — retry later"
                       if reason == "overloaded"
                       else "no healthy replica available")
                return (503,
                        json.dumps({"error": reason, "message": msg}).encode(),
                        "application/json")
            try:
                status, payload, rtype = self._proxy(replica, path, body,
                                                     ctype, request_id=rid,
                                                     deadline=deadline,
                                                     level=level)
            except Exception as e:  # noqa: BLE001 - transport = failover
                self._release(replica.idx)
                last_error = f"{type(e).__name__}: {e}"
                tried.add(replica.idx)
                with self._lock:
                    self._retries += 1
                # a dead/wedged replica shouldn't wait out a poll period
                self.fleet.note_failure(replica.idx)
                continue
            self._release(replica.idx)
            if (status == 504 and b"deadline_exceeded" in payload):
                # the CALLER's budget died on the replica — relaying is
                # correct and replaying on a sibling would waste its
                # slot on the same expired budget; not a replica fault
                with self._lock:
                    self._errors += 1
                span.set(replica=replica.idx, status=status,
                         outcome="deadline_exceeded", attempts=attempt + 1)
                return status, payload, rtype
            if status >= 500:  # replica-level failure: replay on a sibling
                last_error = payload.decode("utf-8", "replace")[:200]
                tried.add(replica.idx)
                with self._lock:
                    self._retries += 1
                self.fleet.note_failure(replica.idx)
                continue
            with self._lock:
                if attempt > 0:
                    self._failovers += 1
                if status < 400:
                    self._responses += 1
                    total = self._responses
                    self._note_done()
                else:
                    self._errors += 1  # structured client error, relayed
                    total = None
            if status < 400:
                self._hist.observe(time.monotonic() - t0)
            if sid is not None and (status < 400 or status == 410):
                # pin the session where its frame actually landed (410
                # included: the session's tombstone lives THERE, so the
                # client's re-prime must return to the same replica to
                # count as a resume). A plain 4xx primed nothing — do
                # not pin an id the replica rejected
                self._sticky_put(sid, replica.idx)
            span.set(replica=replica.idx, status=status,
                     attempts=attempt + 1)
            hook = self.beat_hook
            if total is not None and hook is not None:
                try:
                    hook(total)
                except Exception:  # noqa: BLE001 - obs never kills routing
                    pass
            return status, payload, rtype
        with self._lock:
            self._errors += 1
            self._server_errors += 1
        span.set(outcome="replica_failed", attempts=max(len(tried), 1))
        return (502, json.dumps({
            "error": "replica_failed",
            "message": f"request failed on {max(len(tried), 1)} replica(s); "
                       f"last: {last_error}",
            "attempts": max(len(tried), 1),
        }).encode(), "application/json")

    def _route_pinned(self, path: str, body: bytes, ctype: str, rid: str,
                      t0: float, span, sid: str, pinned: int,
                      deadline: float | None = None,
                      level: int = 0) -> tuple[int, bytes, str]:
        """One attempt against a session's pinned replica — no failover
        (a sibling has no cached frame; replaying there would silently
        re-prime mid-stream). A gone/failing pinned replica demotes to a
        structured 410 `session_lost` the client re-primes from.
        The deadline and brownout level ride through like the unpinned
        path (the replica folds L1's tier downgrade; L2's bucket
        downgrade deliberately does not apply to streaming steps —
        engine.submit_next documents why)."""
        replica = next((r for r in self.fleet.ready_replicas()
                        if r.idx == pinned), None)
        if replica is None:
            return self._session_lost_reply(sid, span,
                                            "replica not ready")
        with self._lock:
            if self._in_flight[replica.idx] >= self.max_in_flight:
                # the hard cap still holds for pinned traffic: shedding
                # keeps the session alive (retry-able), unlike demotion
                self._errors += 1
                self._server_errors += 1
                self._shed += 1
                span.set(outcome="overloaded", session=sid)
                return (503, json.dumps(
                    {"error": "overloaded", "session": sid,
                     "message": "the session's replica is saturated — "
                                "retry later"}).encode(),
                    "application/json")
            self._in_flight[replica.idx] += 1
            self._routed[replica.idx] += 1
        try:
            status, payload, rtype = self._proxy(replica, path, body,
                                                 ctype, request_id=rid,
                                                 deadline=deadline,
                                                 level=level)
        except Exception as e:  # noqa: BLE001 - transport = session lost
            self._release(replica.idx)
            self.fleet.note_failure(replica.idx)
            return self._session_lost_reply(sid, span,
                                            f"{type(e).__name__}: {e}")
        self._release(replica.idx)
        if status == 504 and b"deadline_exceeded" in payload:
            # the caller's budget, not the replica's health — relay;
            # the session (and its pin) stays alive for the next frame
            with self._lock:
                self._errors += 1
            span.set(replica=replica.idx, status=status, session=sid,
                     outcome="deadline_exceeded", attempts=1)
            return status, payload, rtype
        if status >= 500:
            self.fleet.note_failure(replica.idx)
            return self._session_lost_reply(
                sid, span, payload.decode("utf-8", "replace")[:200])
        with self._lock:
            if status == 200:
                # only a frame that produced flow is a STEP — a 202
                # re-prime (rebucket) or a relayed 4xx must not drift
                # this above the sum of replica serve_sessions_steps
                self._session_steps += 1
            if status < 400:
                self._responses += 1
                total = self._responses
                self._note_done()
            else:
                self._errors += 1  # structured client error, relayed
                total = None
        if status < 400:
            self._hist.observe(time.monotonic() - t0)
        span.set(replica=replica.idx, status=status, session=sid,
                 attempts=1)
        hook = self.beat_hook
        if total is not None and hook is not None:
            try:
                hook(total)
            except Exception:  # noqa: BLE001 - obs never kills routing
                pass
        return status, payload, rtype

    def _session_lost_reply(self, sid: str, span,
                            detail: str) -> tuple[int, bytes, str]:
        self._sticky_drop(sid)
        with self._lock:
            self._errors += 1
            self._server_errors += 1
            self._sessions_lost += 1
        span.set(outcome="session_lost", session=sid)
        return (410, json.dumps({
            "error": "session_lost", "session": sid,
            "message": f"the session's replica is gone ({detail}); "
                       "resend the frame to re-prime",
        }).encode(), "application/json")

    def handle_session_delete(self, path: str) -> tuple[int, bytes, str]:
        """Route DELETE /v1/flow/stream/<id>: proxy to the pinned
        replica (dropping the sticky entry either way). An unpinned id
        is a structured 404; a dead pinned replica still counts as
        deleted — its state died with it."""
        # the id is the FULL suffix after the stream prefix (the same
        # parse server.py uses, so the two frontends cannot disagree;
        # slash-bearing ids are rejected at POST, this is the backstop)
        sid = ""
        for prefix in ("/v1/flow/stream/", "/flow/stream/"):
            if path.startswith(prefix):
                sid = path[len(prefix):]
                break
        if not sid:  # bare /v1/flow/stream or an unknown path shape
            return (404, json.dumps({"error": "not_found",
                                     "message": path}).encode(),
                    "application/json")
        pinned = self._sticky_get(sid)
        if pinned is None:
            return (404, json.dumps({"error": "session_unknown",
                                     "session": sid}).encode(),
                    "application/json")
        self._sticky_drop(sid)
        replica = next((r for r in self.fleet.ready_replicas()
                        if r.idx == pinned), None)
        if replica is not None:
            try:
                return self._proxy(replica, path, b"", "application/json",
                                   method="DELETE")
            except Exception:  # noqa: BLE001 - replica gone: state gone too
                self.fleet.note_failure(replica.idx)
        return (200, json.dumps({"session": sid, "deleted": True,
                                 "note": "replica gone; session state "
                                         "died with it"}).encode(),
                "application/json")

    # --------------------------------------------------- scale-down aging
    def in_flight_of(self, idx: int) -> int:
        """Requests this router currently has proxied to one replica —
        the drain gate `Fleet.retire_one` waits out before SIGTERMing a
        retiring slot."""
        with self._lock:
            return self._in_flight.get(idx, 0)

    def retire_slot(self, idx: int) -> None:
        """Age a retired replica slot out of the per-index maps
        (`Fleet.on_retired` — called AFTER the replica is drained,
        stopped and reaped). The slot's routed count folds into the
        retained `fleet_routed_retired` total (bounded map, monotonic
        total); its in-flight entry — zero after the drain — is
        dropped. Sticky sessions pinned to the slot deliberately KEEP
        their entries: the next frame must demote to the structured 410
        `session_lost` (PR 10's contract — silently dropping the pin
        would re-prime mid-stream with no signal to the client), which
        drops the entry; abandoned pins age out via the same TTL the
        replica stores use."""
        with self._lock:
            self._in_flight.pop(idx, None)
            self._routed_retired += self._routed.pop(idx, 0)

    # ------------------------------------------------------------ stats
    def _note_done(self) -> None:
        """Bucket one completed (status < 400) request into the current
        unix second and prune the window. Caller holds self._lock."""
        s = int(time.time())
        self._done_per_s[s] += 1
        if len(self._done_per_s) > LOAD_WINDOW_S + 2:
            cutoff = s - LOAD_WINDOW_S - 1
            for k in [k for k in self._done_per_s if k < cutoff]:
                del self._done_per_s[k]

    def _load_trend(self, now: float) -> tuple[float, float]:
        """(recent requests/s, req/s-per-second slope) over the last
        LOAD_WINDOW_S FULL seconds of completion buckets. The current
        partial second is excluded (its count is still rising and would
        bias the slope down); absent seconds are zero traffic, so the
        window zero-fills — a burst arriving after idle slopes steeply,
        which is exactly the signal the predictive autoscaler wants.
        Caller holds self._lock."""
        end = int(now)
        ys = [float(self._done_per_s.get(s, 0))
              for s in range(end - LOAD_WINDOW_S, end)]
        n = len(ys)
        rps = sum(ys) / n
        mx = (n - 1) / 2.0
        denom = sum((i - mx) ** 2 for i in range(n))
        slope = sum((i - mx) * (y - rps) for i, y in enumerate(ys)) / denom
        return rps, slope

    def in_flight_total(self) -> int:
        with self._lock:
            return sum(self._in_flight.values())

    def stats(self) -> dict:
        """The router's half of the fleet_* counter block (the fleet
        heartbeat merges it with Fleet.stats()), including the
        front-door latency histogram and — when cfg.obs.slo_latency_ms
        is set — the fleet SLO state the error budget burns against."""
        hist = self._hist.snapshot()
        with self._lock:
            rps, slope = self._load_trend(time.time())
            out = {
                "fleet_load_rps": round(rps, 3),
                "fleet_load_slope": round(slope, 4),
                "fleet_requests": self._requests,
                "fleet_responses": self._responses,
                "fleet_errors": self._errors,
                "fleet_server_errors": self._server_errors,
                "fleet_failovers": self._failovers,
                "fleet_retries": self._retries,
                "fleet_shed": self._shed,
                "fleet_unavailable": self._unavailable,
                "fleet_in_flight": sum(self._in_flight.values()),
                "fleet_routed": {f"replica-{i}": n
                                 for i, n in sorted(self._routed.items())},
                "fleet_routed_retired": self._routed_retired,
                "fleet_draining": self.draining,
                # session-affinity axis (serve/session.py): sticky-map
                # size + the pin/step/lost ledger `tail` surfaces
                "fleet_sessions_sticky": len(self._sticky),
                "fleet_session_primes": self._session_primes,
                "fleet_session_steps": self._session_steps,
                "fleet_session_lost": self._sessions_lost,
                "fleet_session_evicted": self._session_evicted,
                "fleet_session_expired": self._session_expired,
                # deadline/brownout admission ledger (router-owned; the
                # engines' deadline_*/degrade_* stage counters arrive
                # via the replica scrape, names disjoint by design)
                "deadline_admission_expired":
                    self._deadline_admission_expired,
                "degrade_shed_low": self._degrade_shed_low,
            }
            requests, failures = self._requests, self._server_errors
        out["fleet_latency_hist"] = hist
        scaler = self.autoscale_stats
        if scaler is not None:
            try:
                out.update(scaler())
            except Exception:  # noqa: BLE001 - obs never kills routing
                pass
        degr = self.degrade_stats
        if degr is not None:
            try:
                out.update(degr())
            except Exception:  # noqa: BLE001 - obs never kills routing
                pass
        if float(self.cfg.obs.slo_latency_ms) > 0:
            # the router's own histogram IS the burn source: it sees
            # every admitted request, including ones no replica answered
            out["fleet_slo"] = slo_state(hist, requests, failures,
                                         self.cfg.obs.slo_latency_ms,
                                         self.cfg.obs.slo_error_budget)
        rec = self.incidents
        if rec is not None:
            slo = out.get("fleet_slo")
            if slo and slo.get("exhausted"):
                # the router's budget is the FLEET's contract — its
                # exhaustion is a supervisor-level incident (dedup
                # window absorbs the heartbeat-cadence re-check)
                rec.record("slo_exhausted", "critical",
                           trigger={"slo": slo})
            out.update(rec.stats())
        return out

    # ---------------------------------------------------------- /metrics
    def scrape_replicas(self, timeout_s: float = 2.0) -> dict:
        """Fleet-aggregated serve_* block: GET /healthz on every ready
        replica (concurrently — one wedged-but-still-ready replica must
        cost at most ONE timeout, not one per scrape position) and
        merge by each key's DECLARED kind (obs/registry.py, the schema
        owner): additive counters sum, per-tier maps sum by key,
        high-water marks take the max, per-replica gauges/bools/derived
        values are dropped, and the latency histograms merge EXACTLY
        (fixed shared buckets, obs/export.py) so the fleet-wide bucket
        counts equal the sum of the replicas' at scrape time. A counter
        registered tomorrow joins this scrape with no edit here — the
        skip/max frozensets + suffix heuristics this replaces needed a
        hand patch in four of the last six PRs. Replicas that fail the
        scrape are skipped and counted."""
        def fetch(replica):
            conn = http.client.HTTPConnection(
                self.fleet.host, replica.port,
                timeout=max(float(timeout_s), 0.1))
            try:
                conn.request("GET", "/healthz")
                return json.loads(conn.getresponse().read())
            finally:
                conn.close()

        replicas = self.fleet.ready_replicas()
        results: list[dict | None] = []
        if replicas:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=len(replicas)) as pool:
                futures = [pool.submit(fetch, r) for r in replicas]
                for fut in futures:
                    try:
                        results.append(fut.result())
                    except Exception:  # noqa: BLE001 - sick replica: skip
                        results.append(None)
        blocks = [{k: v for k, v in stats.items()
                   if k.startswith(("serve_", "deadline_", "degrade_"))}
                  for stats in results if stats is not None]
        out = merge_stats_blocks(blocks)
        out["serve_replicas_scraped"] = len(blocks)
        out["serve_replicas_scrape_failed"] = len(results) - len(blocks)
        return out

    def metrics_text(self) -> str:
        """GET /metrics body: supervisor + router + fleet-aggregated
        replica blocks in Prometheus text format."""
        return render_prometheus({**self.fleet.stats(), **self.stats(),
                                  **self.scrape_replicas()})


def build_router_server(cfg: ExperimentConfig, router: Router):
    """The fleet's front HTTP server (same stdlib stack and API shape as
    `serve/server.py`), bound to cfg.serve.host:port; returned unstarted
    so callers drive serve_forever themselves."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Server(ThreadingHTTPServer):
        daemon_threads = True

        def handle_error(self, request, client_address):
            import sys

            exc = sys.exc_info()[1]
            if isinstance(exc, (ConnectionError, TimeoutError)):
                return
            super().handle_error(request, client_address)

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # obs owns visibility
            pass

        def _reply(self, status: int, body: bytes,
                   ctype: str = "application/json") -> None:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, status: int, payload: dict) -> None:
            self._reply(status, json.dumps(payload).encode())

        def do_GET(self):  # noqa: N802
            if self.path in ("/healthz", "/stats"):
                payload = {**router.fleet.stats(), **router.stats(),
                           "replicas": router.fleet.describe(),
                           "time": time.time()}
                ok = payload.get("fleet_ready", 0) > 0 and not router.draining
                self._reply(200 if ok else 503,
                            json.dumps(payload).encode())
            elif self.path == "/metrics":
                from ..obs.export import PROM_CONTENT_TYPE

                # fleet-aggregated Prometheus scrape: fleet_* + router
                # counters + the replicas' serve_* blocks merged live
                # (histogram bucket counts = exact sum of the replicas')
                self._reply(200, router.metrics_text().encode(),
                            PROM_CONTENT_TYPE)
            else:
                self._reply_json(404, {"error": "not_found",
                                       "message": self.path})

        def do_POST(self):  # noqa: N802
            if self.path not in ("/v1/flow", "/flow",
                                 "/v1/flow/stream", "/flow/stream"):
                self._reply_json(404, {"error": "not_found",
                                       "message": self.path})
                return
            if router.draining:
                self._reply_json(503, {"error": "draining",
                                       "message": "fleet is shutting down"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
            except (ValueError, OSError) as e:
                self._reply_json(400, {"error": "bad_request",
                                       "message": f"{type(e).__name__}: {e}"})
                return
            status, payload, ctype = router.handle_flow(
                self.path, body, self.headers.get("Content-Type", ""),
                headers=self.headers)
            self._reply(status, payload, ctype)

        def do_DELETE(self):  # noqa: N802
            if not self.path.startswith(("/v1/flow/stream/",
                                         "/flow/stream/")):
                self._reply_json(404, {"error": "not_found",
                                       "message": self.path})
                return
            if router.draining:
                self._reply_json(503, {"error": "draining",
                                       "message": "fleet is shutting down"})
                return
            status, payload, ctype = router.handle_session_delete(self.path)
            self._reply(status, payload, ctype)

    return Server((cfg.serve.host, cfg.serve.port), Handler)
