"""Shape bucketing: arbitrary native inputs -> a fixed executable set.

A jit/AOT executable is specialized to its input avals, so serving
arbitrary image sizes naively means one XLA compile per distinct
resolution — unbounded compile debt on a live endpoint. The ladder in
`ServeConfig.buckets` fixes the set: every request maps to one of a few
(H, W) network-input buckets, and only those executables exist (warmed
ahead of time by `warmup --serve` through the PR 1 persistent cache, so
the first request of each bucket loads instead of compiling).

Mapping protocol — deliberately the SAME resize-based protocol the
serial predict path and the eval sweep use (`train/evaluate.py
postprocess_flow`), not letterbox padding: the native image is resized
to the bucket resolution, the net runs at the bucket shape, the finest
flow is amplified/clipped/resized back to native resolution, and the
u/v vectors are rescaled by (W_native/W_bucket, H_native/H_bucket) into
native pixel units. Sharing the protocol is what makes an engine
response bit-identical to the serial path's output at the same bucket
(pinned in tests/test_serve.py) — a padding scheme would change the
numerics at every border.

Bucket choice: the smallest-area bucket that covers the native
resolution in both dimensions (no downscale in either axis), else the
largest bucket (capped upscale cost). Deterministic in (native_hw,
ladder) so identical requests always share an executable.
"""

from __future__ import annotations

import numpy as np

from ..core.config import ExperimentConfig


def resolve_buckets(cfg: ExperimentConfig) -> tuple[tuple[int, int], ...]:
    """The config's ladder, normalized: explicit `serve.buckets` (sorted
    by area then H for a stable warmup/selection order), or the single
    `data.image_size` bucket — which makes the default engine behave
    exactly like the pre-serve predict path."""
    raw = cfg.serve.buckets or (tuple(cfg.data.image_size),)
    buckets = []
    for b in raw:
        h, w = int(b[0]), int(b[1])
        if h <= 0 or w <= 0:
            raise ValueError(f"serve.buckets entry {b!r} must be positive (H, W)")
        buckets.append((h, w))
    return tuple(sorted(set(buckets), key=lambda b: (b[0] * b[1], b[0])))


def pick_bucket(native_hw: tuple[int, int],
                buckets: tuple[tuple[int, int], ...]) -> tuple[int, int]:
    """Smallest-area bucket covering `native_hw` in both axes, else the
    largest bucket in the ladder."""
    h, w = native_hw
    for bh, bw in buckets:  # sorted by area: first cover is the smallest
        if bh >= h and bw >= w:
            return (bh, bw)
    return buckets[-1]


def next_smaller_bucket(bucket: tuple[int, int],
                        buckets: tuple[tuple[int, int], ...],
                        ) -> tuple[int, int]:
    """One rung DOWN the ladder from `bucket` (brownout L2): the
    next-smaller-area bucket, or `bucket` itself when it is already the
    smallest (or off-ladder). Any bucket serves any native size — the
    resize protocol rescales flow back to native pixel units — so the
    downgrade only trades accuracy, never correctness, and the target is
    always a warmed lattice entry (never a compile)."""
    try:
        idx = buckets.index(tuple(bucket))
    except ValueError:
        return tuple(bucket)
    return buckets[idx - 1] if idx > 0 else tuple(bucket)


def prepare_frame(img_raw: np.ndarray, bucket: tuple[int, int],
                  mean) -> np.ndarray:
    """ONE decoded BGR frame -> its preprocessed half-row (H, W, 3)
    float32 at the bucket resolution: resize + the training preprocess
    (subtract BGR mean, /255 — `losses/pyramid.py preprocess`, done here
    in numpy so a corrupt input fails on the submitting thread, before
    batching). The preprocess is per-frame independent, so a network
    input pair is exactly the channel concatenation of two of these —
    the property the streaming-session cache (serve/session.py) relies
    on for bit-identical parity with the pairwise path."""
    from ..data.datasets import _resize

    m = np.asarray(mean, np.float32)
    return ((_resize(img_raw, bucket).astype(np.float32) - m)
            / np.float32(255.0))


def prepare_pair(src_raw: np.ndarray, tgt_raw: np.ndarray,
                 bucket: tuple[int, int], mean) -> np.ndarray:
    """Decoded BGR pair -> one network-input row (H, W, 6) float32 at
    the bucket resolution (two prepare_frame halves, concatenated)."""
    return np.concatenate([prepare_frame(img, bucket, mean)
                           for img in (src_raw, tgt_raw)], axis=-1)


def flow_to_native(flow: np.ndarray, cfg: ExperimentConfig,
                   bucket: tuple[int, int],
                   native_hw: tuple[int, int]) -> np.ndarray:
    """Finest scaled flow (H_b, W_b, 2) at bucket resolution -> native-
    resolution flow in native pixel units: the eval amplify/clip/resize
    protocol, then the u/v vector rescale (identical math to the serial
    predict path — bit-for-bit parity is pinned in tests)."""
    from ..train.evaluate import postprocess_flow

    bh, bw = bucket
    out = postprocess_flow(flow[None].astype(np.float32, copy=False),
                           cfg, native_hw)[0, :, :, :2]
    out[..., 0] *= native_hw[1] / bw  # u: native horizontal px
    out[..., 1] *= native_hw[0] / bh  # v: native vertical px
    return out
