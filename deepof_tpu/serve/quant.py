"""Mixed-precision serving tiers: pure params->params weight transforms.

The serve stack's speed/accuracy frontier is a FAMILY of operating
points, not one (FlowNet 2.0's ladder, Flow Gym's per-request
deployment choice — PAPERS.md); the cheapest new axis on that frontier
is weight precision. This module owns the two quantized tiers and the
protocol the whole stack (engine, warmup, router, serve_bench) shares:

  f32   — identity: the restored checkpoint's native weights.
  bf16  — every floating-point leaf cast to bfloat16: half the weight
          bytes moved per dispatch. flax modules promote params to
          their compute dtype at apply time, so activations stay f32
          and the tier is bit-stable across dispatches (same inputs ->
          same bits; pinned in tests/test_quant.py).
  int8  — weight-only quantization of conv/deconv kernels with
          per-OUTPUT-CHANNEL scales: q = round(w / scale) in [-127,127]
          with scale = amax(|w|, all axes but the last) / 127. Biases
          and norm params stay f32 (they are tiny and additive — a
          bias quantization error shifts every pixel; a weight one
          averages out over the receptive field). Dequantization
          happens INSIDE the jitted forward (`dequantize_params` in
          `engine.make_raw_forward`), so the executable's params input
          is the int8 tree (quarter weight bytes) while activations
          remain f32 — weight-only, activations untouched.

Per-output-channel (not per-tensor) scales matter because conv kernels'
channel dynamic ranges differ by orders of magnitude after training; a
single tensor scale would crush small-range channels to a handful of
int8 levels. Per-channel keeps the round-trip error of EVERY channel
bounded by its own scale/2 (pinned in tests/test_quant.py).

Both transforms are pure pytree->pytree functions of jnp ops only, so
they are `jax.eval_shape`-traceable: `warmup --serve` derives each
tier's params AVALS from an abstract init without materializing
weights, and its lowering matches the engine's by construction (same
cache key — the zero-recompile contract now spans bucket x tier).
"""

from __future__ import annotations

from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

#: The tier vocabulary, cheapest-to-serve last. ServeConfig.precisions
#: must be a subset; the config's FIRST entry is the default tier a
#: request gets when it names none.
PRECISIONS = ("f32", "bf16", "int8")

#: int8 symmetric range: round(w/scale) clipped to [-_QMAX, _QMAX].
_QMAX = 127.0


def resolve_precisions(cfg) -> tuple[str, ...]:
    """The config's serve tier ladder, validated against PRECISIONS.

    Order is preserved (the first entry is the default tier), duplicates
    are rejected rather than deduped — a config naming a tier twice is a
    typo, not a preference.
    """
    tiers = tuple(cfg.serve.precisions) or ("f32",)
    seen = set()
    for t in tiers:
        if t not in PRECISIONS:
            raise ValueError(
                f"serve.precisions entry {t!r} unknown; valid tiers: "
                f"{PRECISIONS}")
        if t in seen:
            raise ValueError(f"serve.precisions names {t!r} twice: {tiers}")
        seen.add(t)
    return tiers


def _is_conv_kernel(name: str, leaf) -> bool:
    """Quantization targets: multi-dim 'kernel' leaves (nn.Conv /
    nn.ConvTranspose both store (spatial..., in, OUT) with output
    channels LAST). Biases, norm scales/offsets, and scalar params pass
    through untouched."""
    ndim = getattr(leaf, "ndim", len(getattr(leaf, "shape", ())))
    return name == "kernel" and ndim >= 2


def _quantize_kernel(w) -> dict:
    """One conv kernel -> {"q": int8, "scale": f32[out_channels]}.

    scale = per-output-channel amax / 127 (1.0 where a channel is all
    zero, so dequantize is exact there); round-trip error is bounded by
    scale/2 per channel.
    """
    w = jnp.asarray(w)
    reduce_axes = tuple(range(w.ndim - 1))
    amax = jnp.max(jnp.abs(w), axis=reduce_axes)
    scale = jnp.where(amax > 0, amax / _QMAX, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return {"q": q, "scale": scale}


def _is_quantized_leaf(node) -> bool:
    return (isinstance(node, Mapping) and set(node.keys()) == {"q", "scale"}
            and getattr(node.get("q"), "dtype", None) == jnp.int8)


def _cast_bf16(leaf):
    arr = jnp.asarray(leaf)
    if jnp.issubdtype(arr.dtype, jnp.floating):
        return arr.astype(jnp.bfloat16)
    return arr


def quantize_params(params, tier: str):
    """Pure params->params transform for one tier (see module doc).

    Works on real arrays AND ShapeDtypeStructs-under-eval_shape (warmup
    derives tier avals abstractly). The returned tree is what the tier's
    AOT executable takes as its params input.
    """
    if tier == "f32":
        return params
    if tier == "bf16":
        return jax.tree_util.tree_map(_cast_bf16, params)
    if tier != "int8":
        raise ValueError(f"unknown precision tier {tier!r}; valid: "
                         f"{PRECISIONS}")

    def rec(node):
        if isinstance(node, Mapping):
            return {k: (_quantize_kernel(v) if _is_conv_kernel(k, v)
                        else rec(v))
                    for k, v in node.items()}
        return node
    return rec(params)


def dequantize_params(params):
    """Inverse of the int8 transform, applied INSIDE the jitted forward
    (traced, so XLA fuses the dequantize into the weight load of each
    conv): {"q", "scale"} leaves become f32 kernels; every other leaf —
    f32 or bf16 — passes through for flax's own dtype promotion. A
    no-op (structurally identical tree, zero inserted ops) on f32/bf16
    trees, so the f32 path's HLO is unchanged from the pre-tier stack.
    """
    def rec(node):
        if _is_quantized_leaf(node):
            return (node["q"].astype(jnp.float32) * node["scale"])
        if isinstance(node, Mapping):
            return {k: rec(v) for k, v in node.items()}
        return node
    return rec(params)


def int8_roundtrip_max_error(params) -> float:
    """max over quantized kernels of (|w - dequant(quant(w))| / scale):
    the per-channel error in SCALE units — the round-trip contract says
    this never exceeds 0.5 (+ float eps). Test/diagnostic helper."""
    quant = quantize_params(params, "int8")
    worst = 0.0

    def rec(orig, q) -> None:
        nonlocal worst
        if _is_quantized_leaf(q):
            dq = np.asarray(q["q"], np.float32) * np.asarray(q["scale"])
            err = np.abs(np.asarray(orig, np.float32) - dq)
            worst = max(worst, float(np.max(err / np.asarray(q["scale"]))))
        elif isinstance(q, Mapping):
            for k in q:
                rec(orig[k], q[k])

    rec(params, quant)
    return worst


def params_nbytes(params) -> int:
    """Total leaf bytes of a (possibly quantized) params tree — the
    per-tier weight-memory figure serve_bench reports."""
    return sum(int(np.prod(getattr(leaf, "shape", ()) or (1,)))
               * np.dtype(leaf.dtype).itemsize
               for leaf in jax.tree_util.tree_leaves(params))


