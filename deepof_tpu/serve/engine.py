"""InferenceEngine: a dynamic micro-batching flow-inference engine.

The serial predict path pays one device round-trip per image pair and
jits ad hoc; under concurrent load that is the whole throughput story.
This engine owns the restored (verified) params and amortizes dispatch:

  submit() threads enqueue preprocessed requests -> a single batcher
  thread coalesces the queue into one batched forward per flush (up to
  `serve.max_batch` pairs, or whatever arrived within
  `serve.batch_timeout_ms` of the oldest pending request) -> per-request
  futures resolve with postprocessed native-resolution flow.

Design decisions that matter:

  - Every dispatch is padded to EXACTLY max_batch rows (zeros beyond the
    live occupancy, outputs sliced). One bucket therefore owns one
    executable — occupancy 1..max_batch never triggers a recompile — and
    a response is bitwise independent of which batch it rode in, so the
    batched path is bit-identical to the serial path at the same bucket
    (pinned in tests/test_serve.py).
  - Executables are AOT-compiled (`jit(...).lower(avals).compile()`)
    through the PR 1 persistent compile cache; `warmup --serve` runs the
    identical lowering per (bucket, tier) ahead of time, so a cold
    engine's first requests LOAD executables instead of compiling
    (compile-cache counters pinned in tests).
  - Precision is a request axis (serve/quant.py): each configured tier
    (f32 / bf16 weight-cast / int8 weight-only per-channel quantized)
    owns its own params tree and its own executable per bucket, and the
    batcher groups by (bucket, tier) — a request's `precision` field
    picks its operating point on the speed/accuracy frontier without
    touching its batchmates.
  - Decode/preprocess runs on the SUBMITTING thread (cv2 releases the
    GIL): a corrupt or undecodable input fails that one future with a
    structured ServeError before it ever reaches the batcher — a
    poisoned request cannot wedge the engine or fail its batchmates.
  - A failure inside the batched forward fails that flush's requests
    (structured `dispatch_failed`) and the batcher keeps serving; a
    per-request postprocess failure fails only that request.

  - Streaming video sessions (serve/session.py): `submit_next(session,
    frame)` keeps the last frame's preprocessed half-row per session and
    forms the (prev, next) pair server-side — one decode + one
    preprocess per frame instead of two for the video walk, with
    bitwise-identical flow to the pairwise path (prepare_pair is the
    concat of two per-frame preprocesses). The store is LRU + TTL
    bounded; a dead session's next frame is a structured
    `session_expired` the client re-primes from.
  - Temporal warm-start (`serve.session.warm_start`, DESIGN.md
    "Temporal warm-start"): the session additionally keeps the last
    step's RESOLVED flow (raw finest-head output), and a step that has one
    dispatches a refinement-only executable — FlowNetRefine
    (models/flownet2.py) on [img1, img2, warp(img2, prior), prior,
    brightness_err] — instead of the full cold network. The executable
    lattice gains a third axis: `_compiled` is keyed (bucket, tier,
    cold|warm), the batcher groups warm steps exactly like a tier
    switch, and `warmup --serve` pre-lowers the whole bucket x tier x
    mode lattice. A step with no prior (first step, or any step after a
    re-prime/rebucket dropped it) falls back cold — counted as
    `serve_sessions_cold_fallbacks` next to `serve_sessions_warm_steps`.
    Custom/fake executors are warm-blind (one forward fn, no refinement
    weights): warm steps still group/count/trace as warm, but execute
    the same function — grouping and bookkeeping testable without jax.

Observability: trace spans (serve_enqueue / serve_batch /
serve_dispatch / serve_postprocess, session_prime / session_step) on
the shared obs tracer, and a `serve_*` counter block (queue depth,
batch occupancy, p50/p99 latency, requests/s, the serve_sessions_*
streaming axis) exposed via stats()/heartbeat_sample() for the serve
heartbeat and `deepof_tpu tail`.
"""

from __future__ import annotations

import itertools
import queue
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable

import numpy as np

from ..core.config import ExperimentConfig
from ..obs import trace as obs_trace
from ..obs.ledger import ExecutableLedger, exec_name, quality_exec_name
from ..obs.export import (LatencyHistogram, percentile_ms, slo_state,
                          validate_slo)
from ..obs.quality import (QualityScorer, make_score_fn, quality_avals,
                           score_pair_np)
from .buckets import (flow_to_native, next_smaller_bucket, pick_bucket,
                      prepare_frame,
                      prepare_pair, resolve_buckets)
from .quant import dequantize_params, quantize_params, resolve_precisions
from .session import SessionExpired, SessionStore

_STOP = object()

#: Latency samples retained for the p50/p99 estimate (newest window).
_LATENCY_WINDOW = 2048
#: Seconds of completion history behind the requests/s figure.
_RATE_WINDOW_S = 10.0


class ServeError(RuntimeError):
    """Structured per-request failure: machine-readable `code` +
    human-readable message, JSON-ready via payload(). Codes:
    bad_input (decode/preprocess), dispatch_failed (the batched forward
    raised — the whole flush fails), postprocess_failed (one request's
    resize/rescale raised), engine_closed, bad_request (server-side),
    session_expired (a streaming session was TTL-expired or LRU-evicted
    — the client re-primes; serve/session.py), deadline_exceeded (the
    caller's propagated X-Deadline-Ms budget expired before dispatch —
    fail-fast instead of occupying a padded batch slot; HTTP 504)."""

    def __init__(self, code: str, message: str,
                 request_id: int | str | None = None):
        super().__init__(message)
        self.code = code
        self.request_id = request_id

    def payload(self) -> dict:
        out = {"error": self.code, "message": str(self)}
        if self.request_id is not None:
            out["request_id"] = self.request_id
        return out


class _Request:
    __slots__ = ("x", "bucket", "tier", "native_hw", "future", "t_enq",
                 "rid", "session", "frame_index", "mode", "prior",
                 "session_epoch", "score", "deadline")

    def __init__(self, x, bucket, tier, native_hw, future, t_enq, rid,
                 session=None, frame_index=None, mode="cold", prior=None,
                 session_epoch=None, deadline=None):
        self.x = x
        self.bucket = bucket
        self.tier = tier
        self.native_hw = native_hw
        self.future = future
        self.t_enq = t_enq
        self.rid = rid
        # streaming-session step provenance (serve/session.py): the
        # session id + 0-based frame index, echoed in the response and
        # observed into the per-session-frame latency histogram
        self.session = session
        self.frame_index = frame_index
        # temporal warm-start: mode "warm" dispatches the refinement
        # executable with `prior` = the session's cached flow (a prior
        # dispatch's raw finest-head output; always None for mode "cold")
        self.mode = mode
        self.prior = prior
        # the session's prime-generation at advance() time: the
        # writeback token set_flow guards on (None off-session)
        self.session_epoch = session_epoch
        # label-free quality sampling (obs/quality.py): set at enqueue
        # by the deterministic sampler; a sampled request's (input,
        # raw flow) pair is handed to the off-path scorer at resolve
        self.score = False
        # absolute time.monotonic() the caller's budget expires (None =
        # no deadline): checked at enqueue backpressure and again at
        # flush, so a doomed request fails fast with deadline_exceeded
        # instead of occupying a padded batch slot
        self.deadline = deadline

    @property
    def key(self) -> tuple[tuple[int, int], str, str]:
        """The dispatch-group identity: requests batch together iff they
        share (bucket, tier, mode) — one executable per key."""
        return (self.bucket, self.tier, self.mode)


def build_serve_model(cfg: ExperimentConfig):
    """The inference model for a config — the same build the serial
    predict path and `warmup --serve` use, so executables compiled by
    either are interchangeable cache entries."""
    from ..models.registry import build_model

    t = cfg.data.time_step
    return build_model(cfg.model, flow_channels=2 * (t - 1),
                      width_mult=cfg.width_mult,
                      corr_max_disp=cfg.corr_max_disp,
                      corr_stride=cfg.corr_stride)


def make_raw_forward(model) -> Callable:
    """(params, pairs[B,H,W,6]) -> finest scaled flow [B,h,w,2]. Defined
    once so the engine's runtime lowering and warmup's AOT lowering
    produce the same HLO (same persistent-cache key). `params` may be a
    quantized tier tree (serve/quant.py): int8 kernels dequantize HERE,
    inside the trace, so the executable's params input stays int8 while
    activations run f32 — on an f32/bf16 tree the dequantize pass
    inserts nothing and the HLO is unchanged."""

    def fwd(params, x):
        flows = model.apply({"params": dequantize_params(params)}, x)
        return flows[0] * model.flow_scales[0]

    return fwd


def build_refine_model(cfg: ExperimentConfig):
    """The warm-path refinement stage for a config (models/flownet2.py
    FlowNetRefine) — ONE definition shared by the engine and
    `warmup --serve` so their lowerings share a cache key.

    flownet_cs configs reuse their own full-width refinement stage
    (direct-prediction semantics; the checkpoint's `refine` subtree IS
    this module's params). Every other 2-frame model gets a standalone
    gated-residual stage at `width_mult * serve.session.warm_width` with
    a deterministic seeded init (`refine_init_params`) — identity on its
    prior until a trained refinement checkpoint exists."""
    from ..models.flownet2 import FlowNetRefine

    if cfg.model == "flownet_cs":
        return FlowNetRefine(width_mult=1.0, residual=False)
    return FlowNetRefine(
        width_mult=cfg.width_mult * float(cfg.serve.session.warm_width),
        residual=True)


def refine_init_params(cfg: ExperimentConfig, refine_model):
    """Deterministic (cfg.train.seed) init of the standalone refinement
    stage. Conv params are spatial-shape-independent, so one init at any
    /64-friendly size serves every bucket; the fixed seed is what makes
    the warm path bit-stable across engines and replicas."""
    import jax
    import jax.numpy as jnp

    variables = refine_model.init(
        jax.random.PRNGKey(cfg.train.seed),
        jnp.zeros((1, 64, 64, PAIR_CHANNELS), jnp.float32),
        jnp.zeros((1, 32, 32, 2), jnp.float32))
    return variables["params"]


def make_refine_forward(refine_model) -> Callable:
    """(refine_params, pairs[B,H,W,6], prior[B,H,W,2]) -> finest scaled
    flow [B,h,w,2] — the warm twin of make_raw_forward, defined once so
    the engine's runtime lowering and warmup's AOT lowering share a
    persistent-cache key. Same dequantize-inside-the-trace contract as
    the cold forward (int8 refine tiers stay int8 at the boundary)."""

    def fwd(params, x, prior):
        flows = refine_model.apply({"params": dequantize_params(params)},
                                   x, prior)
        return flows[0] * refine_model.flow_scales[0]

    return fwd


def cold_output_hw(cold_fwd, cold_params, bucket: tuple[int, int],
                   max_batch: int) -> tuple[int, int]:
    """The (h, w) grid of the COLD executable's output for one bucket —
    derived abstractly (eval_shape; nothing runs). This is the grid the
    session's warm-start prior lives on: the prior is a previous
    dispatch's output stored verbatim, so the warm executable's prior
    aval must match the cold executable's output aval by construction.
    The refinement stage's OWN output must land on the same grid (the
    prior chain is shape-stable only then) — `_executable`/warmup check
    that abstractly and reject the config loudly otherwise."""
    import jax

    params_sds, x_sds = serve_avals(cold_params, bucket, max_batch)
    out = jax.eval_shape(cold_fwd, params_sds, x_sds)
    return (int(out.shape[1]), int(out.shape[2]))


def _lowered_out_hw(lowered) -> tuple[int, int]:
    """The (h, w) grid of a lowering's (single-array) output, read off
    ``Lowered.out_info`` — the shape the trace ALREADY derived, so the
    prior-grid check costs zero additional traces (it formerly paid a
    full eval_shape of the refine forward per warm lattice entry)."""
    import jax

    leaf = jax.tree_util.tree_leaves(lowered.out_info)[0]
    return (int(leaf.shape[1]), int(leaf.shape[2]))


def refine_serve_avals(refine_params, bucket: tuple[int, int],
                       max_batch: int, prior_hw: tuple[int, int]):
    """(params_sds, x_sds, prior_sds) for one warm bucket executable —
    shared by engine._executable and warmup_serve so their cache keys
    match (the serve_avals twin, plus the prior input on the cold
    output's grid — `cold_output_hw`)."""
    import jax

    params_sds, x_sds = serve_avals(refine_params, bucket, max_batch)
    prior_sds = jax.ShapeDtypeStruct(
        (max_batch, prior_hw[0], prior_hw[1], 2), np.float32)
    return params_sds, x_sds, prior_sds


def make_fake_forward(exec_ms: float) -> Callable:
    """Deterministic timed executor standing in for the model: sleeps
    `exec_ms` per DISPATCH (batch-size independent, like a device whose
    forward is latency-bound) and computes flow as the scaled channel
    difference of the input pair — content-dependent, so output equality
    across runs/replicas is a real check. The batcher tests,
    `tools/serve_bench.py`, and fleet replica subprocesses
    (`serve.fake_exec_ms`) all share this one definition: no checkpoint,
    no jax import."""

    def forward(bucket, x):
        time.sleep(max(exec_ms, 0.0) / 1e3)
        return np.stack([x[..., 0] - x[..., 3], x[..., 1] - x[..., 4]],
                        axis=-1).astype(np.float32)

    return forward


#: Serving is pair-based: prepare_pair always concatenates exactly two
#: preprocessed BGR frames, so every executable takes 6 input channels
#: (multi-frame T-volume configs are a training shape, not a serving one).
PAIR_CHANNELS = 6


def serve_avals(params, bucket: tuple[int, int], max_batch: int):
    """(params_sds, x_sds) for one bucket executable — shared by
    engine._executable and warmup_serve so their cache keys match.
    `params` may be real arrays or ShapeDtypeStructs."""
    import jax

    params_sds = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(getattr(a, "shape", ()), a.dtype),
        params)
    x_sds = jax.ShapeDtypeStruct(
        (max_batch, bucket[0], bucket[1], PAIR_CHANNELS), np.float32)
    return params_sds, x_sds


class InferenceEngine:
    """See module docstring.

    cfg: full experiment config (serve.* drives the batcher; data/eval
        fields drive the preprocess/postprocess protocol).
    model_params: optional (model, params) — skips checkpoint restore
        (tests, and predict_pairs after it already restored).
    mean: optional BGR dataset mean override (DATASET_MEANS default).
    forward_fn: optional (bucket, x[max_batch,H,W,6]) -> [max_batch,h,w,2]
        executor replacing the jitted model entirely — the deterministic
        fake timed executor the batcher tests and serve_bench use. A
        custom executor is precision-blind (it has no weights to
        quantize): every tier routes/batches separately but executes the
        same function.
    """

    def __init__(self, cfg: ExperimentConfig, model_params=None, mean=None,
                 forward_fn: Callable | None = None):
        self.cfg = cfg
        self.max_batch = max(int(cfg.serve.max_batch), 1)
        self.timeout_s = max(float(cfg.serve.batch_timeout_ms), 0.0) / 1e3
        self.buckets = resolve_buckets(cfg)
        if float(cfg.obs.slo_latency_ms) > 0:
            validate_slo(cfg.obs)  # an unmeasurable SLO target fails HERE
        # precision tiers: one executable per (bucket, tier); the
        # config's first entry is the default a request gets when it
        # names none (serve/quant.py owns the transforms)
        self.tiers = resolve_precisions(cfg)
        self.default_tier = self.tiers[0]
        if mean is None:
            from ..data.datasets import DATASET_MEANS

            mean = DATASET_MEANS.get(cfg.data.dataset,
                                     DATASET_MEANS["flyingchairs"])
        self.mean = mean

        # temporal warm-start: the refinement-only executable axis
        # (serve/session.py prior + models/flownet2.py FlowNetRefine)
        self.warm_start = bool(cfg.serve.session.warm_start)

        if (forward_fn is None and model_params is None
                and cfg.serve.fake_exec_ms is not None):
            # config-driven fake executor: how a fleet replica subprocess
            # (which only gets a config.json) runs without a checkpoint
            forward_fn = make_fake_forward(float(cfg.serve.fake_exec_ms))
        self._forward_custom = forward_fn is not None
        if self._forward_custom:
            # internal convention: _forward(key, x, prior=None) with key
            # = (bucket, tier, mode); custom executors keep their
            # documented (bucket, x) signature — they are precision- AND
            # warm-blind (no weights to quantize, no refinement stage):
            # warm steps group/count separately but execute the same fn
            self._forward = (lambda key, x, prior=None, _fn=forward_fn:
                             _fn(key[0], x))
            self._model = self._params = None
        else:
            if model_params is not None:
                self._model, self._params = model_params
            else:
                from ..predict import restore_params

                self._model, self._params = restore_params(cfg)
            import jax

            from ..train.warmup import enable_for_config

            # persistent compile cache per config policy (auto: on for
            # accelerator backends): a cold serving process after
            # `warmup --serve` loads its bucket executables instead of
            # compiling them
            enable_for_config(cfg)
            # AOT executables are lowered from bare avals — the same
            # single-device lowering `warmup --serve` persists (cache-key
            # parity). Params restored onto a replicated mesh sharding
            # would mismatch that compiled input spec, so serving
            # canonicalizes them onto one device; scale-out is N engine
            # processes, not in-engine batch sharding.
            dev = jax.devices()[0]
            self._params = jax.device_put(self._params, dev)
            # one quantized params tree per tier, staged once (int8 is a
            # quarter, bf16 half the f32 bytes); the tier trees' avals
            # differ, so each (bucket, tier) lowers to its own cache key
            self._params_by_tier = {
                tier: jax.device_put(quantize_params(self._params, tier),
                                     dev)
                for tier in self.tiers}
            if self.warm_start:
                # the warm refinement stage: flownet_cs reuses its own
                # (restored) refine subtree; other models get the
                # deterministic seeded gated-residual stage — either
                # way, one quantized tree per tier, like the cold params
                self._refine_model = build_refine_model(cfg)
                if cfg.model == "flownet_cs":
                    refine_params = {"refine": self._params["refine"]}
                else:
                    refine_params = refine_init_params(
                        cfg, self._refine_model)
                self._refine_by_tier = {
                    tier: jax.device_put(
                        quantize_params(refine_params, tier), dev)
                    for tier in self.tiers}
                self._warm_jit = jax.jit(
                    make_refine_forward(self._refine_model))
            if "f32" not in self.tiers:
                # nothing reads the f32 tree once the tier trees exist;
                # keeping it would hold 1-2x the configured ladder's
                # weight bytes on the device for the engine's lifetime
                self._params = None
            self._jit = jax.jit(make_raw_forward(self._model))
            self._forward = self._model_forward
        self._compiled: dict[tuple[tuple[int, int], str], object] = {}
        self._compile_lock = threading.Lock()
        # executable ledger (obs/ledger.py): real-model engines append
        # one provenance row per AOT lowering to <log_dir>/ledger.jsonl
        # and export the exec_* block through stats() -> heartbeat +
        # /metrics. Custom/fake executors have no XLA executables to
        # ledger, and obs.ledger=false keeps the stats schema
        # byte-identical to the pre-ledger stack. Hot-path cost is one
        # timed dict update per flush (bounded <= 2% of serve p99 in
        # serve_bench --ledger-overhead).
        self._ledger: ExecutableLedger | None = None
        if not self._forward_custom and bool(cfg.obs.ledger):
            import jax

            self._ledger = ExecutableLedger(
                cfg.train.log_dir, backend=jax.default_backend())
        # artifact plane (serve/artifacts.py): when serve.artifacts_dir
        # names a store, every lattice entry is FETCHED (deserialized)
        # from it instead of compiled, keyed by the local lowering's
        # StableHLO fingerprint — the zero-cold-start replica boot. A
        # miss/reject falls back to the compile path loudly.
        self._artifacts = None
        if not self._forward_custom:
            from .artifacts import store_for_config

            self._artifacts = store_for_config(cfg)
        # executable index (trace-free boot): resolve each lattice entry
        # by its jax-free resolution key BEFORE building avals or
        # lowering anything — an index hit is fetch + gates +
        # deserialize, zero trace/lower calls. Integrity beyond the
        # crc/manifest/name gates is deferred to the deep-verify plane
        # below; any index miss/reject falls through to the
        # fingerprint-then-compile path.
        self._index_enabled = (self._artifacts is not None
                               and bool(cfg.serve.artifacts_index))
        self._deep_verify_enabled = (self._index_enabled
                                     and bool(
                                         cfg.serve.artifacts_deep_verify))
        self._cfg_digest: str | None = None
        # deferred deep-verify plane: every index-resolved entry is
        # queued for a background re-lowering AFTER it starts serving;
        # a fingerprint mismatch loudly demotes it (counter + warn +
        # freshly compiled swap-in under _compile_lock). Lazily started
        # daemon thread; close() stops it.
        self._deep_verify_q: queue.Queue = queue.Queue()
        self._deep_verify_thread: threading.Thread | None = None
        # pacing: one re-lower per serve.deep_verify_interval_s tick —
        # a hundred-entry lattice must not monopolize a core after
        # boot. The event doubles as the close() wake-up so a long
        # interval never stalls shutdown.
        self._deep_verify_stop = threading.Event()
        # incident plane (obs/incident.py): the process-level recorder,
        # installed by server.py when obs.incidents is on. None keeps
        # every trigger site a structural no-op (one attribute check).
        self.incidents = None
        # cold-head output grid per bucket (one eval_shape each, shared
        # by every tier's warm entry and the bucket's quality scorer —
        # the grid is dtype-independent, so re-deriving it per tier was
        # pure duplicated tracing)
        self._cold_hw: dict[tuple[int, int], tuple[int, int]] = {}

        depth = max(int(cfg.serve.queue_depth), 0)
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._closed = False
        self._rid = itertools.count(1)
        # after each flush: (total_responses) -> None — the serve
        # heartbeat's beat() hook (server.py wires it)
        self.flush_hook: Callable[[int], None] | None = None

        # --- counters (guarded by _stats_lock; GIL-atomic reads are not
        # enough for the multi-field snapshots stats() returns) ---
        self._stats_lock = threading.Lock()
        self._requests = 0
        self._responses = 0
        self._errors = 0
        self._batches = 0
        self._dispatch_failures = 0
        self._bucket_splits = 0
        self._tier_splits = 0
        self._warm_splits = 0   # same (bucket, tier), cold|warm boundary
        # temporal warm-start ledger: steps dispatched through the
        # refinement executable vs warm-eligible steps that fell back
        # cold (no prior yet — first step, or dropped by re-prime/
        # rebucket). Both stay 0 with warm_start off.
        self._warm_steps = 0
        self._cold_fallbacks = 0
        # per-tier request/response counts (analyze/tail surface these
        # so a tier nobody asks for is visible as such)
        self._requests_by_tier = {t: 0 for t in self.tiers}
        self._responses_by_tier = {t: 0 for t in self.tiers}
        self._timeout_flushes = 0
        self._occupancy_sum = 0
        self._last_occupancy = 0
        self._max_queue_depth = 0
        self._submitting = 0  # submit() threads currently inside put()
        # server-side failures only (dispatch/postprocess/engine_closed):
        # the SLO error budget must not burn on a CALLER's bad input
        self._server_errors = 0
        # deadline plane: requests arriving WITH a budget, and where
        # expired ones died (enqueue backpressure / pre-dispatch flush /
        # the server's response wait). Expiry is the CALLER's budget
        # running out, not a server fault — like session_expired it
        # counts serve_errors but never serve_server_errors.
        self._deadline_requests = 0
        self._deadline_enqueue_expired = 0
        self._deadline_flush_expired = 0
        self._deadline_wait_expired = 0
        # brownout folding (serve/degrade.py): requests actually served
        # on a cheaper operating point than they would have gotten at L0
        self._degrade_tier_downgrades = 0
        self._degrade_bucket_downgrades = 0
        self._latency_s: deque = deque(maxlen=_LATENCY_WINDOW)
        # fixed-bucket latency histogram (obs/export.py): the scrapeable
        # /metrics face of the latency story — fixed log-spaced buckets,
        # so replica histograms merge EXACTLY at the router
        self._hist = LatencyHistogram()
        # streaming sessions (serve/session.py): last-frame cache +
        # a second fixed-bucket histogram for per-session-frame latency
        # (merges exactly at the router, separately from serve_latency)
        sc = cfg.serve.session
        self.sessions = SessionStore(max_sessions=sc.max_sessions,
                                     ttl_s=sc.ttl_s, sweep_s=sc.sweep_s)
        self._session_hist = LatencyHistogram()
        # per-second completion buckets for requests/s — unlike reusing
        # the latency deque, this can't clamp the rate at high load
        self._done_per_s: dict[int, int] = {}

        # label-free flow-quality scoring (obs/quality.py): OFF by
        # default (sample_rate 0 constructs nothing — the serve path
        # stays bitwise- and schema-unchanged). Real-model engines score
        # through one jitted executable per bucket (pre-lowered by
        # `warmup --serve`); custom/fake executors score through the
        # numpy reference — jax-free fleet replicas keep quality eyes.
        self._quality: QualityScorer | None = None
        self._quality_index = 0  # deterministic sampler's request index
        self._score_compiled: dict[tuple[int, int], object] = {}
        obs = cfg.obs
        if float(obs.quality_sample_rate) > 0:
            if self._forward_custom:
                score_fn = (lambda bucket, x, flow:
                            score_pair_np(x[0], flow[0]))
            else:
                import jax

                self._score_jit = jax.jit(make_score_fn())
                score_fn = (lambda bucket, x, flow:
                            tuple(float(v) for v in np.asarray(
                                self._score_executable(bucket)(x, flow))))
            self._quality = QualityScorer(
                score_fn, obs.quality_sample_rate,
                seed=obs.quality_seed,
                queue_depth=obs.quality_queue_depth,
                ref_samples=obs.quality_ref_samples,
                window=obs.quality_window,
                drift_factor=obs.quality_drift_factor,
                budget=obs.quality_budget)

        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-batcher")
        self._thread.start()

    # ------------------------------------------------------------ submit
    def _decode(self, img) -> np.ndarray:
        """Path -> decoded BGR array (arrays pass through validated)."""
        if isinstance(img, np.ndarray):
            if img.ndim != 3 or img.shape[-1] != 3:
                raise ServeError("bad_input",
                                 f"image array must be (H, W, 3) BGR, "
                                 f"got {img.shape}")
            return img
        from ..data.datasets import _imread_bgr

        return _imread_bgr(str(img))

    def _resolve_tier(self, precision, rid, degrade_level: int = 0) -> str:
        """A request's tier: its explicit `precision` or the config's
        default; a tier this endpoint does not serve is a structured
        per-request error (no executable exists for it — admitting it
        would compile on the hot path).

        At brownout L1+ (serve/degrade.py) a request that named NO
        precision serves at the cheapest configured tier instead of the
        default — an explicit `precision` is always honored. Every tier
        is a pre-warmed lattice entry, so the downgrade never compiles.
        """
        if precision is None:
            if degrade_level >= 1 and len(self.tiers) > 1:
                tier = self.tiers[-1]  # config order: last = cheapest
                if tier != self.default_tier:
                    with self._stats_lock:
                        self._degrade_tier_downgrades += 1
                return tier
            return self.default_tier
        tier = str(precision)
        if tier not in self.tiers:
            raise ServeError(
                "bad_request",
                f"precision {tier!r} not served; this endpoint offers "
                f"{list(self.tiers)}", rid)
        return tier

    def _deadline_abs(self, deadline_s) -> float | None:
        """Caller budget (seconds remaining) -> absolute monotonic
        expiry; also ticks the deadline_requests ledger."""
        if deadline_s is None:
            return None
        with self._stats_lock:
            self._deadline_requests += 1
        return time.monotonic() + max(float(deadline_s), 0.0)

    def submit(self, prev, nxt, precision: str | None = None,
               request_id: int | str | None = None,
               deadline_s: float | None = None,
               degrade_level: int = 0) -> Future:
        """Enqueue one (prev, next) pair — paths or decoded BGR arrays.

        precision: serving tier ("f32" | "bf16" | "int8"); must be in
        cfg.serve.precisions; None = the config's first (default) tier.
        request_id: external correlation id (the router's X-Request-Id)
        stamped on this request's spans and echoed in the response, so
        obs/aggregate.py can chain the request's timeline across the
        router and this replica; None = a process-local sequence id.
        deadline_s: the caller's remaining budget (X-Deadline-Ms / 1e3);
        None = no deadline. An expired request fails fast with
        `deadline_exceeded` at enqueue or flush instead of dispatching.
        degrade_level: the live brownout level the router folded in
        (X-Degrade-Level; serve/degrade.py) — L1+ downgrades the default
        tier, L2+ routes one bucket down the ladder; both targets are
        pre-warmed lattice entries, so degradation never compiles.

        Returns a Future resolving to {"flow": (H_native, W_native, 2)
        float32 in native pixel units, "bucket", "precision",
        "native_hw", "latency_s", "request_id"}; failures raise
        ServeError from .result(). Decode/preprocess errors fail HERE
        (this request only) — they never enter the batcher.
        """
        rid = request_id if request_id is not None else next(self._rid)
        fut: Future = Future()
        with self._stats_lock:
            self._requests += 1
        try:
            tier = self._resolve_tier(precision, rid, degrade_level)
            deadline = self._deadline_abs(deadline_s)
            with obs_trace.span("serve_enqueue", request_id=rid):
                src = self._decode(prev)
                tgt = self._decode(nxt)
                native_hw = (int(src.shape[0]), int(src.shape[1]))
                bucket = pick_bucket(native_hw, self.buckets)
                if degrade_level >= 2:
                    down = next_smaller_bucket(bucket, self.buckets)
                    if down != bucket:
                        bucket = down
                        with self._stats_lock:
                            self._degrade_bucket_downgrades += 1
                x = prepare_pair(src, tgt, bucket, self.mean)
            with self._stats_lock:
                self._requests_by_tier[tier] += 1
            self._enqueue(_Request(x, bucket, tier, native_hw, fut,
                                   time.monotonic(), rid,
                                   deadline=deadline))
        except ServeError as e:
            e.request_id = e.request_id or rid
            self._fail(fut, e)
        except Exception as e:  # noqa: BLE001 - decode errors are per-request
            self._fail(fut, ServeError(
                "bad_input", f"{type(e).__name__}: {e}", rid))
        return fut

    def submit_prepared(self, x: np.ndarray, bucket: tuple[int, int],
                        native_hw: tuple[int, int],
                        precision: str | None = None,
                        request_id: int | str | None = None,
                        deadline_s: float | None = None) -> Future:
        """Enqueue an already-preprocessed row (offline mode: the
        data/pipeline.py worker pool runs prepare_pair concurrently and
        feeds rows here in order). No brownout folding: the row is
        already prepared at its bucket, and offline throughput work is
        not latency-degradable."""
        rid = request_id if request_id is not None else next(self._rid)
        fut: Future = Future()
        with self._stats_lock:
            self._requests += 1
        try:
            tier = self._resolve_tier(precision, rid)
            deadline = self._deadline_abs(deadline_s)
            with self._stats_lock:
                self._requests_by_tier[tier] += 1
            self._enqueue(_Request(np.asarray(x, np.float32), tuple(bucket),
                                   tier, tuple(native_hw), fut,
                                   time.monotonic(), rid,
                                   deadline=deadline))
        except ServeError as e:
            e.request_id = e.request_id or rid
            self._fail(fut, e)
        return fut

    def submit_next(self, session: str, frame,
                    precision: str | None = None,
                    request_id: int | str | None = None,
                    deadline_s: float | None = None,
                    degrade_level: int = 0) -> Future:
        """Advance a streaming session by ONE frame (serve/session.py).

        The first frame of a session primes it: the future resolves
        immediately with {"primed": True, "session", "bucket",
        "native_hw", "frames", "request_id"} — nothing dispatches.
        Every later frame forms the (prev, next) pair from the cached
        previous frame — one decode + one preprocess instead of two —
        and resolves like submit(), plus {"session", "frame_index"}.

        Failure contract: a frame for a TTL-expired or LRU-evicted
        session fails with a structured `session_expired` ServeError
        (the client re-primes by resending — that retry is counted as
        `resumed`); a mid-session resolution change re-primes in place
        (a fresh `primed` reply, counted as `rebucketed`). A decode
        failure fails this frame only and does NOT advance the session.

        Brownout folding is tier-only here: L1+ downgrades the default
        precision, but L2's bucket downgrade is deliberately NOT applied
        to streaming steps — a bucket change re-primes the session
        (advance()'s rebucket path), dropping the cached frame and warm
        prior, which would cost more than the smaller bucket saves.
        """
        rid = request_id if request_id is not None else next(self._rid)
        fut: Future = Future()
        counted = False  # one _requests tick per frame, on ANY path
        # span name is a fast pre-probe; advance() is the authority (a
        # race with the sweeper at most mislabels one span's name)
        kind_hint = "session_step" if self.sessions.contains(session) \
            else "session_prime"
        try:
            tier = self._resolve_tier(precision, rid, degrade_level)
            deadline = self._deadline_abs(deadline_s)
            with obs_trace.span(kind_hint, session=str(session),
                                request_id=rid) as span:
                img = self._decode(frame)
                native_hw = (int(img.shape[0]), int(img.shape[1]))
                bucket = pick_bucket(native_hw, self.buckets)
                row = prepare_frame(img, bucket, self.mean)
                try:
                    out = self.sessions.advance(str(session), row, bucket,
                                                native_hw, tier)
                except SessionExpired as e:
                    raise ServeError(
                        "session_expired",
                        f"session {e.sid!r} {e.reason} — resend the frame "
                        f"to re-prime", rid)
                if out[0] == "primed":
                    _, s = out
                    span.set(kind="session_prime")
                    fut.set_result({"primed": True, "session": s.sid,
                                    "bucket": bucket,
                                    "native_hw": native_hw,
                                    "frames": s.frames,
                                    "request_id": rid})
                    return fut
                _, prev_row, prior, epoch, s = out
                # temporal warm-start: a step with a cached prior flow
                # dispatches the refinement-only executable; without one
                # (first step, or the prior was dropped by a re-prime/
                # rebucket) it falls back to the full cold network
                mode = "cold"
                if self.warm_start and prior is not None:
                    mode = "warm"
                    span.set(kind="session_warm",
                             frame_index=s.frames - 1)
                else:
                    if self.warm_start:
                        with self._stats_lock:
                            self._cold_fallbacks += 1
                    span.set(kind="session_step",
                             frame_index=s.frames - 1)
                x = np.concatenate([prev_row, row], axis=-1)
            with self._stats_lock:
                self._requests += 1
                self._requests_by_tier[tier] += 1
                if mode == "warm":
                    self._warm_steps += 1
            counted = True
            self._enqueue(_Request(x, bucket, tier, native_hw, fut,
                                   time.monotonic(), rid,
                                   session=s.sid,
                                   frame_index=s.frames - 1,
                                   mode=mode,
                                   prior=prior if mode == "warm" else None,
                                   session_epoch=epoch,
                                   deadline=deadline))
        except ServeError as e:
            e.request_id = e.request_id or rid
            if not counted:  # failed frames stay ledgered, exactly once
                with self._stats_lock:
                    self._requests += 1
            self._fail(fut, e)
        except Exception as e:  # noqa: BLE001 - decode errors are per-request
            if not counted:
                with self._stats_lock:
                    self._requests += 1
            self._fail(fut, ServeError(
                "bad_input", f"{type(e).__name__}: {e}", rid))
        return fut

    def _enqueue(self, req: _Request) -> None:
        with self._stats_lock:
            if self._closed:
                raise ServeError("engine_closed", "engine is shut down",
                                 req.rid)
            self._submitting += 1
            if self._quality is not None:
                # the sampling decision is a pure function of the
                # accepted-request index (obs/quality.py): the sampled
                # SET depends only on submission order — never on
                # batching, scorer backlog, or decode-worker count
                req.score = self._quality.should_sample(self._quality_index)
                self._quality_index += 1
        try:
            # bounded put = backpressure, but polled: a submitter blocked
            # on a full queue must observe close() — and its own
            # deadline — instead of completing its put into a dead queue
            # (its future would never resolve — close() drains only
            # after _submitting hits 0). A doomed request releasing its
            # backpressure slot here is load the queue never carries.
            while True:
                if self._closed:
                    raise ServeError("engine_closed", "engine is shut down",
                                     req.rid)
                if req.deadline is not None:
                    rem = req.deadline - time.monotonic()
                    if rem <= 0:
                        with self._stats_lock:
                            self._deadline_enqueue_expired += 1
                        raise ServeError(
                            "deadline_exceeded",
                            "deadline expired while queueing", req.rid)
                else:
                    rem = 0.1
                try:
                    self._q.put(req, timeout=min(0.1, max(rem, 0.001)))
                    break
                except queue.Full:
                    continue
        finally:
            with self._stats_lock:
                self._submitting -= 1
        with self._stats_lock:
            self._max_queue_depth = max(self._max_queue_depth,
                                        self._q.qsize())

    def _fail(self, fut: Future, err: ServeError) -> None:
        with self._stats_lock:
            self._errors += 1
            # session_expired is protocol, not failure: the client let
            # its session idle past the TTL (or lost an LRU race) and
            # re-primes — it must not burn the operator's SLO budget.
            # deadline_exceeded likewise: the CALLER's budget ran out,
            # not the server — overload shows up in the deadline_* and
            # degrade_* ledgers instead.
            if err.code not in ("bad_input", "bad_request",
                                "session_expired", "deadline_exceeded"):
                self._server_errors += 1  # burns the SLO error budget
        fut.set_exception(err)

    def note_wait_expired(self) -> None:
        """The SERVER's deadline ledger hook: its response wait hit the
        caller's budget (min(request_timeout_s, deadline)) before this
        engine resolved the future. Counted here so every stage of the
        deadline story rides one stats surface."""
        with self._stats_lock:
            self._deadline_wait_expired += 1

    # ----------------------------------------------------------- batcher
    def _run(self) -> None:
        pending: _Request | None = None  # carried over a bucket split
        stop = False
        while not stop:
            if pending is not None:
                req, pending = pending, None
            else:
                req = self._q.get()
            if req is _STOP:
                break
            batch = [req]
            timed_out = False
            with obs_trace.span("serve_batch") as batch_span:
                while len(batch) < self.max_batch:
                    rem = (batch[0].t_enq + self.timeout_s) - time.monotonic()
                    try:
                        nxt = (self._q.get(timeout=rem) if rem > 0
                               else self._q.get_nowait())
                    except queue.Empty:
                        timed_out = True  # the oldest waited out the deadline
                        break
                    if nxt is _STOP:
                        stop = True
                        break
                    if nxt.key != batch[0].key:
                        pending = nxt  # flush now; it opens the next batch
                        with self._stats_lock:
                            if nxt.bucket != batch[0].bucket:
                                self._bucket_splits += 1
                            elif nxt.tier != batch[0].tier:
                                self._tier_splits += 1
                            else:  # same shape+precision, cold|warm edge
                                self._warm_splits += 1
                        break
                    batch.append(nxt)
                # ids are only known once the batch closed: stamp them
                # late so aggregate.py can chain the request's timeline
                batch_span.set(request_ids=[r.rid for r in batch],
                               occupancy=len(batch))
            if timed_out and len(batch) < self.max_batch:
                with self._stats_lock:
                    self._timeout_flushes += 1
            self._flush(batch)
        # anything still queued after _STOP was submitted post-close
        # bookkeeping started — fail it loudly rather than hang a caller
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if req is not _STOP:
                self._fail(req.future, ServeError(
                    "engine_closed", "engine shut down before dispatch",
                    req.rid))

    def _flush(self, batch: list[_Request]) -> None:
        # last pre-dispatch deadline gate: a request whose budget
        # expired while batching fails fast HERE — its padded batch slot
        # (and the postprocess work) would be wasted on a reply the
        # caller already abandoned
        expired = [r for r in batch if r.deadline is not None
                   and r.deadline <= time.monotonic()]
        if expired:
            with self._stats_lock:
                self._deadline_flush_expired += len(expired)
            for r in expired:
                self._fail(r.future, ServeError(
                    "deadline_exceeded", "deadline expired before dispatch",
                    r.rid))
            batch = [r for r in batch if r not in expired]
            if not batch:
                return
        bucket, tier, mode = batch[0].key
        n = len(batch)
        tag = f"{bucket[0]}x{bucket[1]}/{tier}/{mode}"
        rids = [r.rid for r in batch]
        with obs_trace.span("serve_dispatch", occupancy=n, bucket=tag,
                            request_ids=rids):
            x = np.zeros((self.max_batch, bucket[0], bucket[1],
                          batch[0].x.shape[-1]), np.float32)
            for i, r in enumerate(batch):
                x[i] = r.x
            prior = None
            if mode == "warm":
                # the refinement executable's second input: per-request
                # priors (finest-head grid — stored dispatch outputs),
                # zero-padded past the live occupancy like x
                ph, pw = batch[0].prior.shape[:2]
                prior = np.zeros((self.max_batch, ph, pw, 2), np.float32)
                for i, r in enumerate(batch):
                    prior[i] = r.prior
            if self._ledger is not None:
                # resolve (compile/load) the executable BEFORE the timed
                # window: the first flush's measured dispatch must be an
                # execution, not compile+execution — the MFU denominator
                # would otherwise be off by orders of magnitude. Same
                # containment as the dispatch below: a compile failure
                # (warm-grid ValueError, XLA error) fails this flush's
                # futures, never the batcher thread.
                try:
                    self._executable(batch[0].key)
                except Exception as e:  # noqa: BLE001 - contained per flush
                    with self._stats_lock:
                        self._dispatch_failures += 1
                    for r in batch:
                        self._fail(r.future, ServeError(
                            "dispatch_failed", f"{type(e).__name__}: {e}",
                            r.rid))
                    return
            t_fwd = time.perf_counter()
            try:
                out = np.asarray(self._forward(batch[0].key, x,
                                               prior=prior))
            except Exception as e:  # noqa: BLE001 - the flush fails, not the engine
                with self._stats_lock:
                    self._dispatch_failures += 1
                for r in batch:
                    self._fail(r.future, ServeError(
                        "dispatch_failed", f"{type(e).__name__}: {e}", r.rid))
                return
            if self._ledger is not None:
                # per-executable measured dispatch time (host-synced):
                # the denominator of the ledger's nominal-roofline MFU.
                # One dict update per FLUSH, not per request — the whole
                # ledger's hot-path cost.
                self._ledger.note_exec(exec_name(bucket, tier, mode),
                                       time.perf_counter() - t_fwd)
        with obs_trace.span("serve_postprocess", occupancy=n, bucket=tag,
                            request_ids=rids):
            for i, r in enumerate(batch):
                try:
                    flow = flow_to_native(out[i], self.cfg, bucket,
                                          r.native_hw)
                except Exception as e:  # noqa: BLE001 - one request's failure
                    self._fail(r.future, ServeError(
                        "postprocess_failed",
                        f"{type(e).__name__}: {e}", r.rid))
                    continue
                if r.session is not None and self.warm_start:
                    # warm-start writeback: this step's raw output
                    # (finest-head grid, stored VERBATIM — no resample,
                    # so the untrained residual identity is exact along
                    # a walk) becomes the session's prior. BEFORE
                    # set_result — a closed-loop client's next frame
                    # must observe it — and guarded inside the store
                    # against re-prime/rebucket/eviction/RESUME races
                    # (the prime-generation epoch captured at advance).
                    # The copy detaches the slice from the batch buffer.
                    self.sessions.set_flow(
                        r.session,
                        np.ascontiguousarray(out[i], np.float32), bucket,
                        r.session_epoch)
                if r.score and self._quality is not None:
                    # sampled label-free quality scoring (obs/quality.py):
                    # hand (input row, RAW dispatch output) to the
                    # off-path scorer. Row copies detach from the flush's
                    # output buffer; a full scorer queue drops-and-counts
                    # inside submit() — this response is never delayed.
                    self._quality.submit(
                        r.x, np.array(out[i], np.float32, copy=True),
                        bucket, r.tier, r.mode)
                done = time.monotonic()
                self._hist.observe(done - r.t_enq)
                if r.session is not None:
                    # per-session-frame latency: the streaming axis's own
                    # histogram (submit -> flow for ONE new frame)
                    self._session_hist.observe(done - r.t_enq)
                with self._stats_lock:
                    self._responses += 1
                    self._responses_by_tier[r.tier] += 1
                    self._latency_s.append(done - r.t_enq)
                    sec = int(done)
                    self._done_per_s[sec] = self._done_per_s.get(sec, 0) + 1
                    if len(self._done_per_s) > _RATE_WINDOW_S + 5:
                        for old in [s for s in self._done_per_s
                                    if s < sec - _RATE_WINDOW_S - 1]:
                            del self._done_per_s[old]
                result = {"flow": flow, "bucket": bucket,
                          "precision": tier, "native_hw": r.native_hw,
                          "latency_s": done - r.t_enq,
                          "request_id": r.rid}
                if r.session is not None:
                    result["session"] = r.session
                    result["frame_index"] = r.frame_index
                    if self.warm_start:
                        # only under the toggle: warm_start=false keeps
                        # the PR 10 response schema byte-identical
                        result["warm"] = r.mode == "warm"
                r.future.set_result(result)
        with self._stats_lock:
            self._batches += 1
            self._occupancy_sum += n
            self._last_occupancy = n
            total = self._responses
        hook = self.flush_hook
        if hook is not None:
            try:
                hook(total)
            except Exception:  # noqa: BLE001 - observability must not kill serving
                pass

    # ---------------------------------------------------------- forward
    def _model_forward(self, key: tuple[tuple[int, int], str, str],
                       x: np.ndarray, prior: np.ndarray | None = None):
        bucket, tier, mode = key
        if mode == "warm":
            return self._executable(key)(self._refine_by_tier[tier], x,
                                         prior)
        return self._executable(key)(self._params_by_tier[tier], x)

    def _executable(self, key: tuple[tuple[int, int], str, str]):
        """The (bucket, tier, mode) triple's AOT-compiled forward —
        cold: the full network, warm: the refinement-only stage —
        compiled (or loaded from the persistent cache — the
        `warmup --serve` contract) on first use. Steady state is a
        lock-free dict read (atomic in CPython; values are fully built
        before insertion under the lock): with the ledger on, every
        flush resolves the executable twice — the pre-resolve that
        keeps compile time out of the measured-dispatch window, then
        _model_forward — and taking the global compile lock both times
        per flush is the per-request lock-churn class PR 14's review
        removed from Fleet.size on this exact path."""
        c = self._compiled.get(key)
        if c is not None:
            return c
        with self._compile_lock:
            c = self._compiled.get(key)
            if c is None:
                bucket, tier, mode = key
                name = exec_name(bucket, tier, mode)
                # trace-free resolution first: an index hit skips the
                # aval construction AND the cold_output_hw eval_shape
                # below — the entire lattice can resolve with zero
                # trace/lower calls (the acceptance contract ISSUE 17
                # proves from the ledger's index_hit rows)
                c = self._resolve_index(name, serve_key=key)
                if c is None:
                    c = self._lower_and_compile(key)
                self._compiled[key] = c
        return c

    def _lower_and_compile(self, key: tuple[tuple[int, int], str, str]):
        """The lowering path (index off / miss / reject / demote):
        build avals, lower ONCE, and resolve via fingerprint fetch or
        compile. One `lowered` object per lattice entry is shared
        across the prior-grid check, the fingerprint, the ledger row,
        and the compile — the warm path's former eval_shape of the
        refine forward (a second full trace per entry) is replaced by
        reading the grid off the lowering's own out_info."""
        bucket, tier, mode = key
        if mode == "warm":
            prior_hw = self._cold_head_hw(bucket)
            params_sds, x_sds, prior_sds = refine_serve_avals(
                self._refine_by_tier[tier], bucket,
                self.max_batch, prior_hw)

            def lower_checked():
                lowered = self._warm_jit.lower(params_sds, x_sds,
                                               prior_sds)
                # the prior chain must be shape-stable: after the
                # first warm step the stored prior is the REFINE
                # stage's output, so its grid must equal the cold
                # head grid the executable was lowered for — check
                # abstractly HERE (warm()/first use), not as a
                # poisoned dispatch three steps in
                out_hw = _lowered_out_hw(lowered)
                if out_hw != tuple(prior_hw):
                    raise ValueError(
                        f"warm_start unsupported for model "
                        f"{self.cfg.model!r} at bucket {bucket}: the "
                        f"refinement head grid {out_hw} differs from "
                        f"the cold head grid {tuple(prior_hw)} — the "
                        f"session's prior would change shape after "
                        f"the first warm step")
                return lowered

            return self._compile_recorded(exec_name(bucket, tier, mode),
                                          lower_checked)
        params_sds, x_sds = serve_avals(
            self._params_by_tier[tier], bucket, self.max_batch)
        return self._compile_recorded(
            exec_name(bucket, tier, mode),
            lambda: self._jit.lower(params_sds, x_sds))

    def _cold_head_hw(self, bucket: tuple[int, int]) -> tuple[int, int]:
        """The cold network's output grid at `bucket` — ONE eval_shape
        per bucket, cached: every tier's warm entry and the bucket's
        quality scorer share it (the grid does not depend on the weight
        dtype), where each formerly paid its own trace."""
        hw = self._cold_hw.get(bucket)
        if hw is None:
            hw = tuple(cold_output_hw(
                self._jit, self._params_by_tier[self.default_tier],
                bucket, self.max_batch))
            self._cold_hw[bucket] = hw
        return hw

    def _compile_recorded(self, name: str, lower_fn):
        """Resolve one lattice executable: through the executable ledger
        when one is active (provenance row: fingerprint, compile
        seconds, cache hit/miss, artifact verdict, cost/memory
        analysis, donation) — which fetches from the artifact store
        before compiling — else bare (same fetch-first order, no
        row)."""
        if self._ledger is not None:
            compiled, _ = self._ledger.record_aot(
                name, lower_fn, artifacts=self._artifacts)
            return compiled
        lowered = lower_fn()
        if self._artifacts is not None:
            from ..obs.ledger import fingerprint_text

            compiled, _verdict = self._artifacts.fetch(
                fingerprint_text(lowered.as_text()))
            if compiled is not None:
                return compiled
        return lowered.compile()

    # ------------------------------------------- trace-free index boot
    def _config_digest(self) -> str:
        if self._cfg_digest is None:
            from .artifacts import serve_config_digest

            self._cfg_digest = serve_config_digest(self.cfg)
        return self._cfg_digest

    def _index_key(self, name: str,
                   serve_key: tuple | None = None,
                   quality_bucket: tuple | None = None) -> str:
        """The entry's jax-free resolution key. The aval signature
        reads shapes/dtypes off the CONCRETE in-memory param trees (no
        trace); `warmup --serve` computes the identical signature from
        its eval_shape trees, so both sides agree without either
        re-lowering. Warm entries sign the refine tree — the prior
        aval is derived state the index entry carries (`prior_hw`),
        validated at publish time and re-checked by deep verify."""
        import jax

        from .artifacts import params_aval_sig, resolution_key

        if serve_key is not None:
            bucket, tier, mode = serve_key
            params = (self._refine_by_tier[tier] if mode == "warm"
                      else self._params_by_tier[tier])
        else:
            bucket = quality_bucket
            params = self._params_by_tier[self.default_tier]
        x_aval = ("__x__",
                  (self.max_batch, bucket[0], bucket[1], PAIR_CHANNELS),
                  "float32")
        sig = params_aval_sig(params, extra=(x_aval,))
        return resolution_key(name, self._config_digest(), sig,
                              jax.default_backend(), jax.__version__)

    def _resolve_index(self, name: str, serve_key: tuple | None = None,
                       quality_bucket: tuple | None = None):
        """Trace-free resolution of one lattice entry through the
        store's executable index: key lookup + trust gates + fetch +
        deserialize, zero trace/lower calls. A hit is recorded as a
        ``cache_verdict="index_hit"`` ledger row and queued for the
        deferred deep-verify plane; every miss/reject returns None and
        the caller falls back to the lowering path (whose own row —
        `aot`/`artifact` — is the loud evidence on `tail`)."""
        if not self._index_enabled:
            return None
        key = self._index_key(name, serve_key=serve_key,
                              quality_bucket=quality_bucket)
        if self._ledger is not None:
            compiled, _row, verdict = self._ledger.record_index(
                name, self._artifacts, key)
        else:
            try:
                compiled, _fp, verdict = self._artifacts.resolve(key)
            except Exception:  # noqa: BLE001 - index is best-effort
                compiled, verdict = None, "index_reject:resolve_failed"
        if compiled is None:
            return None
        ent = self._artifacts.index_entry(key) or {}
        if self._deep_verify_enabled:
            self._schedule_deep_verify(
                name, serve_key, quality_bucket,
                ent.get("fingerprint"))
        return compiled

    # ------------------------------------------- deferred deep verify
    def _schedule_deep_verify(self, name, serve_key, quality_bucket,
                              expected_fp) -> None:
        """Queue one index-resolved entry for background re-lowering.
        Caller holds _compile_lock; the worker itself never takes it
        except for the swap-in, so verification cannot stall a boot."""
        self._deep_verify_q.put((name, serve_key, quality_bucket,
                                 expected_fp))
        if self._deep_verify_thread is None:
            t = threading.Thread(target=self._deep_verify_loop,
                                 name="deep-verify", daemon=True)
            self._deep_verify_thread = t
            t.start()

    def _deep_verify_loop(self) -> None:
        interval = max(float(self.cfg.serve.deep_verify_interval_s), 0.0)
        while True:
            item = self._deep_verify_q.get()
            if item is None:
                self._deep_verify_q.task_done()
                return
            try:
                self._deep_verify_one(*item)
            except Exception as e:  # noqa: BLE001 - verify best-effort
                print(f"serve: deep-verify {item[0]} failed: {e}",
                      file=sys.stderr)
                if self._ledger is not None:
                    self._ledger.note_deep_verify(True)
            finally:
                self._deep_verify_q.task_done()
            if interval > 0:
                # stagger AFTER task_done: deep_verify_join() sees the
                # entry complete immediately, and close() skips the
                # wait via the stop event
                self._deep_verify_stop.wait(interval)

    def _deep_verify_one(self, name, serve_key, quality_bucket,
                         expected_fp) -> None:
        """Re-lower one index-resolved entry and compare StableHLO
        fingerprints. Match -> exec_deep_verify_ok. Mismatch (the
        index's claimed lowering is NOT what local code produces —
        code drift against a stale index) -> loud demote: warn on
        stderr, exec_deep_verify_demoted counter, a
        compile_kind="deep_verify" ledger row carrying the TRUE
        fingerprint, and a freshly compiled executable swapped in
        under _compile_lock. Serving never pauses; at worst a few
        dispatches ride the stale-but-crc-intact executable before the
        swap lands."""
        import time as _time

        from ..obs.ledger import fingerprint_text

        t0 = _time.perf_counter()
        if serve_key is not None:
            bucket, tier, mode = serve_key
            if mode == "warm":
                prior_hw = self._cold_head_hw(bucket)
                params_sds, x_sds, prior_sds = refine_serve_avals(
                    self._refine_by_tier[tier], bucket,
                    self.max_batch, prior_hw)
                lowered = self._warm_jit.lower(params_sds, x_sds,
                                               prior_sds)
            else:
                params_sds, x_sds = serve_avals(
                    self._params_by_tier[tier], bucket, self.max_batch)
                lowered = self._jit.lower(params_sds, x_sds)
        else:
            flow_hw = self._cold_head_hw(quality_bucket)
            x_sds, flow_sds = quality_avals(quality_bucket, flow_hw)
            lowered = self._score_jit.lower(x_sds, flow_sds)
        fp = fingerprint_text(lowered.as_text())
        ok = fp == expected_fp
        if ok:
            if self._ledger is not None:
                self._ledger.note_deep_verify(True)
                self._ledger.record(
                    name, lowered=lowered,
                    compile_s=_time.perf_counter() - t0,
                    compile_kind="deep_verify",
                    cache_verdict="deep_verify_ok")
            return
        print(f"serve: DEEP-VERIFY DEMOTE {name}: index claimed "
              f"{expected_fp}, local code lowers to {fp} — swapping in "
              f"a fresh compile", file=sys.stderr)
        if self.incidents is not None:
            # the drifted executable SERVED requests before this verdict
            # — the evidence bundle (ledger tail, trace) is the story
            self.incidents.record(
                "deep_verify_demote", "critical",
                trigger={"exec": name, "expected_fp": expected_fp,
                         "actual_fp": fp})
        compiled = lowered.compile()
        with self._compile_lock:
            if serve_key is not None:
                self._compiled[serve_key] = compiled
            else:
                self._score_compiled[quality_bucket] = compiled
        if self._ledger is not None:
            self._ledger.note_deep_verify(False)
            self._ledger.record(
                name, lowered=lowered, compiled=compiled,
                compile_s=_time.perf_counter() - t0,
                compile_kind="deep_verify",
                cache_verdict="deep_verify_demoted")

    def deep_verify_join(self, timeout_s: float = 60.0) -> bool:
        """Wait until every queued deep verification has completed
        (tests and offline drills; serving never calls this). True when
        the queue drained within the timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._deep_verify_q.unfinished_tasks == 0:
                return True
            time.sleep(0.01)
        return self._deep_verify_q.unfinished_tasks == 0

    def _score_executable(self, bucket: tuple[int, int]):
        """The bucket's AOT-compiled quality scorer (obs/quality.py) —
        ONE executable per bucket (tiers and modes share it: the scorer
        consumes f32 inputs and f32 flow regardless of the tier that
        produced them), resolved index-first like the serve lattice,
        compiled on first use otherwise. Lock-free fast path on hit,
        same double-checked pattern as _executable."""
        c = self._score_compiled.get(bucket)
        if c is not None:
            return c
        with self._compile_lock:
            c = self._score_compiled.get(bucket)
            if c is None:
                name = quality_exec_name(bucket)
                c = self._resolve_index(name, quality_bucket=bucket)
                if c is None:
                    flow_hw = self._cold_head_hw(bucket)
                    x_sds, flow_sds = quality_avals(bucket, flow_hw)
                    c = self._compile_recorded(
                        name,
                        lambda: self._score_jit.lower(x_sds, flow_sds))
                self._score_compiled[bucket] = c
        return c

    def warm(self) -> dict:
        """AOT-compile every configured (bucket, tier, mode) triple now
        (server startup / offline-mode entry), through the persistent
        compile cache when active — after `warmup --serve` these are
        loads, not compiles. The mode axis exists only under
        serve.session.warm_start; quality-scorer executables
        (obs.quality_sample_rate > 0) ride along, one per bucket, so
        sampling never compiles on the hot path. Returns per-entry
        timings + the cache hit/miss delta."""
        # the postprocess import chain (train/evaluate and friends) is
        # first-request latency too — ~seconds in a fresh process, paid
        # inside the batcher thread if not paid here (measured via
        # tools/serve_bench.py)
        flow_to_native(np.zeros((2, 2, 2), np.float32), self.cfg,
                       (2, 2), (2, 2))
        if self._forward_custom:
            return {"buckets": [], "cache": None}  # nothing to compile
        from ..train.warmup import cache_delta

        modes = ("cold", "warm") if self.warm_start else ("cold",)
        out: dict = {"buckets": [], "modes": list(modes)}
        with cache_delta() as d:
            for b in self.buckets:
                for tier in self.tiers:
                    for mode in modes:
                        t0 = time.perf_counter()
                        self._executable((b, tier, mode))
                        out["buckets"].append(
                            {"bucket": list(b), "tier": tier, "mode": mode,
                             "compile_s": round(
                                 time.perf_counter() - t0, 3)})
                if self._quality is not None:
                    t0 = time.perf_counter()
                    self._score_executable(b)
                    out["buckets"].append(
                        {"bucket": list(b), "tier": "-", "mode": "quality",
                         "compile_s": round(time.perf_counter() - t0, 3)})
        out["cache"] = d.stats()
        return out

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        """The serve_* counter block (heartbeat / tail / serve_bench)."""
        now = time.monotonic()
        with self._stats_lock:
            lat = sorted(self._latency_s)
            recent = sum(c for s, c in self._done_per_s.items()
                         if now - s <= _RATE_WINDOW_S)
            out = {
                "serve_requests": self._requests,
                "serve_responses": self._responses,
                "serve_errors": self._errors,
                # server-side subset of serve_errors (dispatch/
                # postprocess/engine_closed — NOT client bad input): the
                # count that distinguishes a failing executor from noisy
                # clients, and the one the fleet scrape can sum
                "serve_server_errors": self._server_errors,
                "serve_batches": self._batches,
                "serve_dispatch_failures": self._dispatch_failures,
                "serve_bucket_splits": self._bucket_splits,
                "serve_tier_splits": self._tier_splits,
                "serve_warm_splits": self._warm_splits,
                "serve_requests_by_tier": dict(self._requests_by_tier),
                "serve_responses_by_tier": dict(self._responses_by_tier),
                "serve_timeout_flushes": self._timeout_flushes,
                "serve_queue_depth": self._q.qsize(),
                "serve_max_queue_depth": self._max_queue_depth,
                "serve_last_occupancy": self._last_occupancy,
                "serve_occupancy_mean": (
                    round(self._occupancy_sum / self._batches, 3)
                    if self._batches else None),
                "serve_max_batch": self.max_batch,
                "serve_buckets": len(self.buckets),
                "serve_tiers": len(self.tiers),
                # deadline plane: budgeted arrivals + where expired ones
                # died (enqueue / flush / the server's response wait)
                "deadline_requests": self._deadline_requests,
                "deadline_enqueue_expired": self._deadline_enqueue_expired,
                "deadline_flush_expired": self._deadline_flush_expired,
                "deadline_wait_expired": self._deadline_wait_expired,
                # brownout folding: requests actually served cheaper
                # than their L0 operating point (serve/degrade.py)
                "degrade_tier_downgrades": self._degrade_tier_downgrades,
                "degrade_bucket_downgrades": self._degrade_bucket_downgrades,
            }
        if lat:
            out["serve_latency_p50_ms"] = round(
                1e3 * lat[int(0.50 * (len(lat) - 1))], 3)
            out["serve_latency_p99_ms"] = round(
                1e3 * lat[int(0.99 * (len(lat) - 1))], 3)
        else:
            out["serve_latency_p50_ms"] = None
            out["serve_latency_p99_ms"] = None
        out["serve_requests_per_s"] = round(recent / _RATE_WINDOW_S, 3)
        # streaming sessions: the serve_sessions_* block + the per-
        # session-frame latency histogram (p50/p99 read off the fixed
        # buckets — obs/export.py percentile_ms — so the figure an
        # operator sees here matches what a fleet-level merge would say)
        out.update(self.sessions.stats())
        # temporal warm-start ledger (engine-owned: the warm/cold
        # decision happens at submit, not in the store); rides the
        # serve_sessions_* block through heartbeat/metrics/analyze/tail
        with self._stats_lock:
            out["serve_sessions_warm_steps"] = self._warm_steps
            out["serve_sessions_cold_fallbacks"] = self._cold_fallbacks
        out["serve_sessions_warm_start"] = self.warm_start
        shist = self._session_hist.snapshot()
        out["serve_session_latency_hist"] = shist
        out["serve_session_latency_p50_ms"] = percentile_ms(shist, 0.50)
        out["serve_session_latency_p99_ms"] = percentile_ms(shist, 0.99)
        # label-free quality block (obs/quality.py): present ONLY when
        # sampling is configured on — sample_rate 0 keeps the serve
        # schema byte-identical to the pre-quality stack
        if self._quality is not None:
            out.update(self._quality.stats())
        # executable-ledger block (obs/ledger.py): lowering/compile/
        # cache counters + per-executable fingerprints + roofline MFU —
        # present only for real-model engines with obs.ledger on, so
        # fake-replica and ledger-off schemas stay byte-identical
        if self._ledger is not None:
            out.update(self._ledger.stats())
        # fixed-bucket histogram + SLO state (obs/export.py): the
        # scrapeable /metrics face; replica histograms merge exactly at
        # the router because the buckets are fixed by contract
        hist = self._hist.snapshot()
        out["serve_latency_hist"] = hist
        if float(self.cfg.obs.slo_latency_ms) > 0:
            with self._stats_lock:
                requests, failures = self._requests, self._server_errors
            out["serve_slo"] = slo_state(
                hist, requests, failures,
                self.cfg.obs.slo_latency_ms,
                self.cfg.obs.slo_error_budget)
        # incident plane: the verdicts this stats pass just computed
        # become flight-recorder triggers (dedup windows make the
        # heartbeat-cadence re-evaluation safe), and the incident_*/
        # alert_* block rides the same stats surface to /metrics
        rec = self.incidents
        if rec is not None:
            slo = out.get("serve_slo")
            if slo and slo.get("exhausted"):
                rec.record("slo_exhausted", "critical",
                           trigger={"slo": slo})
            q = out.get("serve_quality")
            if q and q.get("exhausted"):
                rec.record("quality_drift", "critical",
                           trigger={"quality": q})
            out.update(rec.stats())
        return out

    def heartbeat_sample(self) -> dict:
        """Heartbeat `sample` callback — same keys as stats()."""
        return self.stats()

    # ------------------------------------------------------------- close
    def close(self) -> None:
        """Flush everything already queued, then stop the batcher.
        Idempotent; submissions after close fail with engine_closed."""
        with self._stats_lock:
            if self._closed:
                return
            self._closed = True
        self.sessions.close()  # stop the TTL sweeper thread
        # drains in order: queued work still serves. The put can block on
        # a full queue only until the batcher frees a slot (it is still
        # consuming at this point).
        self._q.put(_STOP)
        self._thread.join(timeout=60.0)
        if self._deep_verify_thread is not None:
            # stop the verifier before the ledger flush: an in-progress
            # verification finishes (its row lands), queued-but-unstarted
            # ones stay pending (visible as exec_deep_verify_pending)
            self._deep_verify_stop.set()  # skip any pacing wait
            self._deep_verify_q.put(None)
            self._deep_verify_thread.join(timeout=30.0)
        if self._ledger is not None:
            # after the batcher join: every flush's note_exec has landed,
            # so the exec_timing rows carry the full run's measurements
            self._ledger.flush()
        if self._quality is not None:
            # AFTER the batcher join: drained flushes still submit
            # samples, and the scorer's exit sentinel must queue behind
            # them — the shutdown stats record (server.py's final serve
            # record) sees every tail-of-run sample scored, not
            # abandoned mid-queue.
            self._quality.close()  # stop the quality scorer thread
        # submitters that passed the closed check before we flipped it
        # may still complete a put; wait them out, then fail any request
        # the (now dead) batcher will never see
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with self._stats_lock:
                if self._submitting == 0:
                    break
            time.sleep(0.01)
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if req is not _STOP:
                self._fail(req.future, ServeError(
                    "engine_closed", "engine shut down before dispatch",
                    req.rid))

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
