"""Bilinear backward warping — the core "kernel" op.

Semantics match the reference's TF graph construction at
`flyingChairsWrapFlow.py:785-838` exactly, but fully vectorized (one fused
XLA gather instead of the reference's O(batch * channels) python-loop graph
nodes):

  - flow channel 0 = u = horizontal displacement (added to the x/width
    coordinate), channel 1 = v = vertical (y/height);
  - the *already scaled* flow is split into integer floor + fractional
    weights;
  - each of the four neighbor coordinates is clipped to the image border
    independently (clip-at-border, NOT zero-fill outside);
  - the four neighbors are blended bilinearly.

`backward_warp(next_frame, flow)` returns the next frame warped backward to
the previous frame's coordinates ("reconstructs" in the reference).

TPU note: XLA lowers `jnp.take_along_axis` over the flattened H*W axis to a
single dynamic-gather, which is the right tool for fine pyramid levels
(Mosaic cannot express arbitrary-displacement gathers — see
`ops/pallas/warp.py`). For coarse levels (W <= 128) the Pallas row-sweep
kernel computes the same warp in one VMEM pass; select it with
`impl="pallas"` or `impl="auto"`.
"""

from __future__ import annotations

import jax.numpy as jnp

#: levels at least this small on both sides use the Pallas kernel under
#: impl="auto" (W must fit one 128-lane register; the 2H-1 row sweep is
#: what bounds the kernel's cost, so very tall-narrow inputs stay on XLA).
PALLAS_AUTO_MAX_H = 64


def _gather_hw(img_flat: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """img_flat: (B, H*W, C); idx: (B, H*W) int32 -> (B, H*W, C)."""
    return jnp.take_along_axis(img_flat, idx[..., None], axis=1)


def backward_warp(image: jnp.ndarray, flow: jnp.ndarray,
                  impl: str = "xla") -> jnp.ndarray:
    """Warp `image` (B, H, W, C) backward by `flow` (B, H, W, 2).

    `flow` must already include any flow_scale factor (the caller applies it,
    as the reference does at `flyingChairsWrapFlow.py:785`).

    impl: "xla" (fused XLA gather, any size), "pallas" (VMEM row-sweep
    kernel, requires W <= 128), or "auto" (pallas for small levels).
    """
    b, h, w, c = image.shape
    if impl == "pallas" or (impl == "auto" and w <= 128
                            and h <= PALLAS_AUTO_MAX_H):
        from .pallas.warp import backward_warp_pallas

        return backward_warp_pallas(image, flow)
    elif impl not in ("xla", "auto"):
        raise ValueError(f"unknown warp impl {impl!r}")
    img_flat = image.reshape(b, h * w, c)
    flow_flat = flow.reshape(b, h * w, 2)

    floor_flow = jnp.floor(flow_flat)
    frac = flow_flat - floor_flow
    fx = floor_flow[..., 0].astype(jnp.int32)  # u -> x offset
    fy = floor_flow[..., 1].astype(jnp.int32)  # v -> y offset
    wx = frac[..., 0][..., None]
    wy = frac[..., 1][..., None]

    # Flat pixel grid: x = column index, y = row index.
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.int32),
                          jnp.arange(w, dtype=jnp.int32), indexing="ij")
    pos_x = xs.reshape(-1)[None, :]  # (1, H*W)
    pos_y = ys.reshape(-1)[None, :]

    x0 = jnp.clip(pos_x + fx, 0, w - 1)
    x1 = jnp.clip(pos_x + fx + 1, 0, w - 1)
    y0 = jnp.clip(pos_y + fy, 0, h - 1)
    y1 = jnp.clip(pos_y + fy + 1, 0, h - 1)

    ia = _gather_hw(img_flat, y0 * w + x0)
    ib = _gather_hw(img_flat, y1 * w + x0)
    ic = _gather_hw(img_flat, y0 * w + x1)
    id_ = _gather_hw(img_flat, y1 * w + x1)

    out = (ia * (1 - wx) * (1 - wy) + ib * (1 - wx) * wy
           + ic * wx * (1 - wy) + id_ * wx * wy)
    return out.reshape(b, h, w, c)


def backward_warp_volume(volume: jnp.ndarray, flows: jnp.ndarray,
                         impl: str = "xla") -> jnp.ndarray:
    """Multi-frame warp (reference `sintelWrapFlow.py:539-577` semantics).

    volume: (B, H, W, 3*T) channel-stacked frames; flows: (B, H, W, 2*(T-1)).
    Reconstructs frame t from frame t+1 using flow pair t, for t in [0, T-1):
    returns (B, H, W, 3*(T-1)) — channel c is gathered from volume channel
    c+3 using flow channels (2*(c//3), 2*(c//3)+1).
    """
    from ..parallel.spatial import pair_axis_constraint

    b, h, w, c3t = volume.shape
    t = c3t // 3
    frames = volume.reshape(b, h, w, t, 3)
    pairs = flows.reshape(b, h, w, t - 1, 2)
    # Fold the pair axis into batch: warp all (T-1) next-frames at once, and
    # shard the folded axis over ("data", "time") so the independent pair
    # warps run pair-parallel across the mesh (SURVEY.md §5.7a).
    nxt = pair_axis_constraint(
        jnp.moveaxis(frames[..., 1:, :], 3, 1).reshape(b * (t - 1), h, w, 3))
    flw = pair_axis_constraint(
        jnp.moveaxis(pairs, 3, 1).reshape(b * (t - 1), h, w, 2))
    rec = backward_warp(nxt, flw, impl=impl).reshape(b, t - 1, h, w, 3)
    return jnp.moveaxis(rec, 1, 3).reshape(b, h, w, 3 * (t - 1))
