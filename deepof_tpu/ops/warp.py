"""Bilinear backward warping — the core "kernel" op.

Semantics match the reference's TF graph construction at
`flyingChairsWrapFlow.py:785-838` exactly, but fully vectorized (one fused
XLA gather instead of the reference's O(batch * channels) python-loop graph
nodes):

  - flow channel 0 = u = horizontal displacement (added to the x/width
    coordinate), channel 1 = v = vertical (y/height);
  - the *already scaled* flow is split into integer floor + fractional
    weights;
  - each of the four neighbor coordinates is clipped to the image border
    independently (clip-at-border, NOT zero-fill outside);
  - the four neighbors are blended bilinearly.

`backward_warp(next_frame, flow)` returns the next frame warped backward to
the previous frame's coordinates ("reconstructs" in the reference).

TPU note: XLA lowers `jnp.take_along_axis` over the flattened H*W axis to a
single dynamic-gather, which is the right tool for fine pyramid levels
(Mosaic cannot express arbitrary-displacement gathers — see
`ops/pallas/warp.py`). For coarse levels (W <= 128) the Pallas row-sweep
kernel computes the same warp in one VMEM pass; select it with
`impl="pallas"` or `impl="auto"`.

Gather-cost note: a TPU gather's cost scales with the index count times
the gathered-row width, and narrow rows waste the 128-lane datapath. The
naive formulation issues FOUR gathers of C(=3)-wide rows (one per
bilinear neighbor, 3/128 lane utilization). The XLA path here instead
packs the 2x2 neighborhood into channels with two edge-clamped shifts
(patch = [img, img_x+1, img_y+1, img_xy], a (B,H,W,4C) tensor built by
cheap rolls) and issues ONE gather of 4C-wide rows at the (y0, x0) base
address: 4x fewer indices, 4x wider rows. Border exactness: the shifted
channels give neighbor min(x0+1, w-1) instead of the reference's
x1 = clip(x+fx+1), which differ only when x+fx < 0 (both collapse to
column 0 there); zeroing the fractional weight on that saturated side
reproduces the reference's value AND its (zero) flow gradient exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: impl="auto" routes to the Pallas kernel when W <= 128 (the kernel's
#: hard limit: one 128-lane register) AND H <= 128. Measured on v5e
#: (perf_probe warp section, r03): the kernel beats the XLA gather at
#: every real pyramid level it admits (40x56 and 80x112, fwd and grad —
#: no admissible level is taller than 80). The H cap is a safety fence,
#: not a tuning knob: the kernel holds whole (Hp, 128) planes in VMEM
#: and its row sweep is a serial 2H-1 loop, so a tall-narrow input
#: (e.g. 4096x64) would compile slowly or not at all — such shapes fall
#: back to the XLA patch-gather instead.
PALLAS_AUTO_MAX_W = 128
PALLAS_AUTO_MAX_H = 128


def backward_warp(image: jnp.ndarray, flow: jnp.ndarray,
                  impl: str = "xla") -> jnp.ndarray:
    """Warp `image` (B, H, W, C) backward by `flow` (B, H, W, 2).

    `flow` must already include any flow_scale factor (the caller applies it,
    as the reference does at `flyingChairsWrapFlow.py:785`).

    impl: "xla" (one fused patch-gather, any size; the function default —
    golden tests and the Pallas image-cotangent fallback reference it),
    "pallas" (VMEM row-sweep kernel, requires W <= 128), or "auto"
    (pallas where admissible, xla for fine levels — the measured-fastest
    choice and the `LossConfig.warp_impl` default).
    """
    b, h, w, c = image.shape
    # "auto" = the measured-fastest choice, and the measurement is a TPU
    # measurement: off-TPU the kernel only exists in interpret mode
    # (python-level emulation, ~10-100x slower than the XLA gather — it
    # silently dominated the CPU-mesh test suite's runtime before this
    # gate). Explicit impl="pallas" still honors the request anywhere,
    # which is what the kernel's correctness tests use.
    if impl == "pallas" or (impl == "auto" and w <= PALLAS_AUTO_MAX_W
                            and h <= PALLAS_AUTO_MAX_H
                            and jax.default_backend() == "tpu"):
        from .pallas.warp import backward_warp_pallas

        return backward_warp_pallas(image, flow)
    elif impl not in ("xla", "auto"):
        raise ValueError(f"unknown warp impl {impl!r}")
    flow_flat = flow.reshape(b, h * w, 2)

    floor_flow = jnp.floor(flow_flat)
    frac = flow_flat - floor_flow
    fx = floor_flow[..., 0].astype(jnp.int32)  # u -> x offset
    fy = floor_flow[..., 1].astype(jnp.int32)  # v -> y offset

    # Flat pixel grid: x = column index, y = row index.
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.int32),
                          jnp.arange(w, dtype=jnp.int32), indexing="ij")
    pos_x = xs.reshape(-1)[None, :]  # (1, H*W)
    pos_y = ys.reshape(-1)[None, :]

    x0 = jnp.clip(pos_x + fx, 0, w - 1)
    y0 = jnp.clip(pos_y + fy, 0, h - 1)
    # Left/top saturation: the reference's independently clipped +1
    # neighbor collapses onto x0/y0 there; the patch channels instead hold
    # column/row 1 — zeroing the fractional weight on the saturated side
    # restores exact value and (zero) flow-gradient. Right/bottom
    # saturation needs nothing: min(x0+1, w-1) == clip(x+fx+1) there.
    wx = jnp.where(pos_x + fx < 0, 0.0, frac[..., 0])[..., None]
    wy = jnp.where(pos_y + fy < 0, 0.0, frac[..., 1])[..., None]

    # 2x2 neighborhood packed into channels by edge-clamped shifts, then
    # ONE gather of (B, H*W) indices over 4C-wide rows (see module note).
    img_x = jnp.concatenate([image[:, :, 1:], image[:, :, -1:]], axis=2)
    img_y = jnp.concatenate([image[:, 1:], image[:, -1:]], axis=1)
    img_xy = jnp.concatenate([img_x[:, 1:], img_x[:, -1:]], axis=1)
    patch = jnp.concatenate([image, img_x, img_y, img_xy], axis=-1)
    g = jnp.take_along_axis(patch.reshape(b, h * w, 4 * c),
                            (y0 * w + x0)[..., None], axis=1)
    ia, ic, ib, id_ = (g[..., :c], g[..., c:2 * c],
                       g[..., 2 * c:3 * c], g[..., 3 * c:])

    out = (ia * (1 - wx) * (1 - wy) + ib * (1 - wx) * wy
           + ic * wx * (1 - wy) + id_ * wx * wy)
    return out.reshape(b, h, w, c)


def backward_warp_volume(volume: jnp.ndarray, flows: jnp.ndarray,
                         impl: str = "xla") -> jnp.ndarray:
    """Multi-frame warp (reference `sintelWrapFlow.py:539-577` semantics).

    volume: (B, H, W, 3*T) channel-stacked frames; flows: (B, H, W, 2*(T-1)).
    Reconstructs frame t from frame t+1 using flow pair t, for t in [0, T-1):
    returns (B, H, W, 3*(T-1)) — channel c is gathered from volume channel
    c+3 using flow channels (2*(c//3), 2*(c//3)+1).
    """
    from ..parallel.spatial import pair_axis_constraint

    b, h, w, c3t = volume.shape
    t = c3t // 3
    frames = volume.reshape(b, h, w, t, 3)
    pairs = flows.reshape(b, h, w, t - 1, 2)
    # Fold the pair axis into batch: warp all (T-1) next-frames at once, and
    # shard the folded axis over ("data", "time") so the independent pair
    # warps run pair-parallel across the mesh (SURVEY.md §5.7a).
    nxt = pair_axis_constraint(
        jnp.moveaxis(frames[..., 1:, :], 3, 1).reshape(b * (t - 1), h, w, 3))
    flw = pair_axis_constraint(
        jnp.moveaxis(pairs, 3, 1).reshape(b * (t - 1), h, w, 2))
    rec = backward_warp(nxt, flw, impl=impl).reshape(b, t - 1, h, w, 3)
    return jnp.moveaxis(rec, 1, 3).reshape(b, h, w, 3 * (t - 1))
