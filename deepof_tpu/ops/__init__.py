from .warp import backward_warp, backward_warp_volume  # noqa: F401
from .lrn import local_response_normalization  # noqa: F401
from .smoothness import forward_diff_x, forward_diff_y, sobel_gradients  # noqa: F401
