"""Local response normalization (ACROSS_CHANNELS).

The reference normalizes the photometric-loss inputs with TF's LRN at
depth_radius=4, beta=0.7, default bias=1, alpha=1
(`flyingChairsWrapFlow.py:25-26`). Flax has no stock LRN; implemented
directly:

  out[..., d] = x[..., d] / (bias + alpha * sum_{i=d-r}^{d+r} x[..., i]^2) ** beta

For 3-channel images and r=4 the window covers all channels, so the
denominator is shared across channels.
"""

from __future__ import annotations

import jax.numpy as jnp


def local_response_normalization(
    x: jnp.ndarray,
    depth_radius: int = 4,
    bias: float = 1.0,
    alpha: float = 1.0,
    beta: float = 0.7,
) -> jnp.ndarray:
    c = x.shape[-1]
    sq = jnp.square(x)
    if depth_radius >= c - 1:
        window_sum = jnp.sum(sq, axis=-1, keepdims=True)
    else:
        # windowed channel sum via padded cumulative sum (static shapes)
        pad = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(depth_radius + 1, depth_radius)])
        cs = jnp.cumsum(pad, axis=-1)
        window_sum = (
            jnp.take(cs, jnp.arange(c) + 2 * depth_radius + 1, axis=-1)
            - jnp.take(cs, jnp.arange(c), axis=-1)
        )
    return x / jnp.power(bias + alpha * window_sum, beta)
