"""Correlation / cost-volume op for FlowNet-C.

New capability (no reference implementation; spec from the FlowNet paper,
arXiv:1504.06852 §3: multiplicative patch comparison): for displacements
(dy, dx) on a (2K+1)x(2K+1) grid with stride `stride` where K = max_disp //
stride,

    corr[b, y, x, i] = mean_c f1[b, y, x, c] * f2[b, y+dy_i, x+dx_i, c]

out-of-range f2 positions contribute zero. Implemented as a `vmap` over the
displacement grid with `dynamic_slice` into a zero-padded f2 — static
shapes, data-parallel across displacements so XLA can fuse/parallelize (a
`lax.scan` here would serialize the 441 steps). The output is (n*n, B, H, W)
either way, so peak memory is unchanged. A fused Pallas kernel is planned in
`ops/pallas/corr.py`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def correlation(
    f1: jnp.ndarray,
    f2: jnp.ndarray,
    max_disp: int = 20,
    stride: int = 2,
    impl: str = "auto",
) -> jnp.ndarray:
    """f1, f2: (B, H, W, C) -> (B, H, W, (2K+1)**2), K = max_disp // stride.

    impl: "auto" picks the fused Pallas kernel on TPU (one HBM read of f2
    instead of one per displacement) and the XLA sweep elsewhere.
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas":
        from .pallas.corr import correlation_pallas

        return correlation_pallas(f1, f2, max_disp, stride)
    b, h, w, c = f1.shape
    k = max_disp // stride
    n = 2 * k + 1
    pad = k * stride
    f2p = jnp.pad(f2, ((0, 0), (pad, pad), (pad, pad), (0, 0)))

    offsets = jnp.arange(n) * stride  # dy/dx offsets into the padded array
    dydx = jnp.stack(jnp.meshgrid(offsets, offsets, indexing="ij"), -1).reshape(-1, 2)

    def one(off):
        sl = lax.dynamic_slice(f2p, (0, off[0], off[1], 0), (b, h, w, c))
        return jnp.mean(f1 * sl, axis=-1)

    maps = jax.vmap(one)(dydx)  # (n*n, B, H, W)
    return jnp.moveaxis(maps, 0, -1)


def correlation_oracle(f1, f2, max_disp=20, stride=2):
    """Slow numpy oracle for tests."""
    import numpy as np

    b, h, w, c = f1.shape
    k = max_disp // stride
    n = 2 * k + 1
    out = np.zeros((b, h, w, n * n), f1.dtype)
    for i, dy in enumerate(range(-k * stride, k * stride + 1, stride)):
        for j, dx in enumerate(range(-k * stride, k * stride + 1, stride)):
            for y in range(h):
                for x in range(w):
                    yy, xx = y + dy, x + dx
                    if 0 <= yy < h and 0 <= xx < w:
                        out[:, y, x, i * n + j] = (f1[:, y, x] * f2[:, yy, xx]).mean(-1)
    return out
