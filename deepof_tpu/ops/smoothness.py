"""Flow / image spatial-gradient helpers for the smoothness losses.

The reference expresses these as 3x3 conv / depthwise-conv filters
(`flyingChairsWrapFlow_vgg.py:52-59` flow_width/height filters,
`flyingChairsWrapFlow.py:48` FlowDeltaWeights, sobel filters at
`flyingChairsWrapFlow_vgg.py:63-69`). On TPU these are pure shift-subtract
elementwise ops — cheaper than convolutions and fused by XLA.

Conventions (match the intended filter semantics, cross-correlation with
SAME zero padding):
  forward_diff_x(f)[y, x] = f[y, x] - f[y, x+1]   (last column: f[y, x] - 0)
  forward_diff_y(f)[y, x] = f[y, x] - f[y+1, x]   (last row:    f[y, x] - 0)

Note: the reference's gen-2 `FlowDeltaWeights` constant supplies only 18 of
the 36 values of its declared [3,3,2,2] shape; TF fills the remainder with
the trailing zero, which silently distorts the filter (V channel unused and
a diagonal difference on U). We implement the *intended* semantics — x-diff
of U, y-diff of V — which is also what the reference's own depthwise-filter
variants (`flyingChairsWrapFlow_vgg.py:52-59`, `version1/model/warpflow.py`)
compute. Divergence documented here per SURVEY.md §7.3.
"""

from __future__ import annotations

import jax.numpy as jnp

# TF rgb_to_grayscale weights, applied to channels as stored. The reference
# feeds BGR images (cv2) through tf.image.rgb_to_grayscale
# (`version1/model/warpflow.py:105`), so the weights land on swapped
# channels; we reproduce that exact behavior.
_GRAY_WEIGHTS = jnp.array([0.2989, 0.587, 0.114])


def forward_diff_x(f: jnp.ndarray) -> jnp.ndarray:
    """f[..., H, W, C] -> f - shift_left(f) with zero fill at the last column."""
    shifted = jnp.pad(f[..., :, 1:, :], [(0, 0)] * (f.ndim - 3) + [(0, 0), (0, 1), (0, 0)])
    return f - shifted


def forward_diff_y(f: jnp.ndarray) -> jnp.ndarray:
    """f[..., H, W, C] -> f - shift_up(f) with zero fill at the last row."""
    shifted = jnp.pad(f[..., 1:, :, :], [(0, 0)] * (f.ndim - 3) + [(0, 1), (0, 0), (0, 0)])
    return f - shifted


def second_diff_x(f: jnp.ndarray) -> jnp.ndarray:
    """Second difference along W: f[x-1] - 2 f[x] + f[x+1], zero fill at
    both edge columns (the caller masks them out). Zero for any flow
    affine in x — the 2nd-order smoothness prior penalizes curvature,
    not slope, so fronto-parallel motion gradients are free."""
    left = jnp.pad(f[..., :, :-1, :], [(0, 0)] * (f.ndim - 3) + [(0, 0), (1, 0), (0, 0)])
    right = jnp.pad(f[..., :, 1:, :], [(0, 0)] * (f.ndim - 3) + [(0, 0), (0, 1), (0, 0)])
    return left - 2.0 * f + right


def second_diff_y(f: jnp.ndarray) -> jnp.ndarray:
    """Second difference along H (see second_diff_x)."""
    up = jnp.pad(f[..., :-1, :, :], [(0, 0)] * (f.ndim - 3) + [(1, 0), (0, 0), (0, 0)])
    down = jnp.pad(f[..., 1:, :, :], [(0, 0)] * (f.ndim - 3) + [(0, 1), (0, 0), (0, 0)])
    return up - 2.0 * f + down


def sobel_gradients(gray: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """3x3 Sobel x/y gradients of (B, H, W, 1), SAME zero padding.

    Matches tf.nn.depthwise_conv2d with sobel_x = [[-1,0,1],[-2,0,2],[-1,0,1]]
    and sobel_y = its transpose (`flyingChairsWrapFlow_vgg.py:63-69`),
    expressed as shift-adds.
    """

    def shift(a, dy, dx):
        a = a[..., 0]  # (B, H, W)
        h, w = a.shape[-2:]
        pad_y = (max(-dy, 0), max(dy, 0))
        pad_x = (max(-dx, 0), max(dx, 0))
        a = jnp.pad(a, [(0, 0)] * (a.ndim - 2) + [pad_y, pad_x])
        y0 = max(dy, 0)
        x0 = max(dx, 0)
        return a[..., y0 : y0 + h, x0 : x0 + w][..., None]

    # cross-correlation: out(y,x) = sum_k k(dy,dx) * in(y+dy-1, x+dx-1)
    def cc(kernel):
        out = 0.0
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                kv = kernel[dy + 1][dx + 1]
                if kv:
                    out = out + kv * shift(gray, dy, dx)
        return out

    sx = cc([[-1, 0, 1], [-2, 0, 2], [-1, 0, 1]])
    sy = cc([[-1, -2, -1], [0, 0, 0], [1, 2, 1]])
    return sx, sy


def to_grayscale(img: jnp.ndarray) -> jnp.ndarray:
    """(B, H, W, 3) -> (B, H, W, 1) with TF grayscale weights."""
    return jnp.tensordot(img, _GRAY_WEIGHTS, axes=[[-1], [0]])[..., None]
