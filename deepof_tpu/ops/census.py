"""Soft census transform + distance — an illumination-robust photometric
penalty (opt-in alternative to the reference's raw-RGB Charbonnier).

The reference compares warped and target frames directly in RGB
(`flyingChairsWrapFlow.py:841-851`), which is brittle under the
brightness-constancy violations real video has (shadows, exposure).
The census transform describes each pixel by the *signs* of its
differences to a window of neighbors, so any monotonic per-image
intensity change leaves the descriptor (nearly) unchanged. This is the
standard robustness upgrade in modern unsupervised flow (census/ternary
losses of DDFlow/SelFlow/UFlow lineage, PAPERS.md) and is a pure
elementwise+shift computation — no gathers — so it maps cleanly onto
the VPU.

All ops are static-shape jnp (shifted static slices, XLA-fusable).
"""

from __future__ import annotations

import jax.numpy as jnp

from .smoothness import to_grayscale


def census_transform(images: jnp.ndarray, window: int = 7,
                     eps: float = 0.81) -> jnp.ndarray:
    """Soft census descriptors: (B, H, W, C) -> (B, H, W, window**2).

    Per pixel, for every offset o in the window:
        f_o = d_o / sqrt(eps + d_o^2),  d_o = gray(p+o) - gray(p)
    (normalized differences saturate toward the sign bit of classic
    census while staying differentiable). Edge padding replicates border
    rows/cols; the caller's border mask excludes those pixels anyway.
    """
    gray = to_grayscale(images * 255.0)  # census operates on intensities
    b, h, w, _ = gray.shape
    r = window // 2
    padded = jnp.pad(gray, ((0, 0), (r, r), (r, r), (0, 0)), mode="edge")
    shifted = [
        padded[:, dy : dy + h, dx : dx + w, :]
        for dy in range(window)
        for dx in range(window)
    ]
    neighbors = jnp.concatenate(shifted, axis=-1)  # (B,H,W,window^2)
    d = neighbors - gray
    return d / jnp.sqrt(eps + jnp.square(d))


def census_distance(a: jnp.ndarray, b: jnp.ndarray,
                    thresh: float = 0.1) -> jnp.ndarray:
    """Soft Hamming distance between census descriptors.

    (B, H, W, K) x2 -> (B, H, W, 1): sum_k  d_k^2 / (thresh + d_k^2),
    each term in [0, 1) — a robust (saturating) per-neighbor penalty.
    """
    d2 = jnp.square(a - b)
    return jnp.sum(d2 / (thresh + d2), axis=-1, keepdims=True)
