"""Pallas TPU kernels for the framework's hot ops.

Kernel inventory (and why each op is/isn't a kernel):

  - `corr.py` — FlowNet-C correlation / cost volume. The (2K+1)^2
    displacement sweep re-reads the second feature map hundreds of times;
    the XLA `dynamic_slice` formulation pays HBM traffic per displacement,
    while the kernel holds one haloed row-window of f2 in VMEM and sweeps
    all displacements from on-chip memory.

  - The bilinear warp (`ops/warp.py`) deliberately stays an XLA gather:
    flow magnitude is unbounded (the reference clips eval flow at +-300 px,
    `flyingChairsTrain.py:265`), so windowed VMEM loads cannot be sized
    statically without changing semantics, and a one-hot matmul
    decomposition is impossible for jointly spatially-varying (u, v) index
    fields. XLA lowers the single fused `take_along_axis` gather natively;
    the surrounding Charbonnier/smoothness elementwise+reduce work fuses
    into it.
"""

from .corr import correlation_pallas

__all__ = ["correlation_pallas"]
