"""Fused Pallas correlation (cost-volume) kernel for FlowNet-C.

Semantics identical to `ops.corr.correlation` (FlowNet paper §3,
arXiv:1504.06852): for a (2K+1)x(2K+1) displacement grid with stride s,

    corr[b, y, x, i*n+j] = mean_c f1[b,y,x,c] * f2[b, y+dy_i, x+dx_j, c]

with zero contribution outside f2's bounds.

Kernel design (TPU-first):
  - grid = (B, H/TILE_H). Per step, the f1 row-tile lives in VMEM via
    BlockSpec; the zero-padded f2 stays in HBM/ANY and ONE haloed row
    window (TILE_H + 2*pad rows) is DMA'd into VMEM scratch.
  - the (2K+1)^2 displacement sweep then runs entirely from VMEM: each
    displacement is a static-size dynamic slice of the window, an
    elementwise product with the f1 tile, and a channel reduction on the
    VPU. The XLA formulation pays an HBM round-trip per displacement
    ((2K+1)^2 = 441 reads of f2); here f2 is read from HBM exactly once.
  - output layout is (B, n*n, H, W): the displacement index is the
    *leading* (untiled) axis of the block so the per-displacement store is
    a plain row write, not a lane-dimension scatter. The public wrapper
    transposes to the model's (B, H, W, n*n) layout.
  - backward: `correlation_pallas` carries a custom VJP whose adjoints are
    expressed with the same displacement-sweep structure in XLA (gradients
    flow through both feature maps); the forward hot path is the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec as P


def _sweep_offsets(n: int, stride: int) -> jnp.ndarray:
    offs = jnp.arange(n) * stride
    return jnp.stack(jnp.meshgrid(offs, offs, indexing="ij"), -1).reshape(-1, 2)


def _corr_kernel(f1_ref, f2p_ref, out_ref, win_ref, sem, *,
                 n: int, stride: int, tile_h: int, w: int, c: int):
    b = pl.program_id(0)
    t = pl.program_id(1)

    # One haloed window of padded f2: rows [t*TILE_H, t*TILE_H + TILE_H+2p).
    dma = pltpu.make_async_copy(
        f2p_ref.at[b, pl.ds(t * tile_h, win_ref.shape[0])], win_ref, sem)
    dma.start()
    dma.wait()

    f1 = f1_ref[0].astype(jnp.float32)  # (TILE_H, W, C)
    inv_c = 1.0 / c

    def body(idx, _):
        dy = (idx // n) * stride
        dx = (idx % n) * stride
        sl = win_ref[pl.ds(dy, tile_h), pl.ds(dx, w), :].astype(jnp.float32)
        out_ref[0, idx] = jnp.sum(f1 * sl, axis=-1) * inv_c
        return 0

    lax.fori_loop(0, n * n, body, 0)


def _pallas_corr_fwd(f1: jnp.ndarray, f2: jnp.ndarray, max_disp: int,
                     stride: int, tile_h: int, interpret: bool) -> jnp.ndarray:
    b, h, w, c = f1.shape
    k = max_disp // stride
    n = 2 * k + 1
    pad = k * stride

    h_pad = (-h) % tile_h
    if h_pad:
        f1 = jnp.pad(f1, ((0, 0), (0, h_pad), (0, 0), (0, 0)))
        f2 = jnp.pad(f2, ((0, 0), (0, h_pad), (0, 0), (0, 0)))
    hp = h + h_pad
    f2p = jnp.pad(f2, ((0, 0), (pad, pad), (pad, pad), (0, 0)))

    grid = (b, hp // tile_h)
    out = pl.pallas_call(
        functools.partial(_corr_kernel, n=n, stride=stride, tile_h=tile_h,
                          w=w, c=c),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile_h, w, c), lambda bi, ti: (bi, ti, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),  # padded f2, windowed DMA
        ],
        out_specs=pl.BlockSpec((1, n * n, tile_h, w),
                               lambda bi, ti: (bi, 0, ti, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, n * n, hp, w), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((tile_h + 2 * pad, w + 2 * pad, c), f2.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(f1, f2p)
    # accumulate in f32, return the input dtype (matches the XLA sweep, so
    # the cost volume's dtype is not backend-dependent under bf16 compute)
    return jnp.moveaxis(out[:, :, :h], 1, -1).astype(f1.dtype)


@functools.lru_cache(maxsize=None)
def _partitioned_fwd(max_disp: int, stride: int, tile_h: int, interpret: bool):
    """Batch-data-parallel partitioning rule for the opaque pallas_call.

    GSPMD cannot see inside a Pallas kernel; without a rule it would
    all-gather and replicate the cost volume on every chip. Correlation is
    independent per batch element (but needs full H/W/C per shard — the
    displacement window crosses any spatial split), so: keep the batch
    axis sharding, replicate everything else, and run the same kernel on
    each per-shard batch slice.
    """
    fwd = custom_partitioning(
        lambda f1, f2: _pallas_corr_fwd(f1, f2, max_disp, stride, tile_h,
                                        interpret))

    def _batch_axis(arg_infos):
        for info in arg_infos:
            sharding = getattr(info, "sharding", None)
            spec = getattr(sharding, "spec", None)
            if spec and len(spec) and spec[0] is not None:
                return spec[0]
        return None

    def infer(mesh, arg_infos, result_infos):
        return NamedSharding(mesh, P(_batch_axis(arg_infos), None, None, None))

    def partition(mesh, arg_infos, result_infos):
        sh = NamedSharding(mesh, P(_batch_axis(arg_infos), None, None, None))

        def lower(f1, f2):
            return _pallas_corr_fwd(f1, f2, max_disp, stride, tile_h, interpret)

        return mesh, lower, sh, (sh, sh)

    # Shardy propagation rule: only the batch factor `b` is shardable;
    # spatial/channel/displacement dims must be replicated per shard (the
    # displacement window crosses any spatial split).
    fwd.def_partition(
        infer_sharding_from_operands=infer,
        partition=partition,
        sharding_rule="b h w c, b i j c -> b h w k",
        need_replication_factors=("h", "w", "c", "i", "j", "k"),
    )
    return fwd


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def correlation_pallas(f1, f2, max_disp: int = 20, stride: int = 2,
                       tile_h: int = 8, interpret: bool = False):
    """Pallas cost volume: (B,H,W,C) x2 -> (B,H,W,(2K+1)^2), K=max_disp//stride."""
    return _partitioned_fwd(max_disp, stride, tile_h, interpret)(f1, f2)


def _fwd(f1, f2, max_disp, stride, tile_h, interpret):
    return (_partitioned_fwd(max_disp, stride, tile_h, interpret)(f1, f2),
            (f1, f2))


def _bwd(max_disp, stride, tile_h, interpret, res, g):
    f1, f2 = res
    b, h, w, c = f1.shape
    k = max_disp // stride
    pad = k * stride
    inv_c = 1.0 / c
    offsets = _sweep_offsets(2 * k + 1, stride)
    f2p = jnp.pad(f2, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    gm = jnp.moveaxis(g, -1, 0)  # (n*n, B, H, W)

    hp, wp = h + 2 * pad, w + 2 * pad

    # Accumulate over displacements with a scan: a vmap here would
    # materialize all (2K+1)^2 full-size (B,H,W,C) products at once.
    def step(carry, off_gi):
        df1_acc, df2p_acc = carry
        off, gi = off_gi
        sl = lax.dynamic_slice(f2p, (0, off[0], off[1], 0), (b, h, w, c))
        df1_acc = df1_acc + gi[..., None] * sl * inv_c
        # df2p[y+dy, x+dx] += g[..., i] * f1[y, x] / C
        prod = gi[..., None] * f1 * inv_c
        cur = lax.dynamic_slice(df2p_acc, (0, off[0], off[1], 0), (b, h, w, c))
        df2p_acc = lax.dynamic_update_slice(df2p_acc, cur + prod,
                                            (0, off[0], off[1], 0))
        return (df1_acc, df2p_acc), None

    init = (jnp.zeros((b, h, w, c), jnp.float32),
            jnp.zeros((b, hp, wp, c), jnp.float32))
    (df1, df2p), _ = lax.scan(step, init, (offsets, gm))
    df2 = df2p[:, pad : pad + h, pad : pad + w]
    return df1.astype(f1.dtype), df2.astype(f2.dtype)


correlation_pallas.defvjp(_fwd, _bwd)
