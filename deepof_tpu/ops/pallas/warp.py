"""Fused Pallas bilinear backward-warp kernel (coarse pyramid levels).

Replaces the reference's O(batch * channels) python-loop gather graph
(`flyingChairsWrapFlow.py:799-838`) with a single-VMEM-pass TPU kernel.

Why a *bounded-row-sweep* design instead of a plain gather: Mosaic's
dynamic-gather primitive on TPU only lowers for gathers along the lane
dimension within a single 128-lane register (measured on v5e: a
`take_along_axis(axis=-1)` lowers iff the last dim is exactly 128; wider
rows, sublane-dim gathers, and flattened-image gathers all fail to
compile). An arbitrary-displacement 2D gather therefore cannot be
expressed efficiently in Pallas on this hardware — XLA's native gather
HLO is the right tool for fine levels, and `ops.warp.backward_warp`
(one fused XLA gather) remains the default path.

What *can* be fused exactly: levels whose width fits one lane register
(W <= 128). There the reference's clip-at-border indexing
(`flyingChairsWrapFlow.py:815-818`) bounds the row displacement by H-1
regardless of flow magnitude, so a sweep over the 2H-1 possible row
offsets — each a cheap sublane `roll` + per-lane gather + select — is
*exact* for any flow, needs no semantic displacement cap, and runs
entirely from VMEM: image and flow are read from HBM exactly once per
batch element (the XLA formulation reads the image four times, once per
bilinear neighbor).

Layout: channel-planar (B, C, Hp, 128) so each (Hp, 128) plane is a
well-tiled f32 VMEM operand (8x128 tiles); the public wrapper pads
W -> 128 and H -> multiple of 8 and transposes from/to NHWC. Padded
lanes/rows gather only clipped (valid) addresses and are sliced off.

Backward: the FLOW cotangent — the only one the training loss ever uses
(the warped operand is the target image, i.e. data: its cotangent is
dead code under the loss) — is a second row-sweep kernel with the same
single-VMEM-pass structure and no scatter: gu/gv are elementwise in the
output position once the four bilinear neighbors are gathered, the same
a.e.-derivative XLA autodiff produces (through the blend weights, zero
through floor and clipped indices). The IMAGE cotangent (a bilinear
scatter) is delegated to XLA autodiff of the jnp formulation and is
dead-code-eliminated whenever the image is not differentiated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec as P

LANES = 128


def _bilinear_setup(flow_ref, h: int, w: int, hp: int):
    """Shared index/weight setup for the forward and flow-grad kernels —
    they MUST agree exactly (clip bounds, +1 neighbor offset) for the
    gradient to match the primal. Returns (wx, wy, x0, x1, d0, d1)."""
    u = flow_ref[0, 0]
    v = flow_ref[0, 1]
    fu = jnp.floor(u)
    fv = jnp.floor(v)
    wx = u - fu
    wy = v - fv
    i = lax.broadcasted_iota(jnp.int32, (hp, LANES), 0)
    j = lax.broadcasted_iota(jnp.int32, (hp, LANES), 1)
    x0 = jnp.clip(j + fu.astype(jnp.int32), 0, w - 1)
    x1 = jnp.clip(j + fu.astype(jnp.int32) + 1, 0, w - 1)
    y0 = jnp.clip(i + fv.astype(jnp.int32), 0, h - 1)
    y1 = jnp.clip(i + fv.astype(jnp.int32) + 1, 0, h - 1)
    # d0/d1 in [-(h-1), h-1] by construction (clip shrinks offsets)
    return wx, wy, x0, x1, y0 - i, y1 - i


def _to_planar(x, h: int, w: int, hp: int):
    """NHWC -> channel-planar (B, C, Hp, 128), zero-padded to the kernels'
    block shape."""
    xp = jnp.pad(x.astype(jnp.float32),
                 ((0, 0), (0, hp - h), (0, LANES - w), (0, 0)))
    return jnp.transpose(xp, (0, 3, 1, 2))


def _warp_kernel(img_ref, flow_ref, out_ref, *, h: int, w: int, c: int,
                 hp: int):
    """One batch element: img (1,C,Hp,128), flow (1,2,Hp,128) -> out."""
    wx, wy, x0, x1, d0, d1 = _bilinear_setup(flow_ref, h, w, hp)

    def body(k, accs):
        dy = k - (h - 1)
        shift = (hp - dy) % hp  # roll so row i holds img[(i + dy) % hp]
        m0 = (d0 == dy).astype(jnp.float32)
        m1 = (d1 == dy).astype(jnp.float32)
        wsel = (1.0 - wy) * m0 + wy * m1
        out = []
        for ch in range(c):
            plane = pltpu.roll(img_ref[0, ch], shift, 0)
            g0 = jnp.take_along_axis(plane, x0, axis=1)
            g1 = jnp.take_along_axis(plane, x1, axis=1)
            out.append(accs[ch] + wsel * ((1.0 - wx) * g0 + wx * g1))
        return tuple(out)

    accs = lax.fori_loop(
        0, 2 * h - 1, body,
        tuple(jnp.zeros((hp, LANES), jnp.float32) for _ in range(c)))
    for ch in range(c):
        out_ref[0, ch] = accs[ch]


def _warp_flow_grad_kernel(img_ref, flow_ref, ct_ref, out_ref, *, h: int,
                           w: int, c: int, hp: int):
    """One batch element: img (1,C,Hp,128), flow (1,2,Hp,128), cotangent
    (1,C,Hp,128) -> (1,2,Hp,128) = (dL/du, dL/dv).

    Same bounded row sweep as the forward. With the bilinear blend
    recon = (1-wy)[(1-wx)Ia + wx Ib] + wy[(1-wx)Ic + wx Id]:
      d/du = (1-wy)(Ib-Ia) + wy(Id-Ic)
      d/dv = (1-wx)(Ic-Ia) + wx(Id-Ib)
    where Ia/Ib live on the y0 row (mask m0) and Ic/Id on y1 (m1), so per
    row-offset dy both terms reduce to masked combinations of the two
    lane gathers g0=img[.,x0], g1=img[.,x1] — no scatter anywhere.
    """
    wx, wy, x0, x1, d0, d1 = _bilinear_setup(flow_ref, h, w, hp)

    def body(k, accs):
        au, av = accs
        dy = k - (h - 1)
        shift = (hp - dy) % hp
        m0 = (d0 == dy).astype(jnp.float32)
        m1 = (d1 == dy).astype(jnp.float32)
        wu = (1.0 - wy) * m0 + wy * m1
        wv = m1 - m0
        for ch in range(c):
            plane = pltpu.roll(img_ref[0, ch], shift, 0)
            g0 = jnp.take_along_axis(plane, x0, axis=1)
            g1 = jnp.take_along_axis(plane, x1, axis=1)
            gc = ct_ref[0, ch]
            au = au + gc * wu * (g1 - g0)
            av = av + gc * wv * ((1.0 - wx) * g0 + wx * g1)
        return au, av

    zero = jnp.zeros((hp, LANES), jnp.float32)
    au, av = lax.fori_loop(0, 2 * h - 1, body, (zero, zero))
    out_ref[0, 0] = au
    out_ref[0, 1] = av


def _pallas_warp_flow_grad(image: jnp.ndarray, flow: jnp.ndarray,
                           ct: jnp.ndarray, interpret: bool) -> jnp.ndarray:
    b, h, w, c = image.shape
    hp = -(-h // 8) * 8
    out = pl.pallas_call(
        functools.partial(_warp_flow_grad_kernel, h=h, w=w, c=c, hp=hp),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, c, hp, LANES), lambda bi: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, hp, LANES), lambda bi: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c, hp, LANES), lambda bi: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 2, hp, LANES), lambda bi: (bi, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, 2, hp, LANES), jnp.float32),
        interpret=interpret,
    )(_to_planar(image, h, w, hp), _to_planar(flow, h, w, hp),
      _to_planar(ct, h, w, hp))
    return jnp.transpose(out, (0, 2, 3, 1))[:, :h, :w]


def _pallas_warp_fwd(image: jnp.ndarray, flow: jnp.ndarray,
                     interpret: bool) -> jnp.ndarray:
    b, h, w, c = image.shape
    if w > LANES:
        raise ValueError(
            f"pallas warp requires W <= {LANES} (got {w}); use the XLA path "
            "for fine pyramid levels")
    hp = -(-h // 8) * 8
    imgp = _to_planar(image, h, w, hp)   # (B, C, Hp, 128)
    flowp = _to_planar(flow, h, w, hp)   # (B, 2, Hp, 128)

    out = pl.pallas_call(
        functools.partial(_warp_kernel, h=h, w=w, c=c, hp=hp),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, c, hp, LANES), lambda bi: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, hp, LANES), lambda bi: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, c, hp, LANES), lambda bi: (bi, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, c, hp, LANES), jnp.float32),
        interpret=interpret,
    )(imgp, flowp)
    return jnp.transpose(out, (0, 2, 3, 1))[:, :h, :w].astype(image.dtype)


def _batch_partitioned(lower_fn, n_in: int, sharding_rule: str):
    """Batch-data-parallel custom_partitioning wrapper shared by both warp
    kernels (same rationale as pallas/corr.py: GSPMD cannot see inside a
    kernel; the warp is independent per batch element but the row sweep
    needs the full H per shard)."""
    fn = custom_partitioning(lower_fn)

    def _batch_axis(arg_infos):
        for info in arg_infos:
            sharding = getattr(info, "sharding", None)
            spec = getattr(sharding, "spec", None)
            if spec and len(spec) and spec[0] is not None:
                return spec[0]
        return None

    def infer(mesh, arg_infos, result_infos):
        return NamedSharding(mesh, P(_batch_axis(arg_infos), None, None, None))

    def partition(mesh, arg_infos, result_infos):
        sh = NamedSharding(mesh, P(_batch_axis(arg_infos), None, None, None))
        return mesh, lower_fn, sh, (sh,) * n_in

    fn.def_partition(
        infer_sharding_from_operands=infer,
        partition=partition,
        sharding_rule=sharding_rule,
        need_replication_factors=("h", "w", "c", "k"),
    )
    return fn


@functools.lru_cache(maxsize=None)
def _partitioned_fwd(interpret: bool):
    return _batch_partitioned(
        lambda image, flow: _pallas_warp_fwd(image, flow, interpret),
        n_in=2, sharding_rule="b h w c, b h w k -> b h w c")


@functools.lru_cache(maxsize=None)
def _partitioned_flow_grad(interpret: bool):
    return _batch_partitioned(
        lambda image, flow, ct: _pallas_warp_flow_grad(image, flow, ct,
                                                       interpret),
        n_in=3, sharding_rule="b h w c, b h w k, b h w c -> b h w k")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def backward_warp_pallas(image: jnp.ndarray, flow: jnp.ndarray,
                         interpret: bool | None = None) -> jnp.ndarray:
    """Pallas warp: image (B,H,W,C), *scaled* flow (B,H,W,2) -> (B,H,W,C).

    Exact `ops.warp.backward_warp` semantics for W <= 128 (any flow
    magnitude — border clipping bounds the sweep), including gradients
    with respect to both arguments. interpret=None auto-selects
    interpreter mode off-TPU (CPU test meshes).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _partitioned_fwd(interpret)(image, flow)


def _fwd(image, flow, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _partitioned_fwd(interpret)(image, flow), (image, flow)


def _bwd(interpret, res, g):
    from ..warp import backward_warp  # jnp formulation; same a.e. gradient

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    image, flow = res
    g32 = g.astype(jnp.float32)
    # flow cotangent: the training hot path (the model's only gradient
    # route through the warp) — fused Pallas sweep, no scatter
    gf = _partitioned_flow_grad(interpret)(image, flow, g32)
    # image cotangent: XLA bilinear scatter; under jit it is dead-code-
    # eliminated when the image operand is data (the default loss). Eager
    # op-by-op grads do pay it — debug-only territory
    gi = jax.vjp(lambda im: backward_warp(im, flow, impl="xla"),
                 image)[1](g32)[0]
    return gi.astype(image.dtype), gf.astype(flow.dtype)


backward_warp_pallas.defvjp(_fwd, _bwd)
