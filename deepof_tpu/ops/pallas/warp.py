"""Fused Pallas bilinear backward-warp kernel (coarse pyramid levels).

Replaces the reference's O(batch * channels) python-loop gather graph
(`flyingChairsWrapFlow.py:799-838`) with a single-VMEM-pass TPU kernel.

Why a *bounded-row-sweep* design instead of a plain gather: Mosaic's
dynamic-gather primitive on TPU only lowers for gathers along the lane
dimension within a single 128-lane register (measured on v5e: a
`take_along_axis(axis=-1)` lowers iff the last dim is exactly 128; wider
rows, sublane-dim gathers, and flattened-image gathers all fail to
compile). An arbitrary-displacement 2D gather therefore cannot be
expressed efficiently in Pallas on this hardware — XLA's native gather
HLO is the right tool for fine levels, and `ops.warp.backward_warp`
(one fused XLA gather) remains the default path.

What *can* be fused exactly: levels whose width fits one lane register
(W <= 128). There the reference's clip-at-border indexing
(`flyingChairsWrapFlow.py:815-818`) bounds the row displacement by H-1
regardless of flow magnitude, so a sweep over the 2H-1 possible row
offsets — each a cheap sublane `roll` + per-lane gather + select — is
*exact* for any flow, needs no semantic displacement cap, and runs
entirely from VMEM: image and flow are read from HBM exactly once per
batch element (the XLA formulation reads the image four times, once per
bilinear neighbor).

Layout: channel-planar (B, C, Hp, 128) so each (Hp, 128) plane is a
well-tiled f32 VMEM operand (8x128 tiles); the public wrapper pads
W -> 128 and H -> multiple of 8 and transposes from/to NHWC. Padded
lanes/rows gather only clipped (valid) addresses and are sliced off.

Backward: the VJP re-derives both cotangents (image and flow) via XLA
autodiff of the jnp formulation — identical gradient semantics to the
XLA path (flow grads through the bilinear blend weights, the same
a.e.-derivative the reference's TF autodiff produced; image grads are
the bilinear scatter); the forward hot path is the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec as P

LANES = 128


def _warp_kernel(img_ref, flow_ref, out_ref, *, h: int, w: int, c: int,
                 hp: int):
    """One batch element: img (1,C,Hp,128), flow (1,2,Hp,128) -> out."""
    u = flow_ref[0, 0]
    v = flow_ref[0, 1]
    fu = jnp.floor(u)
    fv = jnp.floor(v)
    wx = u - fu
    wy = v - fv
    i = lax.broadcasted_iota(jnp.int32, (hp, LANES), 0)
    j = lax.broadcasted_iota(jnp.int32, (hp, LANES), 1)
    x0 = jnp.clip(j + fu.astype(jnp.int32), 0, w - 1)
    x1 = jnp.clip(j + fu.astype(jnp.int32) + 1, 0, w - 1)
    y0 = jnp.clip(i + fv.astype(jnp.int32), 0, h - 1)
    y1 = jnp.clip(i + fv.astype(jnp.int32) + 1, 0, h - 1)
    d0 = y0 - i  # in [-(h-1), h-1] by construction (clip shrinks offsets)
    d1 = y1 - i

    def body(k, accs):
        dy = k - (h - 1)
        shift = (hp - dy) % hp  # roll so row i holds img[(i + dy) % hp]
        m0 = (d0 == dy).astype(jnp.float32)
        m1 = (d1 == dy).astype(jnp.float32)
        wsel = (1.0 - wy) * m0 + wy * m1
        out = []
        for ch in range(c):
            plane = pltpu.roll(img_ref[0, ch], shift, 0)
            g0 = jnp.take_along_axis(plane, x0, axis=1)
            g1 = jnp.take_along_axis(plane, x1, axis=1)
            out.append(accs[ch] + wsel * ((1.0 - wx) * g0 + wx * g1))
        return tuple(out)

    accs = lax.fori_loop(
        0, 2 * h - 1, body,
        tuple(jnp.zeros((hp, LANES), jnp.float32) for _ in range(c)))
    for ch in range(c):
        out_ref[0, ch] = accs[ch]


def _pallas_warp_fwd(image: jnp.ndarray, flow: jnp.ndarray,
                     interpret: bool) -> jnp.ndarray:
    b, h, w, c = image.shape
    if w > LANES:
        raise ValueError(
            f"pallas warp requires W <= {LANES} (got {w}); use the XLA path "
            "for fine pyramid levels")
    hp = -(-h // 8) * 8
    imgp = jnp.pad(image.astype(jnp.float32),
                   ((0, 0), (0, hp - h), (0, LANES - w), (0, 0)))
    flowp = jnp.pad(flow.astype(jnp.float32),
                    ((0, 0), (0, hp - h), (0, LANES - w), (0, 0)))
    imgp = jnp.transpose(imgp, (0, 3, 1, 2))   # (B, C, Hp, 128)
    flowp = jnp.transpose(flowp, (0, 3, 1, 2))  # (B, 2, Hp, 128)

    out = pl.pallas_call(
        functools.partial(_warp_kernel, h=h, w=w, c=c, hp=hp),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, c, hp, LANES), lambda bi: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, hp, LANES), lambda bi: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, c, hp, LANES), lambda bi: (bi, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, c, hp, LANES), jnp.float32),
        interpret=interpret,
    )(imgp, flowp)
    return jnp.transpose(out, (0, 2, 3, 1))[:, :h, :w].astype(image.dtype)


@functools.lru_cache(maxsize=None)
def _partitioned_fwd(interpret: bool):
    """Batch-data-parallel partitioning (same rationale as pallas/corr.py:
    GSPMD cannot see inside the kernel; the warp is independent per batch
    element but the row sweep needs the full H per shard)."""
    fwd = custom_partitioning(
        lambda image, flow: _pallas_warp_fwd(image, flow, interpret))

    def _batch_axis(arg_infos):
        for info in arg_infos:
            sharding = getattr(info, "sharding", None)
            spec = getattr(sharding, "spec", None)
            if spec and len(spec) and spec[0] is not None:
                return spec[0]
        return None

    def infer(mesh, arg_infos, result_infos):
        return NamedSharding(mesh, P(_batch_axis(arg_infos), None, None, None))

    def partition(mesh, arg_infos, result_infos):
        sh = NamedSharding(mesh, P(_batch_axis(arg_infos), None, None, None))

        def lower(image, flow):
            return _pallas_warp_fwd(image, flow, interpret)

        return mesh, lower, sh, (sh, sh)

    fwd.def_partition(
        infer_sharding_from_operands=infer,
        partition=partition,
        sharding_rule="b h w c, b h w k -> b h w c",
        need_replication_factors=("h", "w", "c", "k"),
    )
    return fwd


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def backward_warp_pallas(image: jnp.ndarray, flow: jnp.ndarray,
                         interpret: bool | None = None) -> jnp.ndarray:
    """Pallas warp: image (B,H,W,C), *scaled* flow (B,H,W,2) -> (B,H,W,C).

    Exact `ops.warp.backward_warp` semantics for W <= 128 (any flow
    magnitude — border clipping bounds the sweep), including gradients
    with respect to both arguments. interpret=None auto-selects
    interpreter mode off-TPU (CPU test meshes).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _partitioned_fwd(interpret)(image, flow)


def _fwd(image, flow, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _partitioned_fwd(interpret)(image, flow), (image, flow)


def _bwd(_interpret, res, g):
    from ..warp import backward_warp  # jnp formulation; same a.e. gradient

    image, flow = res
    _, vjp = jax.vjp(backward_warp, image, flow)
    gi, gf = vjp(g.astype(jnp.float32))
    return gi.astype(image.dtype), gf.astype(flow.dtype)


backward_warp_pallas.defvjp(_fwd, _bwd)
