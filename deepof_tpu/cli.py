"""Single CLI entry — replaces the reference's per-dataset entry scripts
(`deepOF.py`, `deepOF_fc.py`, `version1/deepOF.py`, SURVEY.md §2.1) and the
edit-a-boolean dataset dispatch (`deepOF.py:8-10`).

Usage:
    python -m deepof_tpu train --preset flyingchairs --data-path /data/fc
    python -m deepof_tpu eval  --preset sintel --data-path /data/sintel \
        --log-dir /tmp/run1          # restores latest checkpoint
    python -m deepof_tpu bench --model inception_v3
    python -m deepof_tpu warmup --preset flyingchairs --synthetic \
        --set train.steps_per_call=4   # AOT-compile into the on-disk cache

`warmup` populates the persistent compilation cache (artifacts/xla_cache)
for a config ahead of time — lower + compile only, no data movement, no
step execution — so the next `train`/`bench` process for the same config
starts hot (zero recompilation; see DESIGN.md "Execution layer").

Any config field can be overridden with --set section.field=value, e.g.
    --set optim.learning_rate=1e-4 --set train.num_epochs=10
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import sys

from .core.config import (PRESETS, ExperimentConfig, config_from_dict,
                          get_config)


def _parse_value(raw: str):
    if raw.lower() in ("true", "false"):  # accept lowercase bools
        return raw.lower() == "true"
    if raw.lower() in ("none", "null"):
        return None
    try:
        return ast.literal_eval(raw)
    except (ValueError, SyntaxError):
        return raw


def _apply_override(cfg: ExperimentConfig, dotted: str, raw: str) -> ExperimentConfig:
    """Set a dotted config path (`field`, `section.field`, or deeper —
    e.g. `resilience.faults.decode_p`) on the frozen config tree,
    returning a new config. Every intermediate node must be a dataclass
    field of its parent."""
    value = _parse_value(raw)

    def rec(node, parts: list[str]):
        name, rest = parts[0], parts[1:]
        if not (dataclasses.is_dataclass(node) and hasattr(node, name)):
            raise SystemExit(f"unknown config field {dotted!r}")
        new = rec(getattr(node, name), rest) if rest else value
        return dataclasses.replace(node, **{name: new})

    return rec(cfg, dotted.split("."))


def _recipe_from_file(cfg: ExperimentConfig, path: str) -> ExperimentConfig:
    """Load a `--recipe FILE` JSON (a RecipeConfig dict, train/recipe.py)
    into the config. The file implies recipe.enabled; unknown keys are
    rejected at every nesting level (stages[i], stages[i].mixture[j])."""
    from .core.config import recipe_from_dict

    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise SystemExit(f"--recipe {path!r}: {e}")
    if not isinstance(d, dict):
        raise SystemExit(f"--recipe {path!r}: expected a JSON object "
                         '(a RecipeConfig dict with a "stages" list)')
    d.setdefault("enabled", True)
    try:
        return cfg.replace(recipe=recipe_from_dict(d))
    except (TypeError, ValueError) as e:
        raise SystemExit(f"--recipe {path!r}: {e}")


def _build_cfg(args) -> ExperimentConfig:
    if getattr(args, "config_json", None):
        # the fleet's parent->replica handoff: the exact serialized
        # config tree, not a preset re-derivation (--set still wins)
        with open(args.config_json) as f:
            cfg = config_from_dict(json.load(f))
    else:
        cfg = get_config(args.preset)
    if args.model:
        cfg = cfg.replace(model=args.model)
    if args.data_path:
        cfg = cfg.replace(data=dataclasses.replace(cfg.data, data_path=args.data_path))
    if args.log_dir:
        cfg = cfg.replace(train=dataclasses.replace(cfg.train, log_dir=args.log_dir))
    if getattr(args, "synthetic", False):
        # before --set so explicit overrides win over smoke-test defaults
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data, dataset="synthetic", image_size=(64, 64),
            gt_size=(64, 64), batch_size=8, crop_size=None, time_step=2),
            train=dataclasses.replace(cfg.train, eval_batch_size=8,
                                      eval_amplifier=1.0))
    if getattr(args, "recipe", None):
        # before --set so explicit --set recipe.* overrides win over
        # the file (same convention as every sugar flag above)
        cfg = _recipe_from_file(cfg, args.recipe)
    # serve session/autoscale sugar: the flags ride the same
    # nested-override path as --set (and before it, so an explicit
    # --set still wins)
    for flag, dotted in (("session_ttl", "serve.session.ttl_s"),
                        ("session_max", "serve.session.max_sessions"),
                        ("min_replicas", "serve.fleet.min_replicas"),
                        ("max_replicas", "serve.fleet.max_replicas"),
                        ("artifacts", "serve.artifacts_dir")):
        value = getattr(args, flag, None)
        if value is not None:
            cfg = _apply_override(cfg, dotted, repr(value))
    if getattr(args, "autoscale", False):
        cfg = _apply_override(cfg, "serve.fleet.autoscale", "true")
    for item in args.set or []:
        if "=" not in item:
            raise SystemExit(f"bad --set {item!r}: use section.field=value")
        dotted, raw = item.split("=", 1)
        cfg = _apply_override(cfg, dotted, raw)
    return cfg


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--preset", default="flyingchairs", choices=sorted(PRESETS))
    p.add_argument("--model", default=None)
    p.add_argument("--data-path", default=None)
    p.add_argument("--log-dir", default=None)
    p.add_argument("--set", action="append", metavar="SECTION.FIELD=VALUE")
    p.add_argument("--multihost", action="store_true",
                   help="call jax.distributed.initialize() so the mesh spans "
                        "hosts (data axis over DCN). batch_size is GLOBAL; "
                        "each host loads only its shard's rows for training "
                        "(decorrelated rng streams), val batches load "
                        "host-identically and eval outputs allgather "
                        "(single-writer ckpt/logs/visuals)")
    p.add_argument("--synthetic", action="store_true",
                   help="swap in the synthetic dataset at small shapes "
                        "(smoke tests; no data on disk needed)")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="deepof_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_train = sub.add_parser("train", help="train a model")
    _add_common(p_train)
    p_train.add_argument("--epochs", type=int, default=None)
    p_train.add_argument("--max-steps", "--steps", dest="max_steps",
                         type=int, default=None)
    p_train.add_argument("--recipe", default=None, metavar="FILE",
                         help="staged training-recipe JSON (DESIGN.md "
                              "\"Recipe engine\"): an ordered stage list, "
                              "each with a weighted dataset mixture "
                              "(deterministic for any data.num_workers), "
                              "per-stage shape/time_step/loss/lr "
                              "overrides, and an advance trigger — fixed "
                              "steps or the eval_trend sustained-AEE-"
                              "plateau signal. Implies recipe.enabled; "
                              "--set recipe.* still wins")
    p_train.add_argument("--profile", action="store_true",
                         help="whole-run jax.profiler trace (includes "
                              "compile; grows with run length)")
    p_train.add_argument("--profile-steps", default=None, metavar="K:N",
                         help="jax.profiler trace of steps K..N only "
                              "(excludes compile, stays small enough to "
                              "fetch over the tunnel)")
    p_train.add_argument("--trace", action="store_true",
                         help="cross-thread span timeline to "
                              "<log-dir>/trace.json (Perfetto/"
                              "chrome://tracing loadable) — shorthand "
                              "for --set obs.trace=true")
    p_train.add_argument("--elastic", type=int, default=None, metavar="N",
                         help="elastic multi-host training (DESIGN.md "
                              "\"Elastic training\"): supervise N "
                              "single-host trainer subprocesses that "
                              "survive host loss/preemption — a lost or "
                              "wedged host triggers a generation bump: "
                              "clean barrier stop, re-form on the "
                              "survivors (re-sharded data streams), "
                              "resume from the newest verified "
                              "checkpoint. Requires --max-steps (the "
                              "absolute target step). Overrides "
                              "elastic.hosts; <= 1 keeps plain training")
    p_train.add_argument("--host-index", type=int, default=None,
                         help=argparse.SUPPRESS)  # elastic-internal:
    #                      trainer children carry their host identity
    p_train.add_argument("--config-json", default=None,
                         help=argparse.SUPPRESS)  # elastic-internal:
    #                      children load the coordinator's exact config

    p_eval = sub.add_parser("eval", help="evaluate latest checkpoint")
    _add_common(p_eval)
    p_eval.add_argument("--dump-visuals", action="store_true")

    p_pred = sub.add_parser(
        "predict", help="run a trained model on image pairs; write .flo + png")
    _add_common(p_pred)
    p_pred.add_argument("--pairs", nargs="+", required=True,
                        metavar="PREV:NEXT",
                        help="image-path pairs, colon-separated")
    p_pred.add_argument("--out", required=True, help="output directory")
    p_pred.add_argument("--no-png", action="store_true")
    p_pred.add_argument("--action", action="store_true",
                        help="classify each pair with a trained action "
                             "head (st_single/st_baseline/ucf101_spatial "
                             "— the UCF-101 workload) instead of "
                             "predicting flow: writes <out>/actions.json "
                             "with top-k classes + softmax probs per "
                             "pair")
    p_pred.add_argument("--labels", default=None, metavar="FILE",
                        help="--action: class-name file (one name per "
                             "line, index order) to attach names to "
                             "predictions")
    p_pred.add_argument("--ckpt-dir", default=None, metavar="DIR",
                        help="--action: explicit checkpoint directory "
                             "(a recipe run's final stage lives under "
                             "<log-dir>/ckpt-stage<i>, not <log-dir>/"
                             "ckpt)")
    p_pred.add_argument("--precision", default=None,
                        choices=("f32", "bf16", "int8"),
                        help="serving precision tier (must be in "
                             "serve.precisions; default: the config's "
                             "first tier). bf16 halves and int8 quarters "
                             "the weight bytes each dispatch moves "
                             "(weight-only, per-output-channel scales; "
                             "DESIGN.md \"Precision tiers\")")

    p_cfg = sub.add_parser("config", help="print the resolved config")
    _add_common(p_cfg)

    p_warm = sub.add_parser(
        "warmup", help="AOT-compile a config's train+eval executables into "
                       "the persistent compile cache (no execution)")
    _add_common(p_warm)
    p_warm.add_argument("--no-eval", action="store_true",
                        help="skip the eval executable")
    p_warm.add_argument("--recipe", default=None, metavar="FILE",
                        help="AOT-compile EVERY stage of this training-"
                             "recipe JSON — one (train, eval) executable "
                             "pair per stage — so a later `train "
                             "--recipe` run switches stages with zero "
                             "recompiles (provable from the ledger)")
    p_warm.add_argument("--serve", action="store_true",
                        help="also AOT-compile the serve ladder "
                             "(serve.buckets x serve.precisions "
                             "inference executables at serve.max_batch) "
                             "so a cold engine's first requests load "
                             "instead of compiling")
    p_warm.add_argument("--serve-only", action="store_true",
                        help="compile only the serve ladder (skip "
                             "train/eval)")
    p_warm.add_argument("--artifacts", default=None, metavar="DIR",
                        help="publish each serve executable into this "
                             "artifact store (serialized, fingerprint-"
                             "keyed — DESIGN.md \"Artifact plane\"); "
                             "engines/replicas started with the same "
                             "store boot by fetching instead of "
                             "compiling. Shorthand for "
                             "--set serve.artifacts_dir=DIR; works "
                             "cache-free with --serve-only (single-"
                             "writer publish is cpu-safe)")

    p_srv = sub.add_parser(
        "serve", help="inference serving (DESIGN.md \"Serving\"): dynamic "
                      "micro-batching engine over the latest verified "
                      "checkpoint. Default: stdlib HTTP server (POST "
                      "/v1/flow; streaming video sessions on POST "
                      "/v1/flow/stream — one decode per frame; GET "
                      "/healthz) with a serve heartbeat in "
                      "--log-dir; with --input: offline high-throughput "
                      "directory/video inference to --out")
    _add_common(p_srv)
    p_srv.add_argument("--input", default=None,
                       help="offline mode: a directory of frames "
                            "(consecutive sorted pairs) or a video file")
    p_srv.add_argument("--out", default=None,
                       help="offline mode: output directory for "
                            ".flo/.png results")
    p_srv.add_argument("--no-png", action="store_true")
    p_srv.add_argument("--session-ttl", type=float, default=None,
                       metavar="SECONDS",
                       help="streaming sessions (POST /v1/flow/stream, "
                            "DESIGN.md \"Streaming sessions\"): idle TTL "
                            "before a session expires — shorthand for "
                            "--set serve.session.ttl_s=X; <= 0 disables "
                            "the TTL")
    p_srv.add_argument("--session-max", type=int, default=None,
                       metavar="N",
                       help="streaming sessions: LRU bound on "
                            "concurrently kept sessions per engine — "
                            "shorthand for "
                            "--set serve.session.max_sessions=N")
    p_srv.add_argument("--replicas", type=int, default=None,
                       help="self-healing serving fleet (DESIGN.md "
                            "\"Fleet\"): supervise N engine-replica "
                            "subprocesses behind a health-gated router "
                            "with bucket-affinity routing, failover "
                            "retries, load shedding, and automatic "
                            "evict/respawn of wedged or crashed "
                            "replicas. Overrides serve.fleet.replicas; "
                            "<= 1 keeps single-process serving")
    p_srv.add_argument("--autoscale", action="store_true",
                       help="SLO-driven fleet autoscaling (DESIGN.md "
                            "\"Supervision plane\"): scale the replica "
                            "pool between serve.fleet.min_replicas and "
                            "max_replicas from live signals — sustained "
                            "shed/overload and SLO budget burn scale up, "
                            "sustained idle scales down via graceful "
                            "drain. Shorthand for "
                            "--set serve.fleet.autoscale=true; implies "
                            "fleet mode even without --replicas")
    p_srv.add_argument("--min-replicas", type=int, default=None,
                       metavar="N",
                       help="autoscaler pool floor — shorthand for "
                            "--set serve.fleet.min_replicas=N")
    p_srv.add_argument("--max-replicas", type=int, default=None,
                       metavar="N",
                       help="autoscaler pool ceiling — shorthand for "
                            "--set serve.fleet.max_replicas=N")
    p_srv.add_argument("--artifacts", default=None, metavar="DIR",
                       help="boot executables from this artifact store "
                            "(`warmup --serve --artifacts DIR` publishes "
                            "it): fetch + deserialize instead of "
                            "compiling, fingerprint-gated — a cold "
                            "replica's first request pays zero XLA. "
                            "Shorthand for --set serve.artifacts_dir=DIR")
    p_srv.add_argument("--config-json", default=None,
                       help=argparse.SUPPRESS)  # fleet-internal: replica
    #                      processes load the supervisor's exact config

    p_bench = sub.add_parser("bench", help="throughput benchmark")
    p_bench.add_argument("--model", default="inception_v3")
    p_bench.add_argument("--batch", type=int, default=16)
    p_bench.add_argument("--steps", type=int, default=20)
    p_bench.add_argument("--data-only", action="store_true",
                         help="measure host input-pipeline throughput in "
                              "isolation (batches/s, MB/s; cpu-only, no "
                              "accelerator touched) instead of the train "
                              "step — attributes host vs. device "
                              "bottlenecks without a TPU")
    p_bench.add_argument("--workers", type=int, default=0,
                         help="data-only mode: pipeline worker threads")
    p_bench.add_argument("--batches", type=int, default=32,
                         help="data-only mode: batches to time")
    p_bench.add_argument("--image-size", default="64x64", metavar="HxW",
                         help="data-only mode: decoded image size")
    p_bench.add_argument("--dataset", default="synthetic",
                         help="data-only mode: dataset to assemble "
                              "(flyingchairs/sintel/ucf101/synthetic)")
    p_bench.add_argument("--data-path", default="",
                         help="data-only mode: dataset root on disk")
    p_bench.add_argument("--recipe", default=None, metavar="FILE",
                         help="data-only mode: time the recipe's first-"
                              "stage weighted MIXTURE stream "
                              "(data/mixture.py) through the pipeline "
                              "instead of a single --dataset")

    p_an = sub.add_parser("analyze", help="summarize a run's metrics log")
    p_an.add_argument("--log-dir", required=True)
    p_an.add_argument("--no-plot", action="store_true")

    p_vck = sub.add_parser(
        "verify-ckpt",
        help="offline manifest/checksum validation of every checkpoint "
             "in a run directory (jax-free; nonzero exit on corruption)")
    p_vck.add_argument("dir",
                       help="a run's --log-dir or its ckpt/ subdirectory")

    p_art = sub.add_parser(
        "artifacts",
        help="executable artifact store (DESIGN.md \"Artifact plane\"): "
             "list / verify / gc the fingerprint-keyed serialized AOT "
             "executables `warmup --serve` publishes and replicas boot "
             "from (jax-free; verify-ckpt's rc contract: 1 = corrupt "
             "entries, 2 = empty store)")
    p_art.add_argument("action", choices=("list", "verify", "gc"),
                       help="list: one identity line per entry; verify: "
                            "full structural verdicts (manifest + "
                            "fingerprint + payload size/crc32); gc: "
                            "remove corrupt entries and orphaned tmp "
                            "staging dirs")
    p_art.add_argument("--deep", action="store_true",
                       help="verify only: re-lower every indexed serve "
                            "executable under the given config and "
                            "compare StableHLO fingerprints against the "
                            "store's index (the offline twin of the "
                            "engine's background deep-verify plane). "
                            "Needs jax + the config the index was "
                            "published under (--preset/--model/--set); "
                            "rc 1 on drift, rc 2 on an empty/unindexed "
                            "store")
    p_art.add_argument("--preset", default="flyingchairs",
                       choices=sorted(PRESETS),
                       help="--deep only: config preset the index was "
                            "published under")
    p_art.add_argument("--model", default=None,
                       help="--deep only: model override")
    p_art.add_argument("--set", action="append",
                       metavar="SECTION.FIELD=VALUE",
                       help="--deep only: config overrides (must match "
                            "the publishing warmup's)")
    p_art.add_argument("--dir", default=None,
                       help="store root (default: <repo>/artifacts/exec, "
                            "serve.artifacts_dir's conventional home)")
    p_art.add_argument("--older-than-days", type=float, default=None,
                       metavar="DAYS",
                       help="gc: also remove structurally VALID entries "
                            "whose manifest is older than this many days "
                            "(code churn strands fingerprints forever)")
    p_art.add_argument("--json-indent", type=int, default=2)

    p_lint = sub.add_parser(
        "lint", help="graftlint: project-invariant static analysis "
                     "(DESIGN.md \"Static analysis\"): counters "
                     "registered in obs/registry.py, config attribute "
                     "typos, determinism (unseeded randomness in the "
                     "data/model path), jit-purity (side effects in "
                     "traced code), and cross-thread lock discipline. "
                     "jax-free; exit 0 clean, 2 on findings, 1 on "
                     "usage error")
    p_lint.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: the "
                             "deepof_tpu package + tools/)")
    p_lint.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable findings (CI mode)")
    p_lint.add_argument("--rule", action="append", default=None,
                        metavar="NAME",
                        help="run only this rule (repeatable); default: "
                             "all rules")

    p_tail = sub.add_parser(
        "tail", help="one-glance health of a live or finished run: step, "
                     "loss, recent vs overall throughput, phase shares, "
                     "starvation, resilience counters, heartbeat age; "
                     "exits 3 when the heartbeat reports wedged, 4 when "
                     "a serving fleet evicted or broke a replica, 5 "
                     "when an elastic run lost a host and re-formed, 6 "
                     "when the SLO error budget is exhausted "
                     "(obs.slo_latency_ms / obs.slo_error_budget), 7 "
                     "when the label-free flow-quality drift verdict "
                     "fired (obs.quality_sample_rate / obs.quality_budget"
                     " — with --fleet, any replica's verdict counts), 8 "
                     "when the executable ledger drifted against its "
                     "baseline (HLO fingerprint drift, unexpected "
                     "recompiles, compile blowups, memory growth — "
                     "obs/ledger.py; with --fleet, any replica's ledger "
                     "counts), and 9 — checked ahead of 3-7 (8 stays 8: "
                     "the live ledger verdict records its own bundle) — "
                     "when the incident plane holds unacknowledged "
                     "CRITICAL flight-recorder bundles (obs.incidents; "
                     "`incidents ack` clears it), and 10 when the "
                     "brownout controller held L3 (shedding low-priority"
                     " work) past serve.degrade.l3_sustained_s — "
                     "degradation was meant as a bridge to autoscaled "
                     "capacity that never arrived (serve/degrade.py)")
    p_tail.add_argument("--log-dir", required=True)
    p_tail.add_argument("--recent", type=int, default=10,
                        help="train records in the throughput-trend window")
    p_tail.add_argument("--fleet", action="store_true",
                        help="also aggregate the run dir's supervised "
                             "children (fleet replicas / elastic hosts) "
                             "into per-process blocks + an exact merged "
                             "latency histogram — the whole drill in one "
                             "read")
    p_tail.add_argument("--follow", action="store_true",
                        help="re-print every --interval seconds until ^C")
    p_tail.add_argument("--interval", type=float, default=10.0)
    p_tail.add_argument("--ledger-baseline", default=None, metavar="PATH",
                        help="baseline ledger.jsonl for the executable-"
                             "ledger drift verdict (exit 8). Default: "
                             "<log-dir>/ledger_baseline.jsonl when "
                             "present; no baseline = no verdict")
    p_tail.add_argument("--ledger-compile-factor", type=float,
                        default=None, metavar="X",
                        help="compile-time blowup bound: fail when an "
                             "executable's compile_s exceeds "
                             "max(floor, baseline * X) (default 2.0)")
    p_tail.add_argument("--ledger-compile-floor-s", type=float,
                        default=None, metavar="S",
                        help="compile-blowup floor in seconds — below "
                             "it no compile time fails (default 1.0)")
    p_tail.add_argument("--ledger-memory-factor", type=float,
                        default=None, metavar="X",
                        help="memory-growth bound: fail when arg+out+"
                             "temp bytes exceed baseline * X "
                             "(default 1.2)")

    p_inc = sub.add_parser(
        "incidents",
        help="incident flight-recorder triage (DESIGN.md \"Incident "
             "plane\"): list / show / ack / gc the bounded diagnostic "
             "bundles anomaly triggers committed under "
             "<log-dir>/incidents/ (jax-free; rc 1 = unacknowledged "
             "CRITICAL incidents need attention, rc 2 = none recorded)")
    p_inc.add_argument("action", choices=("list", "show", "ack", "gc"),
                       help="list: one line per committed bundle + the "
                            "summary block tail/analyze embed; show: one "
                            "bundle's full manifest + on-disk file "
                            "inventory; ack: acknowledge bundle(s) — "
                            "clears tail's rc 9; gc: remove old/acked "
                            "bundles and orphaned staging dirs")
    p_inc.add_argument("--log-dir", required=True)
    p_inc.add_argument("--id", default=None, metavar="ID",
                       help="show: required; ack: one bundle "
                            "(default: all)")
    p_inc.add_argument("--older-than-days", type=float, default=None,
                       metavar="DAYS",
                       help="gc: remove bundles whose manifest is older "
                            "than this many days")
    p_inc.add_argument("--acked", action="store_true",
                       help="gc: also remove acknowledged bundles of any "
                            "age")
    p_inc.add_argument("--keep", type=int, default=None, metavar="N",
                       help="gc: keep at most the newest N bundles")
    p_inc.add_argument("--json-indent", type=int, default=2)

    args = parser.parse_args(argv)

    if args.cmd == "lint":
        # jax-free by design (lint/ imports stdlib + core.config +
        # obs.registry only): the CI gate must run on hosts with no
        # accelerator stack at all
        import time as _time

        from .lint import RULES, lint_paths

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        paths = args.paths or [
            p for p in (os.path.join(repo_root, "deepof_tpu"),
                        os.path.join(repo_root, "tools"))
            if os.path.isdir(p)]
        selected = sorted(set(args.rule)) if args.rule else sorted(RULES)
        t0 = _time.perf_counter()
        try:
            findings = lint_paths(paths, rules=selected)
        except (ValueError, FileNotFoundError) as e:
            print(f"lint: {e}", file=sys.stderr)
            return 1  # usage error: distinct from "findings" (2)
        elapsed = round(_time.perf_counter() - t0, 3)
        live = [f for f in findings if not f.waived]
        waived = [f for f in findings if f.waived]
        if args.as_json:
            print(json.dumps({
                "findings": [f.as_dict() for f in live],
                "waived": [f.as_dict() for f in waived],
                "rules": selected,
                "elapsed_s": elapsed}))
        else:
            for f in findings:
                print(f.format())
            print(f"lint: {len(live)} finding(s), {len(waived)} waived, "
                  f"{len(selected)} rule(s) in {elapsed}s")
        return 2 if live else 0

    if args.cmd == "verify-ckpt":
        # jax-free by design (resilience/verify.py is stdlib-only): the
        # manifests inventory files + crc32s, so validation runs from
        # any machine, against a live run, without touching a backend
        from .resilience.verify import verify_run

        report = verify_run(args.dir)
        print(json.dumps(report, indent=2))
        if report["corrupt_steps"]:
            return 1  # corruption is the nonzero-exit contract
        if not report["checkpoints"]:
            print(f"verify-ckpt: no checkpoints under {args.dir!r}",
                  file=sys.stderr)
            return 2
        return 0

    if args.cmd == "artifacts":
        # jax-free by design (serve/artifacts.py's store half is
        # stdlib): the store is listed/verified/gc'd from any machine —
        # same contract as verify-ckpt (rc 1 corrupt, rc 2 empty)
        from .serve.artifacts import (DEFAULT_STORE_DIR, gc_store,
                                      verify_store)

        root = args.dir or DEFAULT_STORE_DIR
        if args.action == "verify" and args.deep:
            # the one artifacts action that DOES need jax: re-lower the
            # serve lattice under the given config and compare StableHLO
            # fingerprints against the index — catches code drift the
            # structural (crc/manifest) verify cannot see
            from .train.warmup import deep_verify_serve

            args.data_path = None  # _build_cfg expects the common args
            args.log_dir = None
            cfg = _build_cfg(args)
            cfg = _apply_override(cfg, "serve.artifacts_dir", repr(root))
            try:
                report = deep_verify_serve(cfg)
            except ValueError as e:
                print(f"artifacts verify --deep: {e}", file=sys.stderr)
                return 2
            print(json.dumps(report, indent=args.json_indent))
            if report["drift"]:
                return 1
            if not report["entries"] or report["ok"] == 0:
                print(f"artifacts: nothing indexed to deep-verify at "
                      f"{root!r}", file=sys.stderr)
                return 2
            return 0
        if args.action == "gc":
            report = gc_store(root, older_than_days=args.older_than_days)
            print(json.dumps(report, indent=args.json_indent))
            return 0
        report = verify_store(root)
        if args.action == "list":
            print(json.dumps(
                {"dir": report["dir"], "total": report["total"],
                 "ok": report["ok"], "corrupt": report["corrupt"],
                 "entries": [{"fingerprint": e["fingerprint"],
                              "name": e["name"], "ok": e["ok"],
                              "size": e["size"], "created": e["created"]}
                             for e in report["entries"]]},
                indent=args.json_indent))
        else:
            print(json.dumps(report, indent=args.json_indent))
        if report["corrupt"]:
            return 1
        if not report["entries"]:
            print(f"artifacts: empty store at {root!r}", file=sys.stderr)
            return 2
        return 0

    if args.cmd == "incidents":
        # jax-free by design (obs/incident.py is stdlib-only): triage
        # runs from any machine, against a live run — same contract
        # family as verify-ckpt/artifacts (rc 1 = attention required,
        # rc 2 = empty plane)
        from .obs import incident as _incident

        if args.action == "show":
            if not args.id:
                print("incidents show: --id required", file=sys.stderr)
                return 1
            try:
                detail = _incident.show_incident(args.log_dir, args.id)
            except FileNotFoundError:
                print(f"incidents: no committed bundle {args.id!r} under "
                      f"{args.log_dir!r}", file=sys.stderr)
                return 1
            print(json.dumps(detail, indent=args.json_indent))
            return 0
        if args.action == "ack":
            acked = _incident.ack_incidents(args.log_dir,
                                            incident_id=args.id)
            print(json.dumps({"acked": acked},
                             indent=args.json_indent))
            if args.id is not None and not acked:
                print(f"incidents: no unacknowledged bundle {args.id!r} "
                      f"under {args.log_dir!r}", file=sys.stderr)
                return 1
            return 0
        if args.action == "gc":
            report = _incident.gc_incidents(
                args.log_dir, older_than_days=args.older_than_days,
                acked=args.acked, keep=args.keep)
            print(json.dumps(report, indent=args.json_indent))
            return 0
        rows = _incident.list_incidents(args.log_dir)
        summary = _incident.incident_summary(args.log_dir)
        print(json.dumps(
            {"dir": _incident.incidents_dir(args.log_dir),
             "summary": summary,
             "incidents": [
                 {"id": r.get("id"), "kind": r.get("kind"),
                  "severity": r.get("severity"), "role": r.get("role"),
                  "time": r.get("iso_time"), "acked": r.get("acked"),
                  "origin": r.get("origin")} for r in rows]},
            indent=args.json_indent))
        if summary is None:
            print(f"incidents: none recorded under {args.log_dir!r}",
                  file=sys.stderr)
            return 2
        return 1 if summary["unacked_critical"] else 0

    if args.cmd == "tail":
        # jax-free like analyze: tailing a run must never touch the
        # accelerator the trainer holds
        from .analyze import tail_summary

        ledger_bounds = {
            k: v for k, v in (
                ("compile_factor", args.ledger_compile_factor),
                ("compile_floor_s", args.ledger_compile_floor_s),
                ("memory_factor", args.ledger_memory_factor))
            if v is not None}
        # a requested ledger gate must never silently pass: a typo'd
        # baseline path or a run that recorded no ledger would
        # otherwise yield "no verdict" => rc 0 forever (the standalone
        # ledger_diff errors rc 1 on the same inputs — the two gates
        # must agree). This covers the committed-by-convention
        # <log_dir>/ledger_baseline.jsonl too: a convention file that
        # EXISTS but holds no parseable rows is a broken gate, not the
        # legitimate no-baseline case.
        from .obs.ledger import (find_baseline, load_ledger,
                                 resolve_ledger_path)

        _base = args.ledger_baseline
        if _base is not None:
            # a run dir holding a ledger.jsonl is a valid baseline —
            # the SAME resolution rule load_ledger/ledger_diff apply,
            # shared so the two gates can never diverge on it
            _p = resolve_ledger_path(_base)
            if not os.path.isfile(_p):
                raise SystemExit(f"tail: --ledger-baseline "
                                 f"{_base!r} does not exist "
                                 f"(expected a ledger.jsonl or a run dir "
                                 f"holding one)")
        else:
            _p = find_baseline(args.log_dir)  # convention file or None
        if _p is not None:
            # the baseline side is STATIC — an empty/truncated file can
            # never become valid, so even --follow must fail it loudly
            # up front (ledger_verdict would return None and the gate
            # would sit silently inert forever)
            try:
                _base_rows = load_ledger(_p)
            except OSError as e:
                raise SystemExit(f"tail: ledger baseline {_p!r} "
                                 f"unreadable: {e}")
            if not _base_rows:
                raise SystemExit(f"tail: ledger baseline {_p!r} "
                                 f"contains no ledger rows")
        while True:
            try:
                summary = tail_summary(args.log_dir, recent=args.recent,
                                       fleet=args.fleet,
                                       ledger_baseline=args.ledger_baseline,
                                       ledger_bounds=ledger_bounds)
            except FileNotFoundError:
                raise SystemExit(f"no metrics.jsonl under {args.log_dir!r} "
                                 "— is this a run's --log-dir?")
            print(json.dumps(summary), flush=True)
            if (args.ledger_baseline is not None
                    and "ledger_diff" not in summary
                    and not args.follow):
                # the explicit gate could not run: baseline unreadable
                # or the run recorded no ledger — loud, never rc 0. In
                # --follow mode keep following instead: a live run's
                # ledger.jsonl only appears after its first compile
                # (minutes, cold), and rc 3-7 likewise keep following
                # until their condition actually fires.
                raise SystemExit(f"tail: --ledger-baseline given but no "
                                 f"verdict could be computed — is "
                                 f"{args.ledger_baseline!r} a ledger and "
                                 f"does {args.log_dir!r} hold a "
                                 f"ledger.jsonl (obs.ledger on)?")
            # rc 8 when the executable ledger drifted against its
            # baseline (obs/ledger.py diff_ledgers): fingerprint
            # drift, unexpected recompiles, compile-time blowups, or
            # memory growth — the executables serving/training are NOT
            # the ones the baseline measured (with --fleet, any
            # replica's verdict counts). Checked before rc 9: the
            # verdict is LIVE — it
            # re-derives from the baseline on every invocation and
            # records its own ledger_drift bundle below — so the
            # invocation that derives the failure must keep the
            # documented rc 8 (otherwise the bundle it just committed
            # would flip every later tail to rc 9 while the drift
            # persists, hiding the specific verdict). The bundle
            # surfaces as rc 9 only once the drift itself is gone but
            # the incident is still un-triaged.
            verdict = summary.get("ledger_diff") or {}
            if verdict.get("failed"):
                # persist the verdict as an incident bundle before
                # exiting: a `tail --follow` gate is often the ONLY
                # process watching, and the regression evidence should
                # outlive its stdout. Structural dedup (the condensed
                # failure set keys the bundle) means re-running tail on
                # the same regression records it once.
                from .obs import incident as _incident

                condensed = {
                    cls: sorted(e.get("name", "?")
                                for e in (verdict.get(cls) or []))
                    for cls in ("fingerprint_drift",
                                "unexpected_recompiles",
                                "compile_blowups", "memory_growth")}
                _incident.record_offline(
                    args.log_dir, "ledger_drift", "critical",
                    trigger=condensed,
                    dedup_key=json.dumps(condensed, sort_keys=True))
                return 8
            # rc 9 ahead of the cumulative rc 3-7 counters:
            # unacknowledged CRITICAL incident bundles outrank them —
            # the same anomaly usually trips both (a SIGKILL eviction
            # bumps the rc-4 counters AND commits a
            # fleet_replica_crash bundle), and the bundle is the
            # richer artifact: it carries the underlying verdict plus
            # the trace/heartbeat/stack context to triage it.
            # `incidents ack` then moves past it, where the cumulative
            # counters would re-fire forever.
            if (summary.get("incidents") or {}).get("unacked_critical"):
                return 9
            # a wedged run must fail scripted health checks loudly: rc 3
            # when the heartbeat's watchdog has declared a wedge — in
            # --follow mode the loop ends at the first wedged heartbeat
            # (the run is no longer making the progress being followed)
            hb = summary.get("heartbeat") or {}
            if hb.get("wedged"):
                return 3
            # rc 4 when a serving fleet self-healed (evictions) or gave
            # up on a replica (circuit breaker): the fleet may be
            # serving again, but an operator must see that replicas
            # were sick — the counters are cumulative by design.
            # Autoscale scale-downs deliberately do NOT trip this:
            # retirement (fleet_retired / autoscale_down) is the pool
            # doing its job, not sickness
            fleet = summary.get("fleet") or {}
            if fleet.get("broken") or fleet.get("evictions"):
                return 4
            # rc 5 when an elastic run lost a host and re-formed (or
            # aborted hosts without re-forming): the run may have
            # completed to target, but an operator must see that the
            # world shrank — distinct from wedged (3) and fleet (4)
            elastic = summary.get("elastic") or {}
            if elastic.get("reforms") or elastic.get("lost_hosts"):
                return 5
            # rc 6 when the SLO error budget is exhausted (the serve
            # engine's serve_slo or the fleet router's fleet_slo block,
            # obs/export.py): latency breaches + server-side failures
            # overran obs.slo_error_budget — the run may still be
            # serving, but it is OUTSIDE its contract
            slo = ((summary.get("serve") or {}).get("slo")
                   or (summary.get("fleet") or {}).get("slo") or {})
            if slo.get("exhausted"):
                return 6
            # rc 7 when the label-free flow-quality drift verdict fired
            # (obs/quality.py): post-reference photometric-proxy
            # breaches overran obs.quality_budget — latency and errors
            # may look perfect while the FLOWS are degrading (quantized
            # tier drift, damaged weights). With --fleet, any child
            # replica's verdict counts: the degraded replica's quality
            # block lives in its own process dir, not the router's.
            quality_blocks = [(summary.get("serve") or {}).get("quality")]
            quality_blocks += [
                (child.get("serve") or {}).get("quality")
                for child in (summary.get("processes") or {}).values()]
            if any((q or {}).get("exhausted") for q in quality_blocks):
                return 7
            # rc 10 when the brownout controller (serve/degrade.py) has
            # held L3 — shedding low-priority work — past its
            # serve.degrade.l3_sustained_s budget: quality degradation
            # was supposed to be a TRANSIENT bridge to autoscaled
            # capacity, and a fleet parked at L3 means the capacity
            # never arrived. Distinct from rc 6 (SLO budget) because a
            # browned-out fleet can sit INSIDE its latency SLO exactly
            # by refusing work.
            if (summary.get("degrade") or {}).get("l3_sustained"):
                return 10
            if not args.follow:
                return 0
            import time as _time

            _time.sleep(max(args.interval, 0.1))

    if args.cmd == "analyze":
        # deliberately light import: must not pull in jax / the train stack
        from .analyze import analyze

        try:
            summary = analyze(args.log_dir, plot=not args.no_plot)
        except FileNotFoundError:
            raise SystemExit(f"no metrics.jsonl under {args.log_dir!r} — "
                             "is this a run's --log-dir?")
        print(json.dumps(summary, indent=2))
        return 0

    if args.cmd == "bench":
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if repo_root not in sys.path:
            sys.path.insert(0, repo_root)
        import bench as bench_mod

        if args.data_only:
            h, w = bench_mod.parse_image_size(args.image_size)
            res = bench_mod.data_bench(num_workers=args.workers,
                                       batch=args.batch,
                                       batches=args.batches,
                                       image_size=(h, w),
                                       dataset=args.dataset,
                                       data_path=args.data_path,
                                       recipe_path=args.recipe or "")
        else:
            res = bench_mod.bench(model_name=args.model, batch=args.batch,
                                  steps=args.steps)
        print(json.dumps(res))
        return 0

    cfg = _build_cfg(args)
    if args.cmd == "config":
        print(json.dumps(dataclasses.asdict(cfg), indent=2, default=str))
        return 0

    if args.cmd == "train":
        if getattr(args, "host_index", None) is not None:
            cfg = cfg.replace(elastic=dataclasses.replace(
                cfg.elastic, host_index=args.host_index))
        hosts = (args.elastic if args.elastic is not None
                 else cfg.elastic.hosts)
        if hosts and hosts > 1 and cfg.elastic.host_index < 0:
            # coordinator mode (train/elastic.py): supervise the pool —
            # dispatched BEFORE jax.distributed/backend init so the
            # supervisor process stays jax-free
            if getattr(args, "multihost", False):
                raise SystemExit(
                    "train: --elastic and --multihost are exclusive — "
                    "elastic mode supervises one single-host trainer "
                    "process per host itself")
            if args.epochs is not None:
                raise SystemExit("train: elastic mode needs an absolute "
                                 "target step (--max-steps), not --epochs")
            if cfg.recipe.enabled and cfg.recipe.stages:
                raise SystemExit(
                    "train: --elastic and --recipe are exclusive — the "
                    "recipe engine drives staged single-pool runs "
                    "(train/recipe.py); run each stage elastically via "
                    "per-stage configs instead")
            # the train-package import chain below initializes a jax
            # backend (orbax does, at import): the coordinator must
            # defuse it FIRST, in EVERY mode — it computes nothing, a
            # wedged device tunnel could hang the supervisor itself
            # (the exact process the elastic layer exists to keep
            # alive), and on a real pod an accelerator-holding
            # supervisor would starve the trainer child it spawns on
            # the same host (device access is exclusive per process;
            # children acquire the real backend themselves when
            # elastic.virtual_devices=0)
            from .core.hostmesh import force_cpu_devices

            force_cpu_devices(1)  # supervisor computes nothing
            from .train.elastic import run_elastic

            try:
                return run_elastic(cfg, hosts=hosts,
                                   max_steps=args.max_steps)
            except ValueError as e:
                raise SystemExit(f"train --elastic: {e}")

    if (args.cmd in ("train", "eval")
            and cfg.elastic.host_index >= 0
            and cfg.elastic.virtual_devices > 0):
        # elastic trainer child in virtual-host mode: force its private
        # CPU device slice BEFORE any backend init (core/hostmesh.py —
        # env vars alone do not defuse the container's axon backend)
        from .core.hostmesh import force_cpu_devices

        force_cpu_devices(cfg.elastic.virtual_devices)
        if cfg.train.compile_cache is not True:
            # force_cpu_devices enables the suite's persistent compile
            # cache, but CONCURRENT trainer children reading entries
            # another process wrote is exactly the cpu cache-read heap
            # corruption bisected in r06 (TrainConfig.compile_cache):
            # the pool segfaults mid-drill. Keep the cpu auto-off
            # default real for children; compile_cache=true opts in.
            from .train.warmup import disable_compile_cache

            disable_compile_cache()

    if getattr(args, "multihost", False):
        import jax

        jax.distributed.initialize()  # coordinator/process env-configured

    if args.cmd == "warmup":
        from .train.warmup import enable_for_config, warmup_compile, warmup_serve

        # the verb's sole purpose is persisting executables: refuse to
        # silently pay minutes of XLA and persist nothing. On cpu the
        # auto default disables the cache (TrainConfig.compile_cache —
        # cross-process read corruption on this host's jaxlib), so the
        # user must opt in explicitly. EXCEPTION: `--serve-only` with
        # serve.artifacts_dir set persists through the artifact plane
        # (serve/artifacts.py — single-writer publish, no concurrent
        # cache reads), which is exactly the cpu-safe path.
        if enable_for_config(cfg) is None:
            if not (args.serve_only and cfg.serve.artifacts_dir):
                print("warmup: persistent compile cache is not active "
                      "for this config/backend (cpu auto-disables it; "
                      "add --set train.compile_cache=true to opt in, or "
                      "publish serve executables cache-free with "
                      "--serve-only --set serve.artifacts_dir=PATH) — "
                      "nothing would be persisted, refusing to compile",
                      file=sys.stderr)
                return 2
        if args.serve_only:
            res = warmup_serve(cfg)
        elif cfg.recipe.enabled and cfg.recipe.stages:
            # recipe mode (via --recipe FILE or --set recipe.*): one
            # (train, eval) executable pair PER STAGE — the stage-switch
            # zero-recompile contract's warm half (train/recipe.py)
            from .train.warmup import warmup_recipe

            res = warmup_recipe(cfg)
            if args.serve:
                res["serve"] = warmup_serve(cfg)
        else:
            res = warmup_compile(cfg, include_eval=not args.no_eval)
            if args.serve:
                res["serve"] = warmup_serve(cfg)
        print(json.dumps(res))
        # nonzero when the cache was already warm is WRONG here — a warm
        # cache is the goal; rc reflects only "did warmup complete"
        return 0

    if args.cmd == "serve":
        if (args.input is None) != (args.out is None):
            raise SystemExit("serve: offline mode needs BOTH --input and "
                             "--out (neither = HTTP server mode)")
        replicas = (args.replicas if args.replicas is not None
                    else cfg.serve.fleet.replicas)
        if args.input is not None:
            if (replicas and replicas > 1) or cfg.serve.fleet.autoscale:
                raise SystemExit("serve: --replicas/--autoscale are "
                                 "HTTP-fleet only (offline mode already "
                                 "parallelizes via serve.workers)")
            from .serve.server import run_offline

            res = run_offline(cfg, args.input, args.out,
                              write_png=not args.no_png)
            print(json.dumps(res))
            return 0
        if (replicas and replicas > 1) or cfg.serve.fleet.autoscale:
            # autoscale implies fleet mode even at --replicas 1: the
            # pool needs the supervisor/router to grow from its floor
            from .serve.fleet import run_fleet

            return run_fleet(cfg, replicas)
        from .serve.server import run_server

        return run_server(cfg)

    if args.cmd == "predict":
        pairs = []
        for item in args.pairs:
            if ":" not in item:
                raise SystemExit(f"bad --pairs {item!r}: use prev.png:next.png")
            prev, nxt = item.split(":", 1)
            pairs.append((prev, nxt))
        if args.action:
            from .predict import predict_action

            labels = None
            if args.labels:
                with open(args.labels) as f:
                    labels = [ln.strip() for ln in f if ln.strip()]
            rows = predict_action(cfg, pairs, args.out, labels=labels,
                                  ckpt_dir=args.ckpt_dir)
            print(json.dumps(
                {"written": [os.path.join(args.out, "actions.json")],
                 "actions": rows}))
            return 0
        from .predict import predict_pairs

        written = predict_pairs(cfg, pairs, args.out,
                                write_png=not args.no_png,
                                precision=args.precision)
        print(json.dumps({"written": written}))
        return 0

    from .train.loop import Trainer, install_preemption_latch

    profile_steps = None
    if getattr(args, "profile_steps", None):
        try:
            k, n = (int(x) for x in args.profile_steps.split(":"))
        except ValueError:
            raise SystemExit(
                f"bad --profile-steps {args.profile_steps!r}: use K:N "
                "(start:stop global steps)")
        if not 0 <= k < n:  # same clean exit as the syntax error above
            raise SystemExit(
                f"bad --profile-steps {args.profile_steps!r}: need "
                "0 <= K < N")
        profile_steps = (k, n)
    if getattr(args, "trace", False):
        import dataclasses as _dc

        cfg = cfg.replace(obs=_dc.replace(cfg.obs, trace=True))
    if args.cmd == "train":
        # before Trainer(): model build + first compile can take minutes,
        # and a preemption SIGTERM in that window must still checkpoint
        install_preemption_latch()
        if cfg.recipe.enabled and cfg.recipe.stages:
            # staged recipe run (train/recipe.py): one Trainer per
            # stage over the curriculum's mixtures, stage index riding
            # the checkpoint manifests, pre-compiled stage executables
            from .train.recipe import run_recipe

            out = run_recipe(cfg, max_steps=args.max_steps,
                             num_epochs=args.epochs)
            print(json.dumps(out))
            return 0
    trainer = Trainer(cfg, profile=getattr(args, "profile", False),
                      profile_steps=profile_steps)
    if args.cmd == "train":
        out = trainer.fit(num_epochs=args.epochs, max_steps=args.max_steps)
        print(json.dumps({k: float(v) for k, v in out.items()}))
    else:  # eval
        res = trainer.evaluate(dump=args.dump_visuals)
        print(json.dumps({k: float(v) for k, v in res.items()}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
