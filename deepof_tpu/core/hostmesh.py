"""Force a virtual multi-device CPU platform in this process.

The container's sitecustomize registers an experimental 'axon' TPU backend
in every interpreter; initializing it (any first `jax.devices()` /
computation) can hang on a wedged device tunnel, and `JAX_PLATFORMS=cpu`
env alone does not prevent that once jax is imported. The working defuse —
used by the test suite and the driver's multi-chip dryrun — is to set the
host-platform device count, switch the platform via `jax.config`, and drop
the axon backend factory before first backend init.
"""

from __future__ import annotations

import os
import re

# Repo-local (gitignored) so it survives across sessions: /tmp is wiped
# between rounds, which made every fresh session's first suite run pay
# ~35 min of XLA compiles (VERDICT r03 item 8). Entries are host-
# portable — XLA loads AOT results compiled on a different machine of
# the same ISA family with a benign `prefer-no-scatter/gather` feature-
# hint warning (observed across the r03->r04 host change).
# Lives under artifacts/ with the other cross-session state so one
# rsync of artifacts/ carries a warm cache to a fresh host; the warmup
# path (train/warmup.py) populates it ahead of a tunnel window.
COMPILE_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "artifacts", "xla_cache")

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_devices(n: int = 8) -> None:
    """Redirect jax onto a CPU platform with >= n virtual devices.

    Must run before any backend initialization. If backends are already
    live (a caller that intentionally initialized real hardware), they are
    left alone; callers that need n devices should assert on
    `len(jax.devices())`. Also enables the persistent compilation cache
    (the workloads behind this helper are XLA-compile-dominated).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
    if m is None:
        flags = (flags + f" {_COUNT_FLAG}={n}").strip()
    elif int(m.group(1)) < n:
        flags = flags.replace(m.group(0), f"{_COUNT_FLAG}={n}")
    os.environ["XLA_FLAGS"] = flags

    import jax
    from jax._src import xla_bridge

    if not xla_bridge._backends:
        jax.config.update("jax_platforms", "cpu")
        xla_bridge._backend_factories.pop("axon", None)
    jax.config.update("jax_compilation_cache_dir", COMPILE_CACHE_DIR)
    # Keep jax's 1 s min-compile-time default: persisting sub-second
    # entries was tried and reverted — serializing thousands of tiny CPU
    # executables (jaxlib 0.4.37) intermittently aborts/segfaults the
    # process mid-suite (background cache-writer threads racing dispatch;
    # reproduced with an empty cache dir, gone at the default threshold).
    # The multi-second model/step compiles that dominate cold starts all
    # clear 1 s and are exactly what the warmup path needs cached.
    # Known residual risk (r06 bisect, TrainConfig.compile_cache): cpu
    # cache READS of entries written by another process intermittently
    # corrupt the heap on this jaxlib. For the suite that means slow-tier
    # tests re-reading a previous session's entries; accepted here for
    # the ~35 min/session compile saving — the suite has been empirically
    # stable — while the CLI/bench default (auto) stays off on cpu.
    # r07 addendum: a NEW in-process Trainer.fit() test whose train-step
    # executable is already cached crashed 4/4 warm runs (rc=139/134 at
    # steady-state pjit dispatch, reproduced with every obs feature
    # disabled) — fit-shaped integration tests should drive the CLI in a
    # subprocess instead, where the cpu auto-gate keeps the cache off
    # (tests/test_obs.py::test_fit_writes_trace_heartbeat_and_telemetry).
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
