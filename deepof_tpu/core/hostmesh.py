"""Force a virtual multi-device CPU platform in this process.

The container's sitecustomize registers an experimental 'axon' TPU backend
in every interpreter; initializing it (any first `jax.devices()` /
computation) can hang on a wedged device tunnel, and `JAX_PLATFORMS=cpu`
env alone does not prevent that once jax is imported. The working defuse —
used by the test suite and the driver's multi-chip dryrun — is to set the
host-platform device count, switch the platform via `jax.config`, and drop
the axon backend factory before first backend init.
"""

from __future__ import annotations

import os
import re

# Repo-local (gitignored) so it survives across sessions: /tmp is wiped
# between rounds, which made every fresh session's first suite run pay
# ~35 min of XLA compiles (VERDICT r03 item 8). Entries are host-
# portable — XLA loads AOT results compiled on a different machine of
# the same ISA family with a benign `prefer-no-scatter/gather` feature-
# hint warning (observed across the r03->r04 host change).
COMPILE_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache")

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_devices(n: int = 8) -> None:
    """Redirect jax onto a CPU platform with >= n virtual devices.

    Must run before any backend initialization. If backends are already
    live (a caller that intentionally initialized real hardware), they are
    left alone; callers that need n devices should assert on
    `len(jax.devices())`. Also enables the persistent compilation cache
    (the workloads behind this helper are XLA-compile-dominated).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
    if m is None:
        flags = (flags + f" {_COUNT_FLAG}={n}").strip()
    elif int(m.group(1)) < n:
        flags = flags.replace(m.group(0), f"{_COUNT_FLAG}={n}")
    os.environ["XLA_FLAGS"] = flags

    import jax
    from jax._src import xla_bridge

    if not xla_bridge._backends:
        jax.config.update("jax_platforms", "cpu")
        xla_bridge._backend_factories.pop("axon", None)
    jax.config.update("jax_compilation_cache_dir", COMPILE_CACHE_DIR)
