"""Shared supervisor core: the child-process plumbing both supervisors
in this repo are built on.

The serving fleet (`serve/fleet.py`, PR 6) and the elastic trainer pool
(`train/elastic.py`, PR 8) are sibling supervisors: each spawns detached
`deepof_tpu <verb> --config-json <child-dir>/config.json` subprocesses,
judges their health from pid-gated `heartbeat.json` reads, evicts with
SIGTERM-then-SIGKILL, respawns with exponential backoff, and drains
gracefully on shutdown. That plumbing was written twice — CHANGES.md
named the extraction as a deferred follow-on from PR 8 on — and this
module is the extraction: the PURE decision pieces (heartbeat verdict,
backoff arithmetic, crash-loop breaker counting) plus the effectful
helpers both supervisors call identically (child-dir preparation, env
assembly, detached spawn, quiet signal delivery, bounded reap).

Deliberately policy-free: the fleet respawns failed replicas in place
while the elastic coordinator never respawns a lost host (it re-forms
the generation on the survivors) — those state machines stay in their
modules, built from these parts. Behavior across the extraction is
pinned by the existing fleet + elastic chaos suites.

The fleet autoscaler (`serve/autoscale.py`) is the first NEW subsystem
built directly on this core: scale-up is one more `spawn_child`, scale-
down is the graceful half of the eviction ladder (drain, SIGTERM, reap)
applied to a healthy replica.

Stdlib-only at import (the supervisor discipline: a supervisor performs
no jax computation and must never touch an accelerator backend its
children need).
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import subprocess
import time
from typing import Callable

#: Repo root — children run with this cwd and import the package from it.
REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


# ----------------------------------------------------------- TCP probes


def listening(host: str, port: int) -> bool:
    """True when something accepts TCP connections on host:port."""
    try:
        with socket.create_connection((host, port), timeout=0.5):
            return True
    except OSError:
        return False


def wait_for_listen(host: str, port: int, timeout_s: float = 20.0,
                    interval_s: float = 0.05) -> None:
    """Block until something accepts TCP connections on host:port, or
    raise TimeoutError — the connect-before-bind guard the fleet and the
    test suite share (tests/conftest.py re-exports it)."""
    deadline = time.monotonic() + max(float(timeout_s), 0.0)
    while True:
        if listening(host, port):
            return
        if time.monotonic() >= deadline:
            raise TimeoutError(f"nothing listening on {host}:{port} "
                               f"within {timeout_s}s")
        time.sleep(interval_s)


# ------------------------------------------------------------ child rec


class Child:
    """Supervisor-side record of one supervised child slot. Subclassed
    by the fleet's `_Replica` and the coordinator's `_TrainerHost`,
    which add their subsystem-specific fields; mutation discipline
    (which lock, if any) is the subclass owner's contract."""

    def __init__(self, idx: int, state: str):
        self.idx = idx
        self.state = state
        self.proc: subprocess.Popen | None = None
        self.incarnation = 0
        self.started_m = 0.0
        self.last_exit: int | None = None
        self.last_reason: str | None = None


# ----------------------------------------------------- heartbeat verdict


def read_heartbeat(child_dir: str) -> dict | None:
    """The child's heartbeat.json content, or None when absent/torn
    (the file is atomically rewritten, so torn means 'not yet')."""
    try:
        with open(os.path.join(child_dir, "heartbeat.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def pid_gated(hb: dict | None, pid: int | None) -> dict | None:
    """The heartbeat, or None when it belongs to another incarnation —
    a dead incarnation's file (possibly `wedged: true` after a SIGKILL
    skipped the final write) can neither vouch for nor condemn the
    current process."""
    if hb is not None and pid is not None \
            and hb.get("pid") not in (None, pid):
        return None
    return hb


def heartbeat_verdict(hb: dict | None, pid: int | None, now_wall: float,
                      stale_after_s: float, stall_after_s: float,
                      stall_gate: Callable[[dict], bool] | None = None
                      ) -> str:
    """Pure health verdict for one child from its heartbeat CONTENT —
    the decision function both supervisors share.

    Returns one of:
      "no_heartbeat"  — no (readable) file yet: pre-start grace, judged
                        only by the caller's spawn timeout;
      "foreign_pid"   — the file belongs to another incarnation: same
                        treatment as no_heartbeat;
      "wedged"        — the child's own watchdog declared the wedge;
      "stale"         — the heartbeat thread itself stopped writing
                        (frozen/SIGSTOPped process, dead host);
      "stalled"       — the file is fresh but `last_step_age_s` grew
                        past `stall_after_s` while `stall_gate(hb)`
                        holds — progress hung before the child's own
                        watchdog (which needs beats to arm) would say
                        so. The gate is the subsystem's "is the stall
                        clock meaningful" predicate: the fleet requires
                        requests in flight, the coordinator requires
                        >= 1 completed step (a first-dispatch compile
                        is never judged). stall_after_s <= 0 disables;
      "ok"            — healthy.
    """
    if hb is None:
        return "no_heartbeat"
    if pid_gated(hb, pid) is None:
        return "foreign_pid"
    if hb.get("wedged"):
        return "wedged"
    t = hb.get("time")
    if isinstance(t, (int, float)) and now_wall - t > float(stale_after_s):
        return "stale"
    age = hb.get("last_step_age_s")
    if (float(stall_after_s) > 0
            and (stall_gate is None or stall_gate(hb))
            and isinstance(age, (int, float))
            and age > float(stall_after_s)):
        return "stalled"
    return "ok"


# ------------------------------------------------- backoff + breaker


def crash_loop_update(fast_failures: int, fast: bool,
                      clean: bool = False) -> int:
    """Next consecutive-fast-failure count after one child death. Only
    a FAST non-clean death counts toward the crash-loop breaker: a slow
    death resets it (the breaker is for crash loops, not for a child
    that ran healthily and then died once), and a clean rc=0 exit never
    counts either way (rolling restarts — however quick — must not open
    the breaker)."""
    if clean:
        return fast_failures
    return fast_failures + 1 if fast else 0


def backoff_delay(base_s: float, cap_s: float, fast_failures: int) -> float:
    """Exponential respawn backoff: base * 2^(fast_failures - 1),
    capped. Deliberately reproduces the fleet's historical arithmetic
    exactly, including the half-base delay at a reset (0) count."""
    return min(float(base_s) * 2 ** (fast_failures - 1), float(cap_s))


def breaker_open(fast_failures: int, threshold: int) -> bool:
    """True when the crash-loop circuit breaker should open (the child
    stays down, surfaced, instead of burning backoff forever while
    masking the defect)."""
    return fast_failures >= int(threshold)


# ---------------------------------------------------------- child spawn


def prepare_child_dir(child_dir: str, cfg) -> str:
    """Make the child's directory, delete any previous incarnation's
    heartbeat.json (a dead incarnation's file must not speak for the
    next — the pid gate would reject it anyway; deleting keeps verdicts
    unambiguous), and serialize the child's EXACT config tree to
    config.json (`core/config.config_from_dict` is the inverse).
    Returns the config path."""
    os.makedirs(child_dir, exist_ok=True)
    try:
        os.remove(os.path.join(child_dir, "heartbeat.json"))
    except OSError:
        pass
    cfg_path = os.path.join(child_dir, "config.json")
    with open(cfg_path, "w") as f:
        json.dump(dataclasses.asdict(cfg), f, indent=2)
    return cfg_path


def child_env(extra: dict | None = None, force_cpu: bool = False) -> dict:
    """The spawn environment: the parent's env with the repo root on
    PYTHONPATH (children import the package from the checkout, whatever
    the parent's cwd), optional JAX_PLATFORMS=cpu (a jax-free fake
    replica or virtual-host trainer must never probe the accelerator
    tunnel), and any caller extras (replica identity, ...)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    if force_cpu:
        env.setdefault("JAX_PLATFORMS", "cpu")
    if extra:
        env.update(extra)
    return env


def spawn_child(argv: list[str], env: dict, stdout, stderr,
                **popen_kw) -> subprocess.Popen:
    """Detached child spawn: cwd pinned to the repo root and
    start_new_session=True — the parent's ^C is not the child's, so
    every supervisor OWNS teardown on every exit path (see the run_*
    entries' finally blocks)."""
    return subprocess.Popen(argv, cwd=REPO_ROOT, env=env, stdout=stdout,
                            stderr=stderr, start_new_session=True,
                            **popen_kw)


# ------------------------------------------------ signals + bounded reap


def terminate_quietly(proc: subprocess.Popen | None) -> None:
    """SIGTERM, swallowing the already-dead race."""
    if proc is not None:
        try:
            proc.terminate()
        except OSError:
            pass


def kill_quietly(proc: subprocess.Popen | None) -> None:
    """SIGKILL, swallowing the already-dead race."""
    if proc is not None:
        try:
            proc.kill()
        except OSError:
            pass


def reap_within(proc: subprocess.Popen | None,
                deadline_m: float) -> int | None:
    """Wait for a child until the monotonic deadline, SIGKILL on expiry
    (the escalation half of SIGTERM-then-SIGKILL), and return its exit
    code. None for a never-spawned slot."""
    if proc is None:
        return None
    try:
        proc.wait(timeout=max(deadline_m - time.monotonic(), 0.1))
    except subprocess.TimeoutExpired:
        kill_quietly(proc)
        proc.wait()
    return proc.returncode
