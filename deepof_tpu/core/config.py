"""Experiment configuration.

One frozen dataclass tree per experiment replaces the reference's scattered
`tf.app.flags` + hard-coded constants + placeholder-fed hyper-parameter lists
(reference `flyingChairsTrain.py:14-53`, `sintelTrain.py:13-56`,
`version1/deepOF.py:12-35`, `version1/trainOF.py:45-53`).

Presets encode the reference's published hyper-parameter baselines
(see BASELINE.md table): FlyingChairs, FlyingChairs-VGG, Sintel, UCF-101.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any

from ..resilience.faults import FaultConfig


@dataclass(frozen=True)
class LossConfig:
    """Unsupervised pyramid-loss hyper-parameters.

    Mirrors the reference's (epsilon, alpha_c, alpha_s, lambda_smooth)
    quadruple (`flyingChairsWrapFlow.py:43-46`, `sintelTrain.py:50-53`,
    `version1/trainOF.py:45-53`) plus structural switches for the smoothness
    variant and edge-aware weighting.
    """

    epsilon: float = 1e-4
    alpha_c: float = 0.25
    alpha_s: float = 0.37
    lambda_smooth: float = 1.0
    # Per-scale loss weights, finest (pr1) first — reference weight_L
    # schedules e.g. [16,8,4,2,1,1] (`flyingChairsTrain.py:165`).
    weights: tuple[float, ...] = (16.0, 8.0, 4.0, 2.0, 1.0, 1.0)
    # "canonical": fused forward-difference filter (x-grad of U, y-grad of V;
    #   `flyingChairsWrapFlow.py:854`); "depthwise": both-direction gradients
    #   per component (`version1/model/warpflow.py:133-136`).
    smoothness: str = "canonical"
    # 1 = first differences (the reference's prior); 2 = second
    # differences (opt-in): penalizes flow curvature instead of slope, so
    # affine motion fields (dominant-plane scenes) are free — a standard
    # quality knob in modern unsupervised flow.
    smoothness_order: int = 1
    # Edge-aware Sobel image-gradient weighting of the smoothness term
    # (`loss_interp_bk`, `version1/model/warpflow.py:93-157`).
    edge_aware: bool = False
    # needImageGradients (`flyingChairsWrapFlow_vgg.py:226-301`): the
    # per-sample min-max-normalized Sobel gradient MAGNITUDE of the target
    # image multiplies the Charbonnier photometric elementwise loss
    # (gradient-rich pixels emphasized) and its complement (1 - |grad|)
    # multiplies both smoothness terms (edges may move freely). Charbonnier
    # photometric, two-frame loss only (multi-frame volume configs are
    # rejected — the reference feature exists only in the vgg 2-frame
    # variant). NOTE: the reference only ever pairs this with
    # smoothness='depthwise' (the vgg variant's shape); combining it with
    # smoothness='canonical' is accepted as an EXTENSION beyond the
    # reference — strict-parity configs should set both together.
    edge_aware_photo: bool = False
    # Smooth the *scaled* flow (canonical `flyingChairsWrapFlow.py:785,854`)
    # vs the raw head output (gen-1 `version1/model/warpflow.py:37,133`).
    smooth_scaled_flow: bool = True
    border_ratio: float = 0.1
    # Warp implementation: "xla" (one fused patch-gather, any level
    # size), "pallas" (VMEM row-sweep kernel, W <= 128 only), "auto"
    # (pallas wherever admissible, xla for fine levels). Default "auto":
    # measured fastest on v5e at every admissible level shape, fwd and
    # grad (perf_probe warp section, r03; see ops/pallas/warp.py).
    warp_impl: str = "auto"
    # Warp OPERAND dtype for the photometric reconstruction gather:
    # "float32" (exact reference numerics, default) or "bfloat16" (half
    # the gathered bytes on the fine-level XLA path; ~0.4% relative
    # quantization of the warped image and its flow-gradient factors —
    # an opt-in throughput lever, see DESIGN.md).
    gather_dtype: str = "float32"
    # Photometric penalty: "charbonnier" = the reference's raw-RGB
    # Charbonnier (`flyingChairsWrapFlow.py:841-851`); "census" = soft
    # census-transform distance (ops/census.py) — illumination-robust,
    # the standard quality upgrade in modern unsupervised flow (opt-in;
    # changes the loss scale, so retune lambda_smooth/weights).
    photometric: str = "charbonnier"
    census_window: int = 7
    # Forward-backward occlusion masking (opt-in; UnFlow/UFlow lineage):
    # the model also runs on the swapped pair, and pixels failing the
    # fw/bw consistency check |f_fw + warp(f_bw)|^2 <
    # occ_alpha*(|f_fw|^2+|warp(f_bw)|^2) + occ_beta are excluded from
    # the photometric term (their appearance is unobservable in the other
    # frame). Costs a second forward pass. Flow-only 2-frame models.
    occlusion: bool = False
    occ_alpha: float = 0.01
    occ_beta: float = 0.5
    # Per-occluded-pixel penalty (added as occ_penalty * occluded interior
    # fraction). Must be > 0: with a free mask the degenerate optimum is
    # to declare hard regions occluded (UnFlow's lambda_p guard).
    occ_penalty: float = 1.0


@dataclass(frozen=True)
class OptimConfig:
    """Adam + stepwise LR decay (reference `flyingChairsTrain.py:27-33,124`)."""

    learning_rate: float = 1.6e-5
    decay_factor: float = 0.5
    epochs_per_decay: int = 18
    beta1: float = 0.9
    beta2: float = 0.999
    adam_eps: float = 1e-8
    grad_clip_norm: float | None = None
    # Accumulate gradients over N micro-batches before each optimizer
    # update (optax.MultiSteps) — effective batch = N * batch_size when
    # the global batch exceeds HBM even with remat. LR-decay boundaries
    # stay aligned to data epochs (the schedule is stretched to count
    # micro-steps).
    grad_accum: int = 1


@dataclass(frozen=True)
class DataConfig:
    dataset: str = "flyingchairs"  # flyingchairs | sintel | ucf101 | synthetic
    data_path: str = ""
    image_size: tuple[int, int] = (384, 512)  # (H, W) network input
    gt_size: tuple[int, int] = (384, 512)  # native ground-truth resolution
    batch_size: int = 4
    time_step: int = 2  # frames per sample; Sintel volumes use 10
    sintel_pass: str = "final"  # clean | final
    # Gen-1 Sintel pair-mode split (`version1/loader/sintelLoader.py:
    # 38-70`): path to Sintel_train_val.txt — one line per consecutive
    # frame pair over sorted clips x sorted frames ("1" = train,
    # "2" = val). Requires time_step=2 (the gen-1 loader is pair-only);
    # None keeps the gen-2 window-membership split.
    sintel_pair_split_file: str | None = None
    # Host-side augmentation streams (reference `flyingChairsTrain_vgg.py:186-195`):
    # photometric-augmented pair feeds the network, geometric-only feeds the loss.
    augment_geo: bool = False
    augment_photo: bool = False
    crop_size: tuple[int, int] | None = None
    prefetch: int = 2
    # Host input-pipeline worker threads (data/pipeline.py): N workers
    # decode/resize/augment/stack (super-)batches out-of-order and
    # deliver them in order through a bounded reorder buffer, with
    # deterministic per-batch seeding — the delivered stream is
    # bit-identical for any worker count. 0 = assemble inline on the
    # prefetch thread (the legacy single-thread path, zero overhead).
    # -1 = auto (data/pipeline.py resolve_num_workers): 0 on hosts with
    # <= 2 cores — BENCH_r06 measured workers=4 at 49.5 vs workers=0 at
    # 85.3 batches/s on a small host (thread contention, nothing to
    # overlap) — else min(4, cores - 2). cv2 and the native C++ IO
    # release the GIL, so decode parallelism is real; size to the host
    # cores left over after the runtime.
    num_workers: int = 0
    # Reorder-buffer bound: how many batches workers may run ahead of
    # delivery (caps buffered-batch memory when one slow batch holds
    # back the cursor). 0 = auto (2 x num_workers). NOTE: with
    # on-device augmentation (augment_geo/augment_photo) the buffered
    # batches are DEVICE arrays, so this bound spends HBM, not host
    # RAM — at large batch x steps_per_call, size it (and num_workers)
    # against the chip's memory headroom.
    reorder_depth: int = 0
    cache_decoded: bool = True
    # byte budget of the decoded-image LRU (host RAM). The cache stores
    # NATIVE-resolution decoded images (resize happens per batch), so the
    # full 22,872-pair FlyingChairs set (~25 GiB at 384x512) does NOT fit
    # the default — use streaming mode (cache_decoded=False) there; 4 GiB
    # pins Sintel (~1k frames/pass) and the val splits comfortably.
    cache_bytes: int = 4 << 30


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh axes for pjit sharding (no reference equivalent; the
    reference is single-GPU, `flyingChairsTrain.py:99`)."""

    data: int = -1  # -1: all available devices on the data axis
    spatial: int = 1  # spatial context-parallel shards of H
    time: int = 1  # temporal pair-parallel shards (Sintel T-1 pairs)


@dataclass(frozen=True)
class TrainConfig:
    num_epochs: int = 110
    log_every: int = 500
    eval_every: int = 5000  # steps; 0 = only at epoch end
    ckpt_every_epochs: int = 18
    # Step-granularity checkpointing (0 = epoch cadence only). The
    # reference saves only every N epochs and restarts its LR schedule on
    # resume (SURVEY.md §5.3-5.4); step cadence bounds work lost to
    # preemption to ckpt_every_steps steps.
    ckpt_every_steps: int = 0
    keep_ckpts: int = 3
    seed: int = 0
    log_dir: str = "/tmp/deepof_tpu"
    # eval protocol: finest flow is multiplied by `amplifier`, clipped, and
    # resized to gt_size before AEE (`flyingChairsTrain.py:264-296`).
    eval_amplifier: float = 2.0
    eval_clip: tuple[float, float] = (-300.0, 250.0)
    eval_batch_size: int = 8
    nan_guard: bool = True
    dump_visuals: bool = False
    # Another run's log_dir to transfer-initialize from on fresh starts:
    # params with matching path+shape are grafted (trunk transfers; pr
    # heads / first conv re-init when T differs). The Chairs->Sintel
    # fine-tune path (reference paper recipe; BASELINE.json north star).
    init_from: str = ""
    # Path to the public `vgg16_weights.npz`; when set, VGG-trunk models
    # start from these conv weights with first-layer in-channel duplication
    # (reference `flyingChairsTrain.py:60-76,142-145`, `ucf101train.py:68-88`
    # with VGG16Init=True). No auto-download (zero-egress). A restored
    # checkpoint takes precedence.
    vgg16_npz: str = ""
    compute_dtype: str = "float32"  # float32 | bfloat16
    # jax.checkpoint the model forward: recompute activations in backward
    # instead of storing them — trades FLOPs for HBM (for high-res /
    # long-T configs that would not otherwise fit).
    remat: bool = False
    # Optimizer steps per jit call (lax.scan over stacked batches). >1
    # amortizes per-dispatch host/RTT overhead — significant on tunneled
    # or remote device transports (DESIGN.md "Benchmark honesty") — at
    # the cost of log/eval granularity rounding up to a multiple of K.
    steps_per_call: int = 1
    # --- Latency-hiding execution layer (DESIGN.md "Execution layer") ---
    # Persistent on-disk XLA compilation cache: a process whose graphs
    # were compiled before (same config, jax/XLA version, backend) loads
    # executables instead of recompiling — minutes saved per cold start
    # on a scarce tunnel window. The `warmup` CLI verb populates it
    # ahead of time (train/warmup.py). None = auto: enabled on
    # accelerator backends (the tunnel-window target), DISABLED on cpu —
    # this host's grafted jaxlib intermittently corrupts the heap when
    # deserializing cache entries written by another process on the cpu
    # backend (~50% of warm CLI runs: spurious NaN rollbacks, rc=139/134;
    # bisected r06 — writes and cache-off runs are clean). True forces it
    # on (tests, opt-in CPU experiments); False forces it off.
    compile_cache: bool | None = None
    # Cache location; "" = <repo>/artifacts/xla_cache (hostmesh.py).
    compile_cache_dir: str = ""
    # Max in-flight async metric fetches: the loop dispatches the next
    # step(s) while previous calls' metric values are still in transit,
    # draining them on a background consumer. 0 = fetch synchronously
    # (the pre-r06 serial dispatch->fetch->dispatch loop). Bounded depth
    # keeps the dispatch clock honest: a full queue blocks dispatch, so
    # host-side progress can never run more than `pipeline_depth` calls
    # ahead of device completion (DESIGN.md "Benchmark honesty").
    pipeline_depth: int = 2


@dataclass(frozen=True)
class ObsConfig:
    """Unified observability layer (deepof_tpu/obs/): cross-thread span
    tracing, liveness heartbeat + wedge watchdog, and train-record
    telemetry. DESIGN.md "Observability" explains what each instrument
    answers."""

    # Ring-buffered span tracer: fit() writes a Perfetto/chrome://tracing
    # loadable Chrome trace-event timeline to <log_dir>/trace.json
    # (main-thread dispatch/eval/ckpt, prefetch put, fetcher fetch,
    # pipeline-worker assemble — the thread overlap made visible).
    trace: bool = False
    # Max retained span events (bounded memory; newest win — the window
    # leading into a stall is the one that matters).
    trace_ring: int = 16384
    # Background liveness file: <log_dir>/heartbeat.json atomically
    # rewritten every heartbeat_period_s with step, rates, queue/staged
    # depths, device memory, and process RSS — progress is one `cat`
    # (or `deepof_tpu tail`) away, even from outside the process.
    heartbeat: bool = True
    heartbeat_period_s: float = 5.0
    # Wedge watchdog: declare a stall when no step completes within
    # watchdog_factor x a robust (median) recent-step-time estimate,
    # floored by watchdog_min_s (so eval pauses / scheduler jitter never
    # fire). On a wedge: all thread stacks dumped to the metrics log,
    # trace ring flushed. Observe-and-report only — never kills the run.
    watchdog_factor: float = 20.0
    watchdog_min_s: float = 60.0
    # XLA cost-analysis FLOPs at first step (lower-only, no extra
    # compile): every periodic train record then carries model_tflops +
    # nominal MFU — the bench-only telemetry, promoted into training.
    flops: bool = True
    # Executable ledger (obs/ledger.py, DESIGN.md "Executable ledger"):
    # every lowering (train step, eval, the serve bucket x tier x mode
    # lattice, quality scorers) appends a provenance row — StableHLO
    # fingerprint, compile seconds, persistent-cache hit/miss, XLA cost
    # analysis, memory footprint, donation map — to <log_dir>/
    # ledger.jsonl, and the exec_* counter block rides heartbeat +
    # /metrics. Costs nothing on the request hot path (rows are written
    # at compile time); tools/ledger_diff.py + `tail` rc 8 turn the
    # rows into a perf-regression gate against a committed baseline.
    ledger: bool = True
    # --- Fleet observability plane (obs/export.py + obs/aggregate.py,
    # DESIGN.md "Fleet observability") ---
    # SLO latency target in ms: requests slower than this (rounded UP to
    # the nearest fixed histogram bucket bound — the bucket contract
    # that makes burn identical at every aggregation level) breach the
    # SLO, and breaches + server-side failures burn the error budget.
    # The serve engine reports `serve_slo`, the fleet router
    # `fleet_slo` (on /healthz, /metrics, heartbeat, and `tail`, which
    # exits 6 when the budget is exhausted). 0 disables the SLO layer.
    slo_latency_ms: float = 0.0
    # Allowed bad fraction (latency breaches + failures over admitted
    # requests); burn = bad_fraction / budget, exhausted at burn >= 1.
    slo_error_budget: float = 0.01
    # Standalone GET /metrics + /healthz endpoint for processes without
    # an HTTP frontend of their own (the elastic coordinator binds one
    # when set; the serve server and fleet router mount /metrics on
    # their existing ports instead). None = off; 0 = ephemeral port
    # (announced on stdout); > 0 = that port.
    metrics_port: int | None = None
    # --- Label-free flow-quality observability (obs/quality.py,
    # DESIGN.md "Quality observability") ---
    # Fraction of served requests scored with the label-free quality
    # proxy (Charbonnier photometric error on warp(frame2, flow) vs
    # frame1, census distance, flow-smoothness magnitude) OFF the hot
    # path: sampled rows go to a bounded queue + one scorer thread; a
    # full queue drops the sample (counted), never blocks a response.
    # 0 = off — the serve path is then bitwise- and schema-unchanged.
    # Sampling is deterministic in (quality_seed, request index).
    quality_sample_rate: float = 0.0
    quality_seed: int = 0
    # Scorer-queue bound: samples waiting to be scored (each holds one
    # preprocessed input row + one flow row). Full = drop-and-count.
    quality_queue_depth: int = 128
    # Drift detection: the first quality_ref_samples scored requests
    # freeze a reference median photometric proxy; afterwards a sample
    # whose photo proxy exceeds ref_p50 * quality_drift_factor is a
    # BREACH, and breaches / post-reference samples burn quality_budget
    # (the SLO error-budget pattern). Exhaustion => `tail` exit code 7.
    # quality_window bounds the rolling current-p50 window the verdict
    # reports alongside the reference.
    quality_ref_samples: int = 64
    quality_window: int = 256
    quality_drift_factor: float = 2.0
    quality_budget: float = 0.1
    # --- Incident plane (obs/incident.py, DESIGN.md "Incident plane") ---
    # Anomaly-triggered flight recorder: every verdict site (watchdog
    # wedge, fleet eviction/broken, elastic re-form/abort, SLO/quality
    # budget exhaustion, ledger drift, deep-verify demote, NaN
    # rollback) snapshots a bounded evidence bundle into
    # <log_dir>/incidents/ — trace ring, last-K heartbeats, metrics
    # tail, thread stacks, ledger rows, manifest. False (the default)
    # is a structural no-op: no recorder object exists, no incident_*
    # key enters any stats block, zero hot-path cost.
    incidents: bool = False
    # Token-bucket rate limit across ALL incident kinds: burst capacity
    # refilled at rate_per_min — a trigger storm cannot fill the disk.
    incident_rate_per_min: float = 6.0
    incident_burst: int = 3
    # Per-kind dedup: a kind that already captured within this window
    # is counted (incident_deduped), not re-captured. Also the re-fire
    # cadence of a continuously-true alert rule.
    incident_dedup_window_s: float = 300.0
    # Bundle bounds: newest metrics/ledger lines per bundle, heartbeat
    # samples ring-buffered into heartbeats.jsonl, and the committed-
    # bundle count beyond which the oldest are pruned at capture time.
    incident_metrics_tail: int = 200
    incident_heartbeats: int = 8
    incident_keep: int = 32
    # Declarative alert rules evaluated on the heartbeat cadence over
    # registry-declared counters — "[name:] [rate(]counter[)] OP value
    # [warn|critical]", e.g. "err_burst: rate(serve_errors) > 5
    # critical". A firing rule records an incident of kind
    # alert_<name>. Malformed rules and unregistered counters fail
    # loudly at process start.
    alerts: tuple[str, ...] = ()


@dataclass(frozen=True)
class FleetConfig:
    """Self-healing serving fleet (serve/fleet.py + serve/router.py,
    DESIGN.md "Fleet"): N supervised engine-replica subprocesses behind
    a health-gated router. The supervisor evicts stale/wedged replicas
    (SIGTERM then SIGKILL), respawns with exponential backoff, and stops
    respawning a crash-looping replica (circuit breaker); the router
    keeps bucket-affinity executables hot, replays failed requests on
    healthy siblings, and sheds load with structured 503s when every
    replica is saturated."""

    # replica count behind the router; 0/1 = single-process serve (the
    # `serve --replicas N` CLI flag overrides this)
    replicas: int = 0
    # supervisor health-poll cadence
    poll_s: float = 1.0
    # a READY replica whose heartbeat.json is older than this is evicted
    # (the serve heartbeat rewrites every obs.heartbeat_period_s, so
    # size this to several periods)
    stale_after_s: float = 15.0
    # supervisor-side stall detector, independent of the replica's OWN
    # wedge watchdog (which arms only after 3 completed flushes — a
    # dispatch that hangs on flush 1 or 2 would otherwise keep a fresh,
    # never-wedged heartbeat forever): evict a replica whose heartbeat
    # shows requests in flight but no completion for this long. Safe
    # against cold-start false positives because engine.warm()
    # compiles the whole bucket ladder BEFORE the replica announces, so
    # a dispatch slower than this is a hang, not a compile. Must exceed
    # the worst-case honest dispatch time; 0 disables.
    stall_after_s: float = 60.0
    # how long an announced replica may take to start listening before
    # the spawn is declared failed (covers model restore + warm compile)
    spawn_timeout_s: float = 180.0
    # eviction: SIGTERM first (graceful drain), SIGKILL after this grace
    term_grace_s: float = 5.0
    # respawn backoff: backoff_s * 2^(consecutive fast failures), capped
    backoff_s: float = 0.5
    backoff_max_s: float = 30.0
    # circuit breaker: this many CONSECUTIVE fast failures (died within
    # healthy_after_s of becoming ready, or never became ready) stops
    # respawning the replica — a crash loop burns backoff forever and
    # masks the real defect; surviving replicas keep serving
    crash_loop_threshold: int = 3
    # alive this long after ready resets the fast-failure counter
    healthy_after_s: float = 5.0
    # failover: how many times ONE request may be replayed on a
    # different replica after a transport error / replica 5xx (requests
    # are pure, so replay is idempotent by construction)
    failover_retries: int = 2
    # router-side per-replica in-flight cap: when EVERY healthy replica
    # is at this bound the request is shed with a structured 503
    # instead of queuing unboundedly at the router
    max_in_flight: int = 32
    # per-replica in-flight level above which the router spills a
    # request past its affinity replica to the next healthy one.
    # 0 = auto (serve.max_batch): below one full batch the affinity
    # replica keeps its executables hot; above it, spreading wins.
    spill_in_flight: int = 0
    # per-attempt proxy timeout (a wedged replica's request times out
    # here and replays on a sibling; the watchdog/evictor handles the
    # replica itself)
    proxy_timeout_s: float = 30.0
    # graceful shutdown: stop admission, wait this long for in-flight
    # requests to flush before reaping replicas
    drain_timeout_s: float = 10.0
    # Artifact-store GC on the retirement path (ROADMAP item 5b): every
    # graceful replica retirement and fleet close sweeps the store —
    # corrupt entries and orphaned tmp staging always go; entries older
    # than this many days also go UNLESS pinned (the index's targets
    # and every fingerprint a live replica's ledger recorded are always
    # roots, so a sweep can never collect an executable the lattice
    # boots from). <= 0 keeps the sweep corrupt/tmp-only (no age-out).
    artifacts_gc_days: float = 0.0
    # --- SLO-driven autoscaler (serve/autoscale.py, DESIGN.md
    # "Supervision plane"): the fixed `--replicas N` pool becomes a
    # load-follower between min_replicas and max_replicas, scaling up on
    # sustained shed/overload, SLO breach burn, or near-saturation
    # occupancy, and down on sustained idle — always via graceful drain
    # (retire, never evict: `tail`'s rc-4 contract stays about
    # sickness). Hysteresis lives in the threshold gap (up_occupancy >>
    # down_occupancy) + the sustain windows; the cooldowns keep the
    # respawn-compile cost of a fresh replica from flapping the pool.
    autoscale: bool = False
    # pool bounds: the autoscaler owns the size between these
    min_replicas: int = 1
    max_replicas: int = 4
    # control-loop evaluation cadence
    autoscale_period_s: float = 1.0
    # scale up only after pressure (shed/overload delta, SLO breach
    # burn, occupancy >= up threshold) persists this long
    autoscale_up_after_s: float = 2.0
    # scale down only after idleness (occupancy <= down threshold AND
    # zero shed) persists this long — much longer than the up window:
    # adding capacity late sheds traffic, removing it late wastes a
    # replica
    autoscale_down_after_s: float = 20.0
    # pool occupancy (router in-flight / (ready * max_in_flight)) at or
    # above which a tick counts as pressure
    autoscale_up_occupancy: float = 0.75
    # occupancy at or below which a tick counts as idle; the wide gap
    # to up_occupancy is the hysteresis band where the pool holds steady
    autoscale_down_occupancy: float = 0.15
    # SLO budget-burn fraction (obs.slo_latency_ms must be set) at or
    # above which NEW latency breaches count as pressure — capacity is
    # added while the budget still has headroom, not after exhaustion
    autoscale_up_slo_burn: float = 0.5
    # Predictive pressure (ISSUE 16): requests/s GROWTH (req/s per
    # second, least-squares slope over the router's per-second
    # completion buckets) at or above this counts a tick as pressure —
    # the pool scales on the load *trend*, before occupancy saturates
    # or the first shed lands. The same up_after_s sustain window and
    # cooldowns apply, so one noisy second never spawns a replica.
    # <= 0 disables the slope signal (reactive-only, the r14 behavior).
    autoscale_up_slope: float = 0.0
    # no second scale-up within this window of the previous one: a
    # burst must not spawn the whole ladder before the first new
    # replica has even compiled
    autoscale_up_cooldown_s: float = 5.0
    # no scale-down within this window of ANY scale event: a fresh
    # replica's warm-up idle must not immediately retire its sibling
    autoscale_down_cooldown_s: float = 30.0


@dataclass(frozen=True)
class DegradeConfig:
    """Brownout control plane (serve/degrade.py, DESIGN.md "Brownout"):
    under overload the fleet walks declared quality-degradation levels
    instead of shedding default-priority work —

      L0 normal -> L1 downgrade the DEFAULT precision tier (requests
      that name no `precision` serve at the cheapest configured tier)
      -> L2 additionally route to the next-smaller shape bucket (flow
      rescales to native pixels either way; only accuracy drops) ->
      L3 additionally shed low-priority requests at router admission —

    with a symmetric recovery ladder. Every (bucket, tier) pair is
    already AOT-resolved through the artifact index, so walking levels
    NEVER compiles anything (provable from the executable ledger).
    The controller is the autoscaler's fast twin: it watches the same
    live shed/occupancy/SLO-burn signals, but degrades within ~a
    second where the autoscaler takes tens of seconds to add capacity
    — degrade instantly, scale up slowly, recover when the new
    capacity actually lands (occupancy falls back under the recovery
    threshold)."""

    # master switch: off keeps the serve/fleet path byte-identical to
    # the pre-brownout stack (no controller thread, level pinned 0)
    enabled: bool = False
    # control-loop cadence — deliberately faster than
    # fleet.autoscale_period_s: degradation is the instant response,
    # capacity the slow one
    period_s: float = 0.25
    # escalate one level only after pressure (new shed/unavailable
    # rejections, occupancy >= up_occupancy, or SLO burn >=
    # up_slo_burn) persists this long
    escalate_after_s: float = 0.5
    # recover one level only after calm (zero new rejections AND
    # occupancy <= down_occupancy AND burn < up_slo_burn) persists
    # this long — much longer than the escalate window: degrading too
    # late sheds work, recovering too early flaps quality
    recover_after_s: float = 3.0
    # no second escalation within this window of the previous one (a
    # burst must not slam L0 -> L3 before L1's relief is even visible)
    escalate_cooldown_s: float = 0.5
    # no recovery within this window of ANY level transition
    recover_cooldown_s: float = 2.0
    # pool occupancy (router in-flight / (ready * fleet.max_in_flight))
    # at or above which a tick counts as pressure — the queue-depth
    # face of the verdict (router in-flight IS the fleet-wide queue)
    up_occupancy: float = 0.85
    # occupancy at or below which a tick can count as calm; the gap to
    # up_occupancy is the hysteresis band where the level holds
    down_occupancy: float = 0.5
    # SLO error-budget burn fraction (obs.slo_latency_ms must be set
    # for the signal to exist) at or above which a tick is pressure
    up_slo_burn: float = 0.7
    # highest level the controller may reach (3 = full ladder; 2 keeps
    # low-priority traffic admitted however hot the fleet runs)
    max_level: int = 3
    # `tail` exits 10 (distinct from rc 3-9) when the fleet has sat at
    # L3 continuously for at least this long — brownout as a steady
    # state means capacity never arrived
    l3_sustained_s: float = 30.0


@dataclass(frozen=True)
class SessionConfig:
    """Streaming video sessions (serve/session.py, DESIGN.md "Streaming
    sessions"): a bounded per-session cache of the last frame's decoded +
    bucket-preprocessed tensor, so `POST /v1/flow/stream` with ONE new
    frame forms the (prev, next) pair server-side — one decode and one
    preprocess per frame instead of two for a client walking a video.
    Sessions end explicitly (DELETE), by idle TTL (the sweeper), or by
    LRU pressure; every eviction is a structured `session_expired` error
    on the session's next use, never a silent drop."""

    # LRU bound on concurrently kept sessions per engine (each holds one
    # bucket-resolution float32 frame: ~H*W*12 bytes). The oldest-used
    # session past the bound is evicted with a tombstone.
    max_sessions: int = 256
    # idle TTL: a session untouched this long is expired by the sweeper
    # (and exactly on access, whichever comes first). <= 0 disables TTL
    # (sessions live until DELETE or LRU pressure).
    ttl_s: float = 120.0
    # sweeper-thread cadence; <= 0 disables the background sweep (TTL is
    # then enforced only lazily on access)
    sweep_s: float = 5.0
    # Temporal warm-start (DESIGN.md "Temporal warm-start"): keep frame
    # t's predicted flow at bucket resolution in the session and dispatch
    # step (t, t+1) through a refinement-only executable (FlowNetCS-style
    # S stage on [img1, img2, warp(img2, prior), prior, brightness_err])
    # instead of the full cold network. Adds a third executable axis —
    # (bucket, tier, cold|warm) — to the engine and `warmup --serve`.
    # Default OFF until the serve_bench --stream `epe_vs_cold` quality
    # gate passes for the deployed weights; a session's first step (and
    # any step after a re-prime/rebucket, which DROP the cached flow)
    # falls back to the cold path.
    warm_start: bool = False
    # Width multiplier of the standalone warm refinement stage relative
    # to the serving model's width (models without a trained refinement
    # stage get a deterministic seeded FlowNetRefine at width_mult *
    # warm_width; flownet_cs reuses its checkpoint's full-width refine
    # stage and ignores this). < 1 is what makes the warm path cheaper
    # than the cold network.
    warm_width: float = 0.5


@dataclass(frozen=True)
class ServeConfig:
    """Inference serving subsystem (deepof_tpu/serve/, DESIGN.md
    "Serving"): the dynamic micro-batching engine, the shape-bucket
    ladder, and the zero-dependency HTTP/offline frontends."""

    # Dynamic micro-batcher: pending requests coalesce into one batched
    # forward of up to max_batch pairs; a partial batch flushes when the
    # OLDEST pending request has waited batch_timeout_ms (latency bound).
    # Every dispatch is padded to exactly max_batch rows, so each bucket
    # owns ONE executable (no per-occupancy recompiles) and a response is
    # bit-identical whatever batch it rode in.
    max_batch: int = 8
    batch_timeout_ms: float = 10.0
    # Shape-bucket resolution ladder, (H, W) network-input sizes (model
    # stride constraints apply — multiples of 64, like data.image_size).
    # Arbitrary native inputs map to the smallest covering bucket (else
    # the largest) and flow vectors rescale back to native pixel units,
    # so the set of compiled executables is fixed and warmable
    # (`warmup --serve`). () = one bucket at data.image_size.
    buckets: tuple[tuple[int, int], ...] = ()
    # Mixed-precision serving tiers (serve/quant.py): which weight
    # precisions this endpoint offers. Each (bucket, tier) pair owns one
    # AOT executable — "f32" (checkpoint-native), "bf16" (weights cast,
    # half the weight bytes per dispatch), "int8" (weight-only
    # per-output-channel quantized conv kernels, dequantized inside the
    # forward; biases/norm params stay f32). A request's `precision`
    # field (HTTP body / predict_pairs arg) picks its tier; the FIRST
    # entry here is the default when a request names none. `warmup
    # --serve` pre-compiles the full bucket x tier ladder.
    precisions: tuple[str, ...] = ("f32",)
    # Request-queue bound: submit() blocks when this many requests are
    # pending (backpressure instead of unbounded host memory). 0 = unbounded.
    queue_depth: int = 256
    # HTTP frontend (`deepof_tpu serve`): stdlib http.server, JSON/PNG/.flo
    # responses, /healthz for the serve counters.
    host: str = "127.0.0.1"
    port: int = 8191
    # Per-request wall-clock bound the HTTP handler waits on a future
    # before answering 504 (the engine keeps working; the slot is freed).
    request_timeout_s: float = 30.0
    # Offline mode (`deepof_tpu serve --input ... --out ...`): decode
    # workers for the data/pipeline.py pool that feeds the engine.
    # 0 = decode inline on the submit thread.
    workers: int = 0
    # Testing/bench executor: when set, the engine replaces the model
    # with the deterministic fake timed executor (sleeps this many ms
    # per dispatch, flow = channel difference) — no checkpoint, no jax.
    # This is how fleet tests and `serve_bench --fleet` run replica
    # subprocesses cheaply; None = the real restored model.
    fake_exec_ms: float | None = None
    # Executable artifact store (serve/artifacts.py, DESIGN.md
    # "Artifact plane"): directory of fingerprint-keyed serialized AOT
    # executables. `warmup --serve` publishes into it (single writer);
    # engine/replica startup fetches+deserializes instead of compiling,
    # keyed by the StableHLO fingerprint of the LOCAL lowering so
    # drifted code can never load a stale artifact. "" = disabled
    # (every process compiles, the pre-r16 behavior). The path rides
    # the parent->replica config.json handoff, so fleet children and
    # autoscale spawns boot from the same store.
    artifacts_dir: str = ""
    # Trace-free boot through the store's executable index (index.json):
    # the engine resolves each lattice executable by its jax-free
    # resolution key — (exec name, config digest, aval signature,
    # backend, jax version) — with ZERO trace/lower calls; any index
    # miss/reject falls back to the fingerprint-then-compile path.
    # False = ignore the index (the r16 fingerprint-keyed boot, which
    # still pays one trace+lower per executable; serve_bench's A/B leg
    # uses this to measure the index's win). No effect when
    # artifacts_dir is empty.
    artifacts_index: bool = True
    # Deferred deep-verify plane: after an index-resolved executable
    # starts serving, a background verifier re-lowers it and compares
    # StableHLO fingerprints; on mismatch the executable is loudly
    # demoted (exec_deep_verify_demoted counter + warn record) and a
    # freshly compiled one is swapped in. False = trust the index +
    # crc gates alone (offline audits remain available via
    # `deepof_tpu artifacts verify --deep`).
    artifacts_deep_verify: bool = True
    # Deep-verify pacing: the background verifier re-lowers ONE queued
    # entry per tick of this interval instead of burning through the
    # whole lattice in a tight loop — a hundred-entry lattice must not
    # monopolize a core right after boot. 0 = no stagger (drain as
    # fast as the re-lowers run).
    deep_verify_interval_s: float = 0.05
    # Streaming video sessions (serve/session.py): POST /v1/flow/stream
    # keeps the last frame per session so consecutive pairs cost one
    # decode, not two; the router pins each session to one replica.
    session: SessionConfig = field(default_factory=SessionConfig)
    # Self-healing replica fleet (serve/fleet.py); replicas=0 keeps the
    # single-process serve path.
    fleet: FleetConfig = field(default_factory=FleetConfig)
    # Brownout control plane (serve/degrade.py): deadline-aware
    # admission + priority shedding + recompile-free quality
    # degradation under overload.
    degrade: DegradeConfig = field(default_factory=DegradeConfig)


@dataclass(frozen=True)
class ElasticConfig:
    """Elastic multi-host training (train/elastic.py, DESIGN.md "Elastic
    training"): a stdlib coordinator supervises N single-host trainer
    subprocesses and survives host loss/preemption without operator
    action. On a lost or wedged host the coordinator bumps the
    **generation**: survivors are stopped at a clean barrier (SIGTERM ->
    verified checkpoint + exit 0), the world re-forms on the survivors
    (new host count, per-host data streams re-sharded with the
    generation folded in as a salt), and every survivor respawns from
    the newest VALID checkpoint in the shared directory. Lost work is
    bounded by the checkpoint cadence.

    Two roles share this config: the COORDINATOR (`train --elastic N`;
    ``hosts`` > 1 and ``host_index`` < 0) and the per-host TRAINER
    children it spawns (``host_index`` >= 0; the coordinator serializes
    each child's exact config — world size, generation, shared ckpt
    dir — to <log_dir>/host-<i>/config.json)."""

    # coordinator world size; 0/1 = plain single-process training (the
    # `train --elastic N` CLI flag overrides this)
    hosts: int = 0
    # abort instead of re-forming below this many surviving hosts
    min_hosts: int = 1
    # --- per-child identity (written by the coordinator; -1/-0 defaults
    # mean "not an elastic child") ---
    host_index: int = -1
    num_hosts: int = 0  # current generation's world size
    generation: int = 0
    # the host that owns checkpoint WRITES this generation (the lowest
    # surviving host index); every host restores from the shared dir
    primary_host: int = 0
    # absolute global step the run trains to (elastic runs need an
    # absolute target so a respawned trainer stops where the run ends,
    # not `max_steps` further); `train --elastic N --max-steps T` sets it
    target_step: int = 0
    # shared verified-checkpoint directory ("" = <log_dir>/ckpt); the
    # primary writes it, every trainer restores from it on (re)spawn
    ckpt_dir: str = ""
    # step-skew limiter: a host pauses (heartbeat-touched, so it never
    # reads as a stall) while it is more than this many steps ahead of
    # the slowest live host (the coordinator publishes the world floor
    # to `world_file` each poll). Real synchronous data-parallel is
    # lockstepped by its collectives; virtual hosts are independent
    # processes, and unbounded skew would void the elastic guarantee
    # that lost work <= the checkpoint cadence (the furthest host's
    # uncommitted tail is what a re-form discards). The floor advances
    # at heartbeat/poll granularity, so size this to AT LEAST the steps
    # one obs.heartbeat_period_s covers or the limiter throttles
    # healthy leaders; 0 disables.
    sync_ahead: int = 4
    # path of the coordinator's world-floor file (written by the
    # coordinator into each child's config; "" = pacing off)
    world_file: str = ""
    # force this many virtual CPU devices per trainer child
    # (core/hostmesh.py) — the whole pool is testable on one host; 0 =
    # use the real backend's devices (an actual per-host accelerator)
    virtual_devices: int = 1
    # --- coordinator supervision knobs (fleet.py lineage) ---
    poll_s: float = 0.5
    # a trainer heartbeat.json older than this is a lost host (heartbeat
    # rewrites every obs.heartbeat_period_s; size to several periods)
    stale_after_s: float = 15.0
    # content-stall verdict: a host whose heartbeat shows >= 1 completed
    # step but no step/touch activity for this long is wedged (its OWN
    # watchdog needs obs.watchdog_min_s — default 60 s — and 3 beats to
    # arm; the coordinator judges earlier). Gated on beats >= 1 so the
    # first-dispatch XLA compile is never judged. 0 disables.
    wedge_after_s: float = 45.0
    # how long a spawned trainer may take to write its first heartbeat
    # (model build + restore + first allocations) before the spawn is
    # declared failed and the world re-forms without it
    spawn_timeout_s: float = 300.0
    # barrier: how long survivors get to save + exit 0 after SIGTERM
    # before SIGKILL escalation (must cover one checkpoint write)
    barrier_timeout_s: float = 120.0
    term_grace_s: float = 10.0
    # give up re-forming after this many generations (a fault that
    # keeps killing hosts is a defect to surface, not to retry forever)
    max_reforms: int = 16


@dataclass(frozen=True)
class ResilienceConfig:
    """Fault-tolerance layer (deepof_tpu/resilience/, DESIGN.md
    "Resilience"): the self-healing data path, verified checkpoints, the
    graduated divergence-recovery ladder, and the deterministic fault
    injector that chaos-tests all of them."""

    # --- self-healing data path (resilience/healing.py) ---
    # bounded retries (exponential backoff) per sample draw before the
    # draw is quarantined and replaced by a deterministic substitute
    # from the same derive_batch_rng stream (salted by the round)
    data_retries: int = 2
    data_backoff_s: float = 0.05
    # quarantine-and-redraw rounds before giving up (every redraw
    # failing means the data path is down, not one bad sample)
    data_substitutes: int = 3
    # re-attempts of a failed batch assembly on a pipeline worker
    # (make_batch is index-pure, so a retry is bit-identical)
    pipeline_retries: int = 1
    # re-attempts of a failed device->host metric value fetch
    fetch_retries: int = 2
    # --- graduated divergence recovery (train/step.py + loop.py) ---
    # rung 1: non-finite grads detected INSIDE the jitted step, before
    # the update — the update is skipped in place (state unchanged,
    # `skipped_updates` counter) instead of poisoning the params
    skip_nonfinite: bool = True
    # rung 2: escalate to the checkpoint rollback only after this many
    # consecutively observed skipped updates (rung 3 — abort — stays the
    # existing 3-failed-rollbacks ladder)
    max_consecutive_skips: int = 5
    # --- verified checkpoints (train/checkpoint.py) ---
    # validate manifests (file inventory + checksums) on restore and
    # fall back to the newest checkpoint that verifies
    verify_checkpoints: bool = True
    # --- deterministic fault injection (resilience/faults.py) ---
    # disabled by default (and then never constructed: zero overhead);
    # e.g. --set resilience.faults.enabled=true
    #      --set resilience.faults.decode_p=0.05
    faults: FaultConfig = field(default_factory=FaultConfig)


@dataclass(frozen=True)
class MixtureMemberConfig:
    """One weighted member of a stage's dataset mixture (data/mixture.py).

    Empty/zero fields inherit the stage-resolved DataConfig, so a member
    usually names only its dataset and weight. All members of a stage
    must agree on per-sample structure (shape, dtype, implied
    time_step) — validated loudly at build time, naming the stage."""

    dataset: str = "synthetic"  # flyingchairs | sintel | ucf101 | synthetic
    weight: float = 1.0
    data_path: str = ""  # "" = the stage's data.data_path
    sintel_pass: str = ""  # "" = the stage's data.sintel_pass
    time_step: int = 0  # 0 = the stage's data.time_step


@dataclass(frozen=True)
class StageConfig:
    """One stage of a training recipe (train/recipe.py): a weighted
    dataset mixture plus per-stage overrides of the base config and an
    advance condition. Sentinel values (None / 0 / empty) inherit the
    base ExperimentConfig, so a stage names only what it changes."""

    name: str = "stage"
    # weighted dataset mixture; () = the base config's single dataset
    mixture: tuple[MixtureMemberConfig, ...] = ()
    # --- per-stage config overrides (sentinels inherit the base) ---
    image_size: tuple[int, int] | None = None
    gt_size: tuple[int, int] | None = None
    crop_size: tuple[int, int] | None = None
    time_step: int = 0
    batch_size: int = 0
    model: str = ""  # e.g. the UCF-101 action stage swaps in st_single
    loss_weights: tuple[float, ...] = ()
    learning_rate: float = 0.0  # this stage's lr-schedule segment base
    # --- advance condition ---
    # "steps": advance after exactly `steps` optimizer steps.
    # "plateau": advance when the stage's eval-AEE trend (analyze.py
    #   eval_trend over this stage's evals) has flattened — slope >=
    #   -plateau_slope AEE per 1000 steps over plateau_window evals —
    #   with `steps` (when > 0) as a hard step budget backstop.
    advance: str = "steps"
    steps: int = 0  # 0 = unbounded (terminal stage / plateau-only)
    plateau_window: int = 8
    plateau_slope: float = 0.01  # flat when slope >= -this (AEE/kstep)
    min_evals: int = 3  # plateau needs at least this many stage evals


@dataclass(frozen=True)
class RecipeConfig:
    """Staged training recipe (train/recipe.py, DESIGN.md "Recipe
    engine"): an ordered list of stages, each with a deterministic
    weighted dataset mixture, per-stage shape/time_step/loss/lr
    overrides, and a fixed-step or EPE-plateau advance condition. The
    active stage index rides the checkpoint manifest so resume — plain
    or post-reform — lands in the correct stage; `warmup` pre-compiles
    every stage's executable set so a stage switch is a zero-recompile
    event provable from the executable ledger."""

    enabled: bool = False
    stages: tuple[StageConfig, ...] = ()
    # AOT pre-compile every stage's (train, eval) executables at recipe
    # start (train/recipe.py precompile_stages) so stage boundaries
    # compile nothing mid-run. False = compile lazily per stage.
    warmup: bool = True
    # eval cadence driving the plateau trigger rides the per-stage
    # train.eval_every; this caps how many stage evals the trigger
    # retains (bounded memory on very long stages)
    max_trigger_evals: int = 512


@dataclass(frozen=True)
class ExperimentConfig:
    name: str = "flyingchairs_flownet_s"
    # any models/registry.py name: flownet_s | vgg16 | inception_v3 |
    # flownet_c | flownet_cs | st_single | st_baseline | ucf101_spatial
    model: str = "flownet_s"
    # Thin-variant channel multiplier — honored by models declaring a
    # width_mult field (flownet_s, flownet_c; the parity backbones keep
    # their exact reference widths and build_model rejects non-default
    # values for them by name). 1.0 = reference widths; the test suite
    # uses 0.25 so full-train-step wiring checks don't pay 38M-param
    # compute on the CPU mesh.
    width_mult: float = 1.0
    # FlowNet-C/CS correlation cost-volume geometry. The displacement
    # bins live on the 1/8-resolution conv3 grid: bin granularity =
    # 8 * corr_stride image pixels, search radius ~ 8 * max_disp image
    # pixels. Size them to the expected flow at that grid — a task whose
    # displacements fit inside ONE bin is architecturally invisible to
    # the correlation (DESIGN.md r04: for 8 px flows at 64 px images the
    # working setting was corr_stride=1, corr_max_disp=3; the defaults
    # match the FlowNet paper's 320x448 large-displacement regime).
    corr_max_disp: int = 20
    corr_stride: int = 2
    loss: LossConfig = field(default_factory=LossConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    data: DataConfig = field(default_factory=DataConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    elastic: ElasticConfig = field(default_factory=ElasticConfig)
    recipe: RecipeConfig = field(default_factory=RecipeConfig)

    def replace(self, **kw: Any) -> "ExperimentConfig":
        return dataclasses.replace(self, **kw)


# --- Presets: reference hyper-parameter baselines (BASELINE.md) ---

FLYINGCHAIRS = ExperimentConfig(
    name="flyingchairs_inception",
    model="inception_v3",
    loss=LossConfig(epsilon=1e-4, alpha_c=0.25, alpha_s=0.37, lambda_smooth=1.0,
                    weights=(16, 8, 4, 2, 1, 1)),
    optim=OptimConfig(learning_rate=1.6e-5, epochs_per_decay=18),
    # network input 320x448 (`deepOF.py:22`), GT kept native 384x512
    # (`flyingChairsLoader.py:74-81`); eval resizes pr1*2 back to gt_size.
    data=DataConfig(dataset="flyingchairs", image_size=(320, 448),
                    gt_size=(384, 512), batch_size=4),
    train=TrainConfig(num_epochs=110, ckpt_every_epochs=18,
                      eval_amplifier=2.0, eval_clip=(-300.0, 250.0)),
)

FLYINGCHAIRS_VGG = ExperimentConfig(
    name="flyingchairs_vgg",
    model="vgg16",
    loss=LossConfig(epsilon=1e-4, alpha_c=0.25, alpha_s=0.37, lambda_smooth=1.0,
                    weights=(16, 8, 4, 2, 1), smoothness="depthwise"),
    optim=OptimConfig(learning_rate=1.6e-5, epochs_per_decay=18),
    data=DataConfig(dataset="flyingchairs", image_size=(320, 448),
                    gt_size=(384, 512), batch_size=8,
                    augment_geo=True, augment_photo=True),
    # pr1 is half the final flow: x2 before clip (`flyingChairsTrain_vgg.py:291-292`)
    train=TrainConfig(num_epochs=110, eval_amplifier=2.0,
                      eval_clip=(-204.4790, 201.3478)),
)

SINTEL = ExperimentConfig(
    name="sintel_inception_multiframe",
    model="inception_v3",
    loss=LossConfig(epsilon=1e-4, alpha_c=0.3, alpha_s=0.3, lambda_smooth=0.0,
                    weights=(16, 8, 4, 4, 2, 1)),
    optim=OptimConfig(learning_rate=1.6e-5, epochs_per_decay=60),
    data=DataConfig(dataset="sintel", image_size=(256, 512), gt_size=(436, 1024),
                    crop_size=(224, 480), batch_size=4, time_step=10,
                    sintel_pass="final"),
    train=TrainConfig(num_epochs=400, ckpt_every_epochs=30, eval_amplifier=3.0,
                      eval_clip=(-420.621, 426.311)),
)

UCF101 = ExperimentConfig(
    name="ucf101_st_single",
    model="st_single",
    loss=LossConfig(epsilon=1e-4, alpha_c=0.25, alpha_s=0.37, lambda_smooth=0.8,
                    weights=(16, 8, 4, 2, 1)),
    optim=OptimConfig(learning_rate=1.6e-4, epochs_per_decay=50),
    # gen-2 entry trains 320x384 (`deepOF.py:19`), 1000 epochs (`ucf101train.py:50`)
    data=DataConfig(dataset="ucf101", image_size=(320, 384),
                    gt_size=(320, 384), batch_size=8),
    train=TrainConfig(num_epochs=1000, eval_amplifier=1.0, eval_clip=(-1e9, 1e9)),
)

# gen-1 per-model loss-weight alternates (`version1/trainOF.py:76-87`),
# selectable via LossConfig.weights overrides.
GEN1_LOSS_WEIGHTS = {
    "vgg16": (7.0, 5.0, 3.0, 3.0, 1.0),
    "flownet_s": (9.0, 7.0, 5.0, 3.0, 3.0, 1.0),
    "inception_v3": (9.0, 7.0, 5.0, 3.0, 3.0, 1.0),
}

PRESETS: dict[str, ExperimentConfig] = {
    "flyingchairs": FLYINGCHAIRS,
    "flyingchairs_vgg": FLYINGCHAIRS_VGG,
    "sintel": SINTEL,
    "ucf101": UCF101,
}


def get_config(name: str, **overrides: Any) -> ExperimentConfig:
    cfg = PRESETS[name]
    return cfg.replace(**overrides) if overrides else cfg


# --- JSON round-trip: the fleet's parent->replica config handoff ---


def _tupleize(value: Any) -> Any:
    """JSON arrays -> the tuples the frozen config tree uses (nested:
    serve.buckets round-trips as a tuple of tuples)."""
    if isinstance(value, list):
        return tuple(_tupleize(v) for v in value)
    return value


def _from_dict(cls: type, d: dict, path: str = "") -> Any:
    import typing

    unknown = set(d) - {f.name for f in dataclasses.fields(cls)}
    if unknown:
        where = path or cls.__name__
        raise ValueError(
            f"config_from_dict: unknown field(s) {sorted(unknown)} in "
            f"{where}")
    hints = typing.get_type_hints(cls)
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        if f.name not in d:
            continue  # absent fields keep their defaults (older dumps)
        value = d[f.name]
        hint = hints.get(f.name)
        where = f"{path}.{f.name}" if path else f.name
        if dataclasses.is_dataclass(hint) and isinstance(value, dict):
            value = _from_dict(hint, value, where)
        elif (typing.get_origin(hint) is tuple
              and typing.get_args(hint)
              and dataclasses.is_dataclass(typing.get_args(hint)[0])
              and isinstance(value, (list, tuple))):
            # tuple-of-dataclass fields (recipe.stages, stage.mixture):
            # each element recurses with an indexed path so unknown-key
            # rejection names the exact offending entry
            elem = typing.get_args(hint)[0]
            value = tuple(
                _from_dict(elem, v, f"{where}[{i}]")
                if isinstance(v, dict) else _tupleize(v)
                for i, v in enumerate(value))
        else:
            value = _tupleize(value)
        kwargs[f.name] = value
    return cls(**kwargs)


def config_from_dict(d: dict) -> ExperimentConfig:
    """Inverse of `dataclasses.asdict` + JSON for the config tree:
    rebuilds the nested frozen dataclasses and re-tuples JSON arrays.
    `serve/fleet.py` serializes the parent's exact config to each
    replica's `config.json` and the replica loads it via the CLI's
    `serve --config-json` — replicas must serve the same ladder and the
    same fault schedule as the supervisor intended, not a preset
    re-derivation. Unknown keys are rejected AT EVERY LEVEL (a typo'd
    field must not silently become its default); missing keys keep
    their defaults so older dumps load."""
    return _from_dict(ExperimentConfig, d)


def recipe_from_dict(d: dict) -> RecipeConfig:
    """Strict dict -> RecipeConfig for the `train --recipe FILE` payload
    (train/recipe.py): the same unknown-key rejection as
    `config_from_dict`, at every nesting level — a typo in
    `stages[i].mixture[j]` fails with the exact indexed path, never a
    silently-defaulted field."""
    return _from_dict(RecipeConfig, d, "recipe")
