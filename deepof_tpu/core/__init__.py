from .config import (  # noqa: F401
    LossConfig,
    OptimConfig,
    DataConfig,
    MeshConfig,
    TrainConfig,
    ExperimentConfig,
    FLYINGCHAIRS,
    FLYINGCHAIRS_VGG,
    SINTEL,
    UCF101,
    PRESETS,
    get_config,
)
