"""Deterministic, seeded fault injection — the chaos-test substrate.

Every recovery path in this repo (decode retry/quarantine, pipeline
worker retries, fetch retries, skip-in-place divergence handling,
checkpoint verification + fallback) is provable only if faults can be
produced on demand, reproducibly, at the exact site the recovery code
guards. `FaultInjector` does that from config alone:

  - **Sites** are string-keyed chokepoints: ``decode`` (per micro-batch
    sample assembly), ``assemble`` (per dispatch-batch build on a
    pipeline worker), ``dispatch`` (per global step; poisons the batch
    with a NaN instead of raising — the divergence-ladder substrate),
    ``fetch`` (per metric value fetch), ``ckpt_save`` / ``ckpt_restore``
    (per checkpoint step), and the post-commit tamper sites
    ``ckpt_truncate`` / ``ckpt_corrupt`` (filesystem-level checkpoint
    damage, exercising manifest verification).
  - **Scheduling** is per-site: an explicit index tuple (``decode_at``)
    and/or a probability (``decode_p``) hashed from (seed, site, index)
    — so whether index i faults is a pure function of the config, never
    of thread timing or worker count.
  - **Persistence** is attempt-counted: the injector counts how many
    times each (site, index) has been checked and stops faulting after
    ``fail_attempts`` — ``1`` models a transient error (the first retry
    succeeds), ``retries + 1`` exhausts the retry budget and forces the
    quarantine/substitute path, a large value is a permanently bad
    sample. The counter is keyed by (site, index), so the sequence of
    outcomes is identical for any ``num_workers``.

Zero overhead when disabled: `build_injector` returns ``None`` for a
disabled config and every call site guards with ``if inj is not None``.

Stdlib-only: importable from the jax-free CLI paths and from
`core/config.py` without a cycle.
"""

from __future__ import annotations

import os
import threading
import zlib
from dataclasses import dataclass


class InjectedFault(OSError):
    """An injector-raised IO-shaped failure. Subclasses OSError so every
    retry/degrade path treats it exactly like the real transient errors
    it stands in for."""


@dataclass(frozen=True)
class FaultConfig:
    """Config-driven injection schedule (see module docstring).

    ``*_p`` fields are per-index probabilities in [0, 1]; ``*_at``
    fields are explicit index tuples that always fault. Both may be set;
    either triggers. All scheduling is deterministic in (seed, site,
    index).
    """

    enabled: bool = False
    seed: int = 0
    # raising sites
    decode_p: float = 0.0
    decode_at: tuple[int, ...] = ()
    assemble_p: float = 0.0
    assemble_at: tuple[int, ...] = ()
    fetch_p: float = 0.0
    fetch_at: tuple[int, ...] = ()
    ckpt_save_at: tuple[int, ...] = ()
    ckpt_restore_at: tuple[int, ...] = ()
    # acting sites: dispatch poisons the batch (one NaN) at these steps;
    # tamper sites damage the COMMITTED checkpoint dir for these steps
    # (truncate = delete one manifested file, corrupt = flip one byte)
    dispatch_at: tuple[int, ...] = ()
    ckpt_truncate_at: tuple[int, ...] = ()
    ckpt_corrupt_at: tuple[int, ...] = ()
    # replica-level acting sites (serving fleet chaos, serve/server.py
    # install_replica_faults): the site index is the REPLICA index (the
    # fleet exports DEEPOF_TPU_REPLICA to each subprocess), and the
    # fault arms once that replica has completed `replica_fault_after`
    # responses — "mid-load" by construction. replica_crash = SIGKILL
    # the serving process (kill -9); replica_wedge = the next dispatch
    # blocks forever (a hung device call — exactly what the serve
    # heartbeat watchdog exists to flag). Each replica process builds a
    # fresh injector from config, so a respawned replica re-arms the
    # same schedule: a crash-looping replica is one `replica_crash_at`
    # entry with a small replica_fault_after.
    replica_crash_at: tuple[int, ...] = ()
    replica_wedge_at: tuple[int, ...] = ()
    # replica_degrade: every dispatch AFTER the arm point returns
    # deliberately corrupted flow (a large constant offset) — the
    # deterministic stand-in for silently damaged weights (bad quantized
    # tier, bit-rotted checkpoint). The replica keeps serving and stays
    # healthy on every latency/SLO axis; ONLY the label-free quality
    # proxies (obs/quality.py) can see it — exactly the blind spot the
    # quality drift verdict exists to close.
    replica_degrade_at: tuple[int, ...] = ()
    replica_fault_after: int = 8
    # host-level acting sites (elastic training chaos, train/elastic.py
    # maybe_host_fault): the site index is the TRAINER HOST index
    # (cfg.elastic.host_index — the coordinator writes it into each
    # trainer's config.json), and the fault arms once that host's
    # global step reaches `host_fault_step` — "mid-run" by
    # construction. host_loss = SIGKILL the trainer process (a
    # preempted/OOM-killed/vanished pod host); host_wedge = the main
    # loop blocks forever after a step (a hung device dispatch — the
    # coordinator's content-stall verdict exists for exactly this);
    # preempt_notice = SIGTERM self-delivery (the cloud's preemption
    # warning — the trainer's graceful handler saves a verified
    # checkpoint and exits 0, and the coordinator re-forms without
    # it). Each trainer incarnation rebuilds the injector from config;
    # a lost host is never respawned under the same index, so a host
    # site fires at most once per run.
    host_loss_at: tuple[int, ...] = ()
    host_wedge_at: tuple[int, ...] = ()
    preempt_notice_at: tuple[int, ...] = ()
    host_fault_step: int = 0
    # how many checks of one (site, index) fault before it recovers:
    # 1 = transient (first retry succeeds); data_retries + 1 = exhausts
    # the retry budget and forces quarantine + substitution; a large
    # value = permanently failing.
    fail_attempts: int = 1


_SITES = ("decode", "assemble", "fetch", "ckpt_save", "ckpt_restore",
          "dispatch", "ckpt_truncate", "ckpt_corrupt",
          "replica_crash", "replica_wedge", "replica_degrade",
          "host_loss", "host_wedge", "preempt_notice")


def _u01(seed: int, site: str, index: int) -> float:
    """Deterministic uniform draw in [0, 1) from (seed, site, index)."""
    h = zlib.crc32(f"{seed}:{site}:{index}".encode())
    return h / 2**32


class FaultInjector:
    """See module docstring. Thread-safe: pipeline workers, the prefetch
    thread, the fetch consumer, and the main loop all consult one
    injector."""

    def __init__(self, cfg: FaultConfig):
        self.cfg = cfg
        self._lock = threading.Lock()
        self._attempts: dict[tuple[str, int], int] = {}
        self._fired: set[tuple[str, int]] = set()
        self._counts: dict[str, int] = {s: 0 for s in _SITES}

    # -------------------------------------------------------- scheduling
    def scheduled(self, site: str, index: int) -> bool:
        """Pure query: does the config schedule a fault at (site, index)?"""
        c = self.cfg
        at = getattr(c, f"{site}_at", ())
        if isinstance(at, (int, float)):  # --set ...dispatch_at=9 (scalar)
            at = (at,)
        if int(index) in tuple(int(i) for i in at):
            return True
        p = float(getattr(c, f"{site}_p", 0.0) or 0.0)
        return p > 0.0 and _u01(c.seed, site, int(index)) < p

    # ----------------------------------------------------- raising sites
    def check(self, site: str, index: int) -> None:
        """Raise `InjectedFault` if (site, index) is scheduled and has
        not yet exhausted `fail_attempts` checks. Each call for a
        scheduled key counts as one attempt, so bounded-retry callers
        recover from transient schedules and exhaust persistent ones —
        identically for any worker interleaving."""
        if not self.scheduled(site, index):
            return
        key = (site, int(index))
        with self._lock:
            n = self._attempts.get(key, 0) + 1
            self._attempts[key] = n
            if n > max(self.cfg.fail_attempts, 1):
                return
            self._counts[site] += 1
        raise InjectedFault(
            f"injected {site} fault at index {index} (attempt {n})")

    # ------------------------------------------------------ acting sites
    def hit(self, site: str, index: int) -> bool:
        """Consume-once acting-site query (e.g. ``dispatch``): True the
        first time a scheduled (site, index) is asked about, False after
        — the caller performs the fault action itself."""
        if not self.scheduled(site, index):
            return False
        key = (site, int(index))
        with self._lock:
            if key in self._fired:
                return False
            self._fired.add(key)
            self._counts[site] += 1
        return True

    def tamper_checkpoint(self, step: int, path: str) -> list[str]:
        """Post-commit checkpoint damage for the verification chaos
        tests: ``ckpt_truncate_at`` deletes one file from the committed
        dir, ``ckpt_corrupt_at`` flips one byte of one file. File choice
        is deterministic (largest file, ties broken by path) so runs
        reproduce. Returns a description of each action taken."""
        actions: list[str] = []
        for site, act in (("ckpt_truncate", "truncate"),
                          ("ckpt_corrupt", "corrupt")):
            if not self.hit(site, step):
                continue
            target = self._pick_file(path)
            if target is None:
                continue
            if act == "truncate":
                os.remove(target)
            else:
                with open(target, "r+b") as f:
                    b = f.read(1)
                    f.seek(0)
                    f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
            actions.append(f"{act}d {os.path.relpath(target, path)} of "
                           f"checkpoint step {step}")
        return actions

    @staticmethod
    def _pick_file(path: str) -> str | None:
        best: tuple[int, str] | None = None
        for root, _, names in os.walk(path):
            for nm in sorted(names):
                p = os.path.join(root, nm)
                try:
                    size = os.path.getsize(p)
                except OSError:
                    continue
                # prefer the largest file (the data payload, not a tiny
                # metadata sidecar); deterministic tie-break on path
                if best is None or (size, p) > best:
                    best = (size, p)
        return best[1] if best else None

    # ----------------------------------------------------- observability
    def stats(self) -> dict[str, int]:
        """Injected-fault counts per site (snapshot)."""
        with self._lock:
            return dict(self._counts)


def build_injector(cfg: FaultConfig | None) -> FaultInjector | None:
    """None unless injection is enabled — the zero-overhead contract:
    disabled configs never construct an injector and hot sites skip on
    one `is not None`."""
    if cfg is None or not cfg.enabled:
        return None
    return FaultInjector(cfg)
