"""Fault-tolerance layer (L0 at import — stdlib only).

At production scale, corrupt samples, flaky storage, preemptions, and
torn checkpoint directories are routine events, not exceptions. This
package holds the machinery that turns each of them from a run-killer
into a counted, logged, recoverable event — and the deterministic fault
injector that *proves* every recovery path in CI instead of hoping.

  faults.py   seeded, config-driven fault injection (`FaultConfig`) at
              the four operational fault sites: image decode, batch
              assembly, device dispatch/fetch, checkpoint save/restore
              — plus post-commit checkpoint tampering (truncation, byte
              corruption) for the verified-checkpoint chaos tests. Zero
              overhead when disabled: `build_injector` returns None and
              every site guards with one `is not None` check.
  healing.py  self-healing sample assembly: bounded retries with
              exponential backoff, then quarantine + a deterministic
              substitute drawn from the same `derive_batch_rng` stream
              so batch shapes and the rng sequence survive any
              `num_workers`.
  verify.py   jax-free checkpoint manifests (pytree structure digest +
              per-file size/crc32 inventory) and their offline
              validation (`deepof_tpu verify-ckpt`).

The recovery ladder these pieces implement (cheap rungs first) is
documented in DESIGN.md "Resilience"; `train/loop.py`,
`train/checkpoint.py`, `data/pipeline.py`, and `train/metrics_log.py`
are the wired consumers.

Import discipline: this __init__, faults.py, and verify.py import only
the stdlib (+numpy in healing.py) so `cli.py verify-ckpt` and
`analyze.py` never initialize an accelerator backend.
"""

from .faults import FaultConfig, FaultInjector, InjectedFault, build_injector

__all__ = [
    "FaultConfig",
    "FaultInjector",
    "InjectedFault",
    "build_injector",
]
