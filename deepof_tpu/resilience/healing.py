"""Self-healing sample assembly: retry, then quarantine + substitute.

The data path's failure modes at scale are (a) transient — a flaky NFS
read, a storage hiccup, an injected `InjectedFault` — and (b) persistent
— a corrupt image, a truncated .flo. Today either kind kills the batch
and, through the input pipeline, the run. `HealingSampler` turns both
into bounded, counted events:

  transient   bounded retries with exponential backoff. The batch rng is
              RE-DERIVED per attempt (`make_rng(index, round)` is pure),
              so a retry reproduces the exact draw the fault interrupted
              — a run whose faults all recover on retry is bit-identical
              to a fault-free run at the same seed and `num_workers`
              (the chaos acceptance pin).
  persistent  after the retry budget, the draw is QUARANTINED (counted,
              logged with the failing sample's identity, listed in the
              run summary) and replaced by a deterministic substitute:
              the batch is re-drawn from `make_rng(index, round)` with
              the next round number — the same `derive_batch_rng` stream
              salted by the substitution round — so the replacement
              depends only on (stream seed, batch index, round), never
              on which worker hit the fault or when. Batch shapes and
              the rng sequence of every OTHER batch index are untouched.

Runs inside the input-pipeline workers (the sampler is called from
`make_batch(i)`), so healing parallelizes with assembly and a slow
retry on one index never blocks other workers.
"""

from __future__ import annotations

import time
from typing import Callable

#: Exception types worth retrying: real IO/decode errors (cv2, native
#: batch IO, filesystem) and injected faults arrive as OSError or
#: RuntimeError, and CORRUPT payloads as ValueError (io/flo.py raises it
#: for truncated/garbled .flo data — exactly the persistent per-sample
#: failure the quarantine path exists for; a code-bug ValueError in the
#: sample path costs a bounded retry ladder and then surfaces inside
#: QuarantineError with the original message). Other programming errors
#: (KeyError, TypeError, ...) surface immediately.
RETRYABLE = (OSError, RuntimeError, ValueError)


def retry_bounded(fn, retries: int = 0, backoff_s: float = 0.0,
                  on_retry: Callable[[], None] | None = None,
                  exc_types: tuple = RETRYABLE,
                  sleep: Callable[[float], None] = time.sleep):
    """THE retry ladder, shared by every resilience rung (sample draws
    here, pipeline-worker assembly, metric fetches): up to `retries`
    re-attempts of `fn()` on `exc_types`, exponential backoff starting
    at `backoff_s`, `on_retry` called once per re-attempt (counters).
    One implementation so retry semantics (backoff shape, retryable set)
    can never silently diverge between sites."""
    delay = max(float(backoff_s), 0.0)
    retries = max(int(retries), 0)
    for attempt in range(retries + 1):
        try:
            return fn()
        except exc_types:
            if attempt >= retries:
                raise
            if on_retry is not None:
                on_retry()
            if delay > 0:
                sleep(delay)
                delay *= 2
    raise AssertionError("unreachable")  # loop always returns/raises


class QuarantineError(Exception):
    """Raised when even the substitution rounds are exhausted — every
    redraw kept failing, which means the data path itself is down (not
    one bad sample); the run cannot make progress. Deliberately NOT an
    OSError/RuntimeError: this is the ladder's terminal verdict, and the
    outer retry layers (pipeline workers, fetchers) must surface it, not
    re-run the whole exhausted ladder."""


class HealingSampler:
    """Per-batch-index self-healing wrapper around sample assembly.

    make_rng: (index, round) -> rng. Pure; round 0 is the canonical
        stream (`derive_batch_rng(seed, index)`), rounds >= 1 are the
        substitute streams (`salt=round`).
    sample: (index, rng) -> batch dict. May raise RETRYABLE.
    retries: extra attempts per round after the first (bounded).
    backoff_s: initial sleep before a retry; doubles per retry.
    substitutes: quarantine-and-redraw rounds after round 0 fails.
    injector: optional FaultInjector; consulted at the ``decode`` site
        once per attempt (inside the retry loop, so injected faults
        exercise exactly the real-fault recovery path).
    log: optional str sink (warn records in metrics.jsonl).
    """

    def __init__(self, make_rng: Callable, sample: Callable,
                 retries: int = 2, backoff_s: float = 0.05,
                 substitutes: int = 3, injector=None,
                 log: Callable[[str], None] | None = None,
                 sleep: Callable[[float], None] = time.sleep):
        self._make_rng = make_rng
        self._sample = sample
        self._retries = max(int(retries), 0)
        self._backoff = max(float(backoff_s), 0.0)
        self._substitutes = max(int(substitutes), 0)
        self._inj = injector
        self._log = log
        self._sleep = sleep
        # GIL-atomic int updates (workers call concurrently); quarantine
        # list appends are likewise single C-level ops
        self._sample_retries = 0
        self._quarantined = 0
        self._substituted = 0
        self.quarantine_log: list[dict] = []

    def _draw(self, index: int, rnd: int) -> dict:
        """One attempt: injector's decode site, then the real draw —
        inside the retry ladder, so injected faults exercise exactly the
        real-fault recovery path."""
        if self._inj is not None:
            self._inj.check("decode", index)
        return self._sample(index, self._make_rng(index, rnd))

    def _count_retry(self) -> None:
        self._sample_retries += 1  # GIL-atomic (workers call concurrently)

    def __call__(self, index: int) -> dict:
        last: BaseException | None = None
        for rnd in range(self._substitutes + 1):
            try:
                batch = retry_bounded(
                    lambda: self._draw(index, rnd),
                    retries=self._retries, backoff_s=self._backoff,
                    on_retry=self._count_retry, sleep=self._sleep)
            except RETRYABLE as e:
                # this round's retry budget is spent: quarantine the draw
                # and fall through to a substitute redraw (next round's rng)
                last = e
                self._quarantined += 1
                ev = {"index": int(index), "round": rnd,
                      "attempts": self._retries + 1,
                      "error": f"{type(e).__name__}: {e}"}
                self.quarantine_log.append(ev)
                if self._log is not None:
                    self._log(
                        f"quarantined sample draw for batch index {index} "
                        f"(round {rnd}, {self._retries + 1} attempts: "
                        f"{ev['error']}); substituting a deterministic "
                        "redraw")
                continue
            if rnd > 0:
                self._substituted += 1
            return batch
        raise QuarantineError(
            f"batch index {index}: all {self._substitutes} substitute "
            f"redraws failed after quarantine (last: "
            f"{type(last).__name__}: {last}) — the data path is down, "
            "not one bad sample") from last

    def stats(self) -> dict[str, int]:
        """Log/summary-ready counters: retries burned, draws quarantined,
        substitutes delivered."""
        return {"sample_retries": self._sample_retries,
                "quarantined": self._quarantined,
                "substituted": self._substituted}
