"""Checkpoint manifests + offline verification (jax-free).

A checkpoint that cannot be proven intact is a liability: a truncated
orbax directory or a bit-flipped array file restores into garbage (or
crashes mid-restore) exactly when a run most needs its rollback target.
`CheckpointManager` therefore writes one manifest per committed
checkpoint, and every restore path — plus the offline
``deepof_tpu verify-ckpt`` verb — validates against it.

Manifest format (``step_XXXXXXXXXX.manifest.json``, a SIBLING of the
orbax step directory so the checkpoint payload itself stays untouched):

    {
      "version": 1,
      "step": 120,
      "time": 1722580000.0,
      "files": {"<relpath>": {"size": 1234, "crc32": 305419896}, ...},
      "content_crc32": 123456,          # crc over the sorted file table
      "structure": {"num_leaves": 42, "crc32": 987654},  # pytree digest
      "config_digest": "a1b2c3d4"       # crc of the experiment config
    }

``files`` inventories every file under the committed directory with its
size and crc32 — verification is a filesystem walk + checksum, no jax,
no orbax, so the CLI verb can run against a live run's log dir from any
machine. ``structure`` digests the TrainState pytree (leaf paths +
shapes + dtypes, computed by the writer which does hold jax) so a
same-files-different-tree restore mismatch is also detectable.
``config_digest`` ties the checkpoint to the config that produced it
(advisory: restore warns on mismatch but proceeds — fine-tune handoffs
legitimately cross configs).

All writes are atomic (tmp + rename): a reader never sees a torn
manifest, and a manifest's absence (legacy checkpoint, or a crash
between commit and manifest flush) is reported as "unverified", never
as corruption.
"""

from __future__ import annotations

import json
import os
import time
import zlib

MANIFEST_VERSION = 1
MANIFEST_SUFFIX = ".manifest.json"


def manifest_path(ckpt_path: str) -> str:
    """Sibling manifest file for a checkpoint step directory."""
    return ckpt_path.rstrip("/\\") + MANIFEST_SUFFIX


def file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


def config_digest(cfg_dict) -> str:
    """Stable 8-hex-digit digest of a JSON-able config dict."""
    blob = json.dumps(cfg_dict, sort_keys=True, default=str).encode()
    return f"{zlib.crc32(blob):08x}"


def build_manifest(ckpt_path: str, step: int,
                   structure: dict | None = None,
                   cfg_digest: str | None = None,
                   extra: dict | None = None) -> dict:
    """Inventory the COMMITTED checkpoint directory (call only after the
    write has fully committed — for async saves that is after
    `wait_until_finished`). ``extra`` is an optional jsonable block the
    writer rides along (e.g. the recipe engine's active stage index so
    resume lands in the correct stage); it is carried verbatim and never
    participates in verification."""
    files: dict[str, dict] = {}
    for root, _, names in os.walk(ckpt_path):
        for nm in sorted(names):
            p = os.path.join(root, nm)
            rel = os.path.relpath(p, ckpt_path).replace(os.sep, "/")
            files[rel] = {"size": os.path.getsize(p), "crc32": file_crc32(p)}
    content = 0
    for rel in sorted(files):
        content = zlib.crc32(
            f"{rel}:{files[rel]['size']}:{files[rel]['crc32']};".encode(),
            content)
    manifest = {
        "version": MANIFEST_VERSION,
        "step": int(step),
        "time": time.time(),
        "files": files,
        "content_crc32": content,
        "structure": structure,
        "config_digest": cfg_digest,
    }
    if extra:
        manifest["extra"] = dict(extra)
    return manifest


def write_manifest(ckpt_path: str, manifest: dict) -> str:
    path = manifest_path(ckpt_path)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, path)  # readers never see a torn manifest
    return path


def load_manifest(path: str) -> dict | None:
    """The manifest dict, or None when absent/unreadable/torn (an
    unreadable manifest reports as unverified, not as corruption — the
    checkpoint payload itself may be fine)."""
    try:
        with open(path) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    return m if isinstance(m, dict) and "files" in m else None


def verify_files(ckpt_path: str, manifest: dict) -> list[str]:
    """Validate the checkpoint directory against its manifest. Returns a
    list of problems (empty = intact). Checks: directory present, every
    manifested file present with matching size and crc32. Extra files
    are tolerated (orbax layouts vary across versions/hosts; additions
    cannot corrupt the inventoried payload)."""
    problems: list[str] = []
    if not os.path.isdir(ckpt_path):
        return [f"checkpoint directory missing: {ckpt_path}"]
    for rel, spec in sorted(manifest.get("files", {}).items()):
        p = os.path.join(ckpt_path, *rel.split("/"))
        if not os.path.isfile(p):
            problems.append(f"missing file: {rel}")
            continue
        size = os.path.getsize(p)
        if size != spec.get("size"):
            problems.append(
                f"size mismatch: {rel} ({size} != {spec.get('size')})")
            continue
        crc = file_crc32(p)
        if crc != spec.get("crc32"):
            problems.append(
                f"checksum mismatch: {rel} (crc32 {crc} != {spec.get('crc32')})")
    return problems


def _step_dirs(ckpt_dir: str) -> list[tuple[int, str]]:
    import re

    out = []
    if not os.path.isdir(ckpt_dir):
        return out
    for name in os.listdir(ckpt_dir):
        m = re.match(r"step_(\d+)$", name)
        p = os.path.join(ckpt_dir, name)
        if m and os.path.isdir(p):
            out.append((int(m.group(1)), p))
    return sorted(out)


def verify_run(path: str) -> dict:
    """Validate every checkpoint of a run (``deepof_tpu verify-ckpt``).

    `path` may be a run's ``--log-dir`` (the ``ckpt/`` subdirectory is
    used) or a checkpoint directory itself. Returns a jsonable report:
    per-checkpoint status (``ok`` / ``corrupt`` / ``unverified``), the
    problem list for corrupt ones, and the valid/corrupt/unverified step
    partitions. ``ok`` is False iff any manifested checkpoint fails its
    manifest."""
    sub = os.path.join(path, "ckpt")
    ckpt_dir = sub if os.path.isdir(sub) else path
    checkpoints = []
    valid, corrupt, unverified = [], [], []
    for step, p in _step_dirs(ckpt_dir):
        manifest = load_manifest(manifest_path(p))
        if manifest is None:
            status, problems = "unverified", ["no manifest"]
            unverified.append(step)
        else:
            problems = verify_files(p, manifest)
            if problems:
                status = "corrupt"
                corrupt.append(step)
            else:
                status, problems = "ok", []
                valid.append(step)
        checkpoints.append({"step": step, "path": p, "status": status,
                            "problems": problems})
    return {
        "dir": os.path.abspath(ckpt_dir),
        "checkpoints": checkpoints,
        "valid_steps": valid,
        "corrupt_steps": corrupt,
        "unverified_steps": unverified,
        "ok": not corrupt,
    }
