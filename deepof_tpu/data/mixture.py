"""Deterministic weighted multi-dataset mixing (DESIGN.md "Recipe
engine") — the data half of the staged training recipes the reference
ships as three disjoint trainers (Chairs pairs, Sintel volumes, UCF-101
two-stream; PAPER.md §0).

`MixtureDataset` wraps N member datasets behind the same `Dataset`
protocol: each `sample_train` call folds the member CHOICE out of the
per-batch rng the caller passes in (`derive_batch_rng(seed, batch_index)`
— pipeline.py), then delegates the draw to the chosen member with the
SAME rng. The whole mixed batch is therefore a pure function of the
batch index, which is what makes the mixed stream bit-identical for any
`data.num_workers`, any `steps_per_call` regrouping, and across elastic
generation bumps — exactly the contract the single-dataset stream
already pins (tests/test_recipe.py pins the mixed one).

Member batches are structurally validated at BUILD time, not mid-run: a
T=2 Sintel volume batch is normalized to the pair form ({source,
target, flow}) Chairs emits, and any remaining disagreement on keys,
per-sample shapes, dtypes, or implied time_step raises a ValueError
naming the offending recipe stage and both members.
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np

from ..core.config import DataConfig, StageConfig


def normalize_batch(batch: dict) -> dict:
    """Canonical batch form shared by mixture members: a T=2 volume
    ((B, H, W, 6) frames + (B, H, W, 2) flow) becomes the pair form
    {source, target, flow} FlyingChairs emits, so Chairs pairs and
    2-frame Sintel windows mix structurally. T > 2 volumes pass
    through untouched (every member must then be volume-form)."""
    vol = batch.get("volume")
    if vol is not None and vol.ndim == 4 and vol.shape[-1] == 6:
        out = {k: v for k, v in batch.items() if k != "volume"}
        out["source"] = np.ascontiguousarray(vol[..., :3])
        out["target"] = np.ascontiguousarray(vol[..., 3:])
        return out
    return batch


def batch_structure(batch: dict) -> dict[str, tuple]:
    """{key -> (per-sample shape, dtype, implied time_step)} of one
    normalized batch — the structural signature members must agree on
    (the batch axis is dropped: members may be probed at any size)."""
    out: dict[str, tuple] = {}
    for k in sorted(batch):
        v = np.asarray(batch[k])
        shape = tuple(int(s) for s in v.shape[1:])
        if k == "volume":
            t = shape[-1] // 3 if shape else 0
        elif k in ("source", "target"):
            t = 2
        else:
            t = None
        out[k] = (shape, str(v.dtype), t)
    return out


class MixtureDataset:
    """Weighted deterministic mixture of member datasets behind the
    `Dataset` protocol (datasets.py).

    Train draws pick one member per batch (weight-proportional, folded
    from the caller's rng) and delegate with that same rng; val
    delegates entirely to the DOMINANT member (highest weight, first on
    ties) — eval AEE tracks the mixture's primary objective instead of
    averaging incomparable protocols. `mean` is the weight-averaged
    member mean so preprocessing is identical whichever member a batch
    came from (the compiled step bakes ONE mean).
    """

    def __init__(self, members: list, weights: list[float],
                 names: list[str], stage: str = ""):
        if not members or len(members) != len(weights) \
                or len(members) != len(names):
            raise ValueError(
                f"recipe stage {stage!r}: mixture needs parallel "
                f"members/weights/names, got {len(members)}/"
                f"{len(weights)}/{len(names)}")
        if any(w <= 0 for w in weights):
            raise ValueError(
                f"recipe stage {stage!r}: mixture weights must be "
                f"positive, got {weights}")
        self.members = list(members)
        self.names = list(names)
        self.stage = stage
        total = float(sum(weights))
        self.weights = [float(w) / total for w in weights]
        # cumulative bounds for the single uniform draw per batch
        self._cum = np.cumsum(self.weights)
        self._validate_members()
        self.num_train = sum(int(m.num_train) for m in self.members)
        # eval protocol: the dominant member owns the val split
        self._primary = int(max(range(len(self.members)),
                                key=lambda i: self.weights[i]))
        self.num_val = int(self.members[self._primary].num_val)
        self.mean = sum(
            w * np.asarray(m.mean, dtype=np.float64)
            for w, m in zip(self.weights, self.members)).astype(np.float32)
        # draws-by-member counters (obs/registry.py recipe_draws_by_
        # dataset): pipeline workers call sample_train concurrently
        self._lock = threading.Lock()
        self._draws = {n: 0 for n in self.names}

    # ------------------------------------------------------- validation
    def _validate_members(self) -> None:
        """Loud build-time structure agreement check (ISSUE 20
        satellite): every member is probed for one normalized sample
        and any disagreement on keys / per-sample shape / dtype /
        implied time_step raises, naming the stage and both members —
        a mixed recipe must fail at build, not mid-run."""
        ref_sig = ref_name = None
        for name, member in zip(self.names, self.members):
            # probe rng is local: member probing must not perturb the
            # training stream (sample_train is pure in the rng)
            batch = normalize_batch(
                member.sample_train(1, rng=np.random.RandomState(0)))
            sig = batch_structure(batch)
            if ref_sig is None:
                ref_sig, ref_name = sig, name
            elif sig != ref_sig:
                where = (f"recipe stage {self.stage!r}" if self.stage
                         else "mixture")
                raise ValueError(
                    f"{where}: mixture members disagree on sample "
                    f"structure/time_step — {ref_name!r} yields "
                    f"{ref_sig} but {name!r} yields {sig}; align the "
                    f"stage's image_size/time_step (or the members' "
                    f"overrides) so every member produces identical "
                    f"per-sample shapes")

    # --------------------------------------------------------- sampling
    def _pick(self, rng) -> int:
        """Member index from ONE uniform draw of the per-batch rng —
        the choice (and everything after it) is pure in the batch
        index, so any worker count replays the identical stream."""
        u = rng.random_sample()
        return int(np.searchsorted(self._cum, u, side="right").clip(
            0, len(self.members) - 1))

    def sample_train(self, batch_size, iteration=None, rng=None):
        if rng is None:
            rng = np.random.RandomState(iteration)
        idx = self._pick(rng)
        with self._lock:
            self._draws[self.names[idx]] += 1
        batch = self.members[idx].sample_train(batch_size,
                                               iteration=iteration,
                                               rng=rng)
        return normalize_batch(batch)

    def sample_val(self, batch_size, batch_id):
        return normalize_batch(
            self.members[self._primary].sample_val(batch_size, batch_id))

    def cache_stats(self) -> dict:
        out = {"hits": 0, "misses": 0, "evictions": 0}
        for m in self.members:
            s = m.cache_stats()
            for k in out:
                out[k] += int(s.get(k, 0))
        return out

    def mixture_stats(self) -> dict:
        """The registry-declared recipe mixture block: cumulative
        draws per member dataset name (kind: map — fleet merges sum
        key-wise)."""
        with self._lock:
            return {"recipe_draws_by_dataset": dict(self._draws)}


def build_mixture(data_cfg: DataConfig, stage: StageConfig):
    """Build a stage's mixture dataset from its member configs.

    `data_cfg` is the STAGE-resolved DataConfig (image_size/time_step
    overrides already applied by train/recipe.py); each member inherits
    it and overrides only dataset identity, path, sintel_pass, and
    time_step. A single-member mixture degenerates to that member's
    dataset wrapped for the counters — same code path, no special case.
    """
    from .datasets import build_dataset

    if not stage.mixture:
        raise ValueError(f"recipe stage {stage.name!r}: empty mixture — "
                         f"declare at least one member")
    members, weights, names = [], [], []
    for m in stage.mixture:
        dcfg = dataclasses.replace(
            data_cfg,
            dataset=m.dataset,
            data_path=m.data_path or data_cfg.data_path,
            sintel_pass=m.sintel_pass or data_cfg.sintel_pass,
            time_step=m.time_step or data_cfg.time_step)
        members.append(build_dataset(dcfg))
        weights.append(float(m.weight))
        names.append(m.dataset)
    return MixtureDataset(members, weights, names, stage=stage.name)
