"""Multi-worker host batch assembly with deterministic ordered delivery.

PR 1's execution layer hid device-side latency (compile cache, AOT
warmup, pipelined dispatch/fetch), which moves the wall-clock ceiling to
the host: a single thread decoding/resizing/augmenting/stacking every
(super-)batch is exactly the per-step host-decode starvation SURVEY.md
§7.3.4 flags — and with `steps_per_call` scans it must assemble
`steps_per_call x batch` images per dispatch.

`InputPipeline` is the host-side fan-out: a pool of N worker threads
(cv2 imdecode/resize and the native C++ batch IO both release the GIL,
so decode parallelism is real even under CPython) assembles batches
out-of-order and delivers them **in order** through a bounded reorder
buffer. Determinism is by construction, not by luck:

  - every batch index maps to its own rng via `derive_batch_rng(base,
    i)` (MT19937 init_by_array over `[base..., i_lo, i_hi]`), so the
    sample/augment stream for index i never depends on which worker ran
    it, in what order, or how many workers exist;
  - delivery order is the index order, enforced by the reorder buffer.

Together: the delivered batch stream is bit-identical for ANY
`num_workers`, including 0 — where `get()` assembles inline on the
caller's thread (the Prefetcher's producer thread in the train loop,
i.e. today's single-thread topology) with zero pool overhead.

The layer is observable end-to-end (`stats()`): batches assembled,
per-batch assemble seconds, reorder-queue depth (current + max),
consumer waits (`get()` found the next batch not ready — the host side
of device starvation), and worker utilization. The train loop folds
these into the periodic metrics line and `bench.py --data` measures the
pipeline in isolation (batches/s, MB/s) so host vs. device bottlenecks
are attributable without a TPU.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable

import numpy as np

from ..obs import trace as obs_trace
from ..resilience.healing import retry_bounded


def resolve_num_workers(num_workers: int,
                        cpu_count: int | None = None) -> int:
    """`data.num_workers` -> an actual pool size.

    >= 0 passes through. -1 (auto) sizes to the host: 0 (inline
    assembly, zero pool overhead) when `os.cpu_count() <= 2` — BENCH_r06
    measured workers=4 LOSING to workers=0 on a small host (49.5 vs
    85.3 batches/s: pure thread contention, nothing to overlap when the
    runtime already owns the cores) — else `min(4, cpu_count - 2)`:
    leave two cores for the jax runtime + prefetch/fetcher threads, cap
    at 4 (decode parallelism saturates well before that on the measured
    workloads; beyond it the reorder buffer just buys memory).

    cpu_count: test override for the host probe.
    """
    n = int(num_workers)
    if n >= 0:
        return n
    if n != -1:  # a typo'd worker count must not silently become auto
        raise ValueError(f"num_workers must be >= 0 or -1 (auto), got {n}")
    cpus = cpu_count if cpu_count is not None else (os.cpu_count() or 1)
    if cpus <= 2:
        return 0
    return min(4, cpus - 2)


def derive_batch_rng(base_seed, batch_index: int,
                     salt: int = 0) -> np.random.RandomState:
    """Deterministic per-batch rng: (stream seed, batch index) -> rng.

    `base_seed` is an int or a uint32 array (the train loop passes
    `data_stream_seed(...)` — process-decorrelated, resume-fresh). The
    derived stream depends only on (base, index): identical for any
    worker count and any assembly order, the pipeline's determinism
    contract. Base words and the index are both carried as uint32
    PAIRS, so 64-bit seeds and indices are folded in losslessly.

    `salt` selects a SIBLING stream for the same (base, index) — the
    self-healing data path's substitute draws (resilience/healing.py:
    round r redraws a quarantined batch index from salt=r). salt=0
    appends nothing, so existing streams are bit-identical to the
    pre-salt implementation.
    """
    base = np.atleast_1d(np.asarray(base_seed, dtype=np.uint64))
    words = np.empty(2 * base.size + 2, np.uint32)
    words[0:-2:2] = (base & 0xFFFFFFFF).astype(np.uint32)
    words[1:-2:2] = (base >> 32).astype(np.uint32)
    idx = int(batch_index)
    words[-2] = idx & 0xFFFFFFFF
    words[-1] = (idx >> 32) & 0xFFFFFFFF
    if salt:
        s = int(salt)
        words = np.concatenate([
            words,
            np.asarray([s & 0xFFFFFFFF, (s >> 32) & 0xFFFFFFFF], np.uint32),
        ])
    return np.random.RandomState(words)


class InputPipeline:
    """Ordered delivery of `make_batch(i)` results over a worker pool.

    make_batch: batch index -> batch dict. Must be a pure function of
        the index (derive any randomness from the index — see
        `derive_batch_rng`); with `num_workers > 0` it runs concurrently
        on pool threads, so shared state it touches (decoded caches,
        ...) must be thread-safe.
    num_workers: pool size. 0 = no threads; `get()` assembles inline on
        the caller's thread (the legacy single-thread path, bit-identical
        stream, zero overhead). -1 = auto (`resolve_num_workers`): 0 on
        hosts with <= 2 cores, min(4, cores - 2) otherwise — the stream
        stays bit-identical either way (the determinism contract is
        worker-count independent).
    reorder_depth: how many indices past the delivery cursor workers may
        claim — bounds both in-flight assembly and the completed-but-
        undelivered reorder buffer, so buffered-batch memory stays
        bounded when one slow batch holds back delivery. The bounded
        memory lives WHERE the batches live: host RAM for numpy
        assembly, device HBM when make_batch returns device-resident
        arrays (e.g. on-device augmentation output) — size reorder_depth
        x batch bytes against the right budget. 0 = auto
        (2 x num_workers). Values below num_workers just idle the excess
        workers (never deadlock: the cursor's own batch is always
        claimable).
    retries: re-attempts of a failed `make_batch(i)` before the error
        dooms delivery (resilience layer: a transient IO/runtime error
        on a pipeline worker no longer kills the run). Safe because
        make_batch is a pure function of the index — a retry reproduces
        the exact same batch. Only OSError/RuntimeError retry;
        programming errors surface immediately.
    backoff_s: initial sleep before a retry; doubles per attempt.
    """

    def __init__(self, make_batch: Callable[[int], dict],
                 num_workers: int = 0, reorder_depth: int = 0,
                 retries: int = 0, backoff_s: float = 0.05):
        self._make = make_batch
        self._n = resolve_num_workers(num_workers)
        self._depth = (int(reorder_depth) if reorder_depth > 0
                       else max(2 * self._n, 1))
        self._retries = max(int(retries), 0)
        self._backoff = max(float(backoff_s), 0.0)
        self._cv = threading.Condition()
        self._next_claim = 0  # next index a worker will take
        self._next_out = 0  # next index get() delivers
        self._ready: dict[int, dict] = {}
        self._exc: BaseException | None = None
        self._fail_idx: int | None = None  # lowest index that errored
        self._stop = False
        # --- counters (all guarded by _cv; snapshot via stats()) ---
        self._batches = 0
        self._assemble_s = 0.0
        self._busy_s = 0.0
        self._waits = 0
        self._wait_s = 0.0
        self._retry_count = 0
        self._max_depth = 0
        self._t0 = time.perf_counter()
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"pipeline-worker-{i}")
            for i in range(self._n)
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------- pool
    def _attempt(self, i: int) -> dict:
        """`make_batch(i)` with the shared bounded retry ladder
        (resilience/healing.py). Purity of make_batch makes a retry
        deliver the identical batch, so determinism survives transient
        faults."""

        def make():
            with obs_trace.span("assemble", index=i):
                return self._make(i)

        return retry_bounded(make, retries=self._retries,
                             backoff_s=self._backoff,
                             on_retry=self._count_retry)

    def _count_retry(self) -> None:
        with self._cv:
            self._retry_count += 1

    def _worker(self) -> None:
        while True:
            with self._cv:
                while (not self._stop and self._exc is None
                       and self._next_claim >= self._next_out + self._depth):
                    self._cv.wait()
                if self._stop or self._exc is not None:
                    return
                i = self._next_claim
                self._next_claim += 1
            t0 = time.perf_counter()
            try:
                batch = self._attempt(i)
            except BaseException as e:  # noqa: BLE001 - surfaced on get()
                with self._cv:
                    if self._exc is None:
                        self._exc = e
                    if self._fail_idx is None or i < self._fail_idx:
                        self._fail_idx = i
                    self._cv.notify_all()
                return
            dt = time.perf_counter() - t0
            with self._cv:
                self._ready[i] = batch
                self._batches += 1
                self._assemble_s += dt
                self._busy_s += dt
                self._max_depth = max(self._max_depth, len(self._ready))
                self._cv.notify_all()

    # ---------------------------------------------------------- consume
    def get(self) -> dict:
        """Deliver the next batch, in index order."""
        if self._n == 0:
            with self._cv:
                if self._exc is not None:
                    raise self._exc
                i = self._next_out
                self._next_out += 1
            t0 = time.perf_counter()
            try:
                batch = self._attempt(i)
            except BaseException as e:  # noqa: BLE001 - one idiom for both paths
                with self._cv:
                    if self._exc is None:
                        self._exc = e
                    if self._fail_idx is None or i < self._fail_idx:
                        self._fail_idx = i
                raise
            dt = time.perf_counter() - t0
            with self._cv:
                self._batches += 1
                self._assemble_s += dt
                self._busy_s += dt
            return batch
        with self._cv:
            i = self._next_out
            if i not in self._ready:
                # the consumer outran the pool: the host side of device
                # starvation (the train loop's `starved` counter is the
                # device-facing mirror of this)
                self._waits += 1
                t0 = time.perf_counter()
                while i not in self._ready:
                    # a pool error only dooms delivery from the FAILED
                    # index on: lower indices were claimed earlier by
                    # healthy workers and still arrive — deliver them
                    # (deterministically) before surfacing the error
                    if (self._exc is not None
                            and (self._fail_idx is None
                                 or i >= self._fail_idx)):
                        raise self._exc
                    if self._stop:
                        raise RuntimeError("InputPipeline closed during get()")
                    if not self._cv.wait(timeout=5.0):
                        if not any(t.is_alive() for t in self._threads):
                            if self._exc is not None:
                                raise self._exc
                            raise RuntimeError(
                                "all pipeline workers died without error")
                self._wait_s += time.perf_counter() - t0
            batch = self._ready.pop(i)
            self._next_out += 1
            self._cv.notify_all()  # a claim slot opened
            return batch

    def __iter__(self):
        while True:
            yield self.get()

    # ------------------------------------------------------ observability
    def stats(self) -> dict:
        """Counter snapshot, log/bench-ready (plain ints/floats)."""
        with self._cv:
            wall = max(time.perf_counter() - self._t0, 1e-9)
            denom = max(self._n, 1) * wall
            return {
                "num_workers": self._n,
                "batches": self._batches,
                "assemble_s": round(self._assemble_s, 4),
                "assemble_s_mean": round(
                    self._assemble_s / self._batches, 4) if self._batches
                    else 0.0,
                "queue_depth": len(self._ready),
                "max_queue_depth": self._max_depth,
                "waits": self._waits,
                "wait_s": round(self._wait_s, 4),
                "retries": self._retry_count,
                "worker_util": round(self._busy_s / denom, 4),
            }

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)
