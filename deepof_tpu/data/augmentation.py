"""On-device augmentation as pure JAX functions of a PRNG key.

Capability union of the reference's two pipelines (SURVEY.md §2.6):
  - numpy host pipeline (`flyingChairsUtils.py:83-294`): geometric =
    translation (±0.2 of size), rotation (±17°), scale (0.9–2.0), L-R flip;
    photometric = contrast (−0.8–0.4), additive brightness noise,
    per-channel color (0.5–2), gamma (0.7–1.5), additive Gaussian noise
    (σ ≤ 0.04) — both frames transformed identically per sample;
  - TF in-graph pipeline (`version1/utils/augmentation.py`): same families,
    narrower ranges, no rotation.

Here both run jit-compiled on device under explicit PRNG keys (instead of
host cv2 loops), with the numpy pipeline's ranges as defaults. Geometric
transforms are expressed as an inverse-affine displacement field fed to the
same `backward_warp` gather used by the loss — one code path for all
resampling. Images are raw 0–255 BGR throughout; photometric ops work on
x/255 and rescale (the trainer's `preprocess` does mean/255 afterwards).

Dual-stream contract (`flyingChairsTrain_vgg.py:186-195`): `augment_batch`
returns geo-only `source`/`target` (the loss pair) plus photo-augmented
`net_source`/`net_target` (the network input pair).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import DataConfig
from ..ops.warp import backward_warp

# numpy-pipeline ranges (flyingChairsUtils.py:83-294)
TRANSLATION = 0.2
ROTATION_DEG = 17.0
SCALE_RANGE = (0.9, 2.0)
CONTRAST = (-0.8, 0.4)
BRIGHTNESS_SIGMA = 0.2
COLOR_RANGE = (0.5, 2.0)
GAMMA_RANGE = (0.7, 1.5)
NOISE_SIGMA_MAX = 0.04


def sample_geo_params(key: jax.Array, batch: int,
                      rotation: bool = True) -> dict[str, jnp.ndarray]:
    """Per-sample geometric parameters: angle (rad), scale, translation
    fractions, flip flag."""
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    rot = math.radians(ROTATION_DEG) if rotation else 0.0
    return {
        "angle": jax.random.uniform(k1, (batch,), minval=-rot, maxval=rot),
        "scale": jax.random.uniform(k2, (batch,), minval=SCALE_RANGE[0],
                                    maxval=SCALE_RANGE[1]),
        "tx": jax.random.uniform(k3, (batch,), minval=-TRANSLATION,
                                 maxval=TRANSLATION),
        "ty": jax.random.uniform(k4, (batch,), minval=-TRANSLATION,
                                 maxval=TRANSLATION),
        "flip": jax.random.bernoulli(k5, 0.5, (batch,)),
    }


def identity_geo_params(batch: int) -> dict[str, jnp.ndarray]:
    z = jnp.zeros((batch,))
    return {"angle": z, "scale": z + 1.0, "tx": z, "ty": z,
            "flip": jnp.zeros((batch,), bool)}


def apply_geo(images: jnp.ndarray, params: dict[str, jnp.ndarray]) -> jnp.ndarray:
    """Apply per-sample inverse-affine resampling to (B, H, W, C) images.

    Output pixel p maps to input coordinate
    c + R(-angle)/scale · flip_x · (p - c) - t·(W,H), clip-at-border bilinear
    (same convention as the warp loss). Expressed as a displacement field so
    `backward_warp` does the gather.
    """
    b, h, w, _ = images.shape
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    dx = (xs - cx)[None]  # (1, H, W)
    dy = (ys - cy)[None]

    ang = params["angle"][:, None, None]
    inv_s = 1.0 / params["scale"][:, None, None]
    fx = jnp.where(params["flip"], -1.0, 1.0)[:, None, None]
    cos, sin = jnp.cos(-ang), jnp.sin(-ang)

    dxf = dx * fx  # flip about the vertical axis first (in output space)
    src_x = cx + inv_s * (cos * dxf - sin * dy) - params["tx"][:, None, None] * w
    src_y = cy + inv_s * (sin * dxf + cos * dy) - params["ty"][:, None, None] * h

    flow = jnp.stack([src_x - xs[None], src_y - ys[None]], axis=-1)  # (B,H,W,2)
    return backward_warp(images, flow)


def photometric_augment(key: jax.Array, *frames: jnp.ndarray,
                        color_range=COLOR_RANGE, contrast=CONTRAST,
                        gamma_range=GAMMA_RANGE) -> list[jnp.ndarray]:
    """Contrast/brightness/color/gamma/noise, identical parameters for every
    frame of a sample (`flyingChairsUtils.py:220-294`). Frames are 0–255."""
    b = frames[0].shape[0]
    kc, kb, kcol, kg, kn1, kn2 = jax.random.split(key, 6)
    c = jax.random.uniform(kc, (b, 1, 1, 1), minval=contrast[0], maxval=contrast[1])
    bright = jax.random.normal(kb, (b, 1, 1, 1)) * BRIGHTNESS_SIGMA
    color = jax.random.uniform(kcol, (b, 1, 1, 3), minval=color_range[0],
                               maxval=color_range[1])
    gamma = jax.random.uniform(kg, (b, 1, 1, 1), minval=gamma_range[0],
                               maxval=gamma_range[1])
    sigma = jax.random.uniform(kn1, (b, 1, 1, 1), maxval=NOISE_SIGMA_MAX)

    out = []
    for i, f in enumerate(frames):
        x = f / 255.0
        x = x * (1.0 + c)          # contrast about black
        x = x + bright             # brightness offset
        x = x * color              # per-channel color
        x = jnp.clip(x, 0.0, 1.0) ** gamma
        noise = jax.random.normal(jax.random.fold_in(kn2, i), f.shape) * sigma
        x = jnp.clip(x + noise, 0.0, 1.0)
        out.append(x * 255.0)
    return out


@functools.partial(jax.jit, static_argnames=("geo", "photo", "rotation"))
def augment_batch(batch: dict, key: jax.Array, geo: bool = True,
                  photo: bool = True, rotation: bool = True) -> dict:
    """Dual-stream augmentation of a {source, target, ...} batch.

    Returns the batch with geo-transformed source/target (loss pair) and,
    when `photo`, additional net_source/net_target (network pair). Extra
    keys (flow, label) pass through untouched — GT flow is only used for
    eval, which never augments (`flyingChairsTrain_vgg.py:266-271`).
    """
    src, tgt = batch["source"], batch["target"]
    kg, kp = jax.random.split(key)
    if geo:
        params = sample_geo_params(kg, src.shape[0], rotation)
        src, tgt = apply_geo(src, params), apply_geo(tgt, params)
    out = dict(batch)
    out["source"], out["target"] = src, tgt
    if photo:
        out["net_source"], out["net_target"] = photometric_augment(kp, src, tgt)
    return out


def make_augment_fn(cfg: DataConfig):
    """Host-callable augmenter: (numpy batch, int seed) -> augmented batch.

    Image tensors stay on device — the downstream `device_put` with the
    batch sharding reshards them device-to-device instead of forcing a
    device->host->device roundtrip on the hot input path.

    Thread contract: the train loop calls this from input-pipeline
    WORKER threads (`data/pipeline.py`), concurrently at num_workers>1.
    That is safe — jax jit dispatch is thread-safe and the fn holds no
    state — and deterministic: the seed is drawn from the caller's
    per-batch derived rng, so a batch's augmentation never depends on
    which worker ran it.
    """
    geo, photo = cfg.augment_geo, cfg.augment_photo

    def fn(batch: dict, seed) -> dict:
        key = jax.random.PRNGKey(int(seed))
        return dict(augment_batch(batch, key, geo=geo, photo=photo))

    return fn
