"""Dataset index builders and batch samplers.

Numpy/cv2 host-side loaders with decoded-image caching. Batches are dicts of
float32 numpy arrays, BGR channel order with per-dataset means preserved from
the reference (`flyingChairsLoader.py:28`, `sintelLoader.py:29`,
`version1/loader/ucf101Loader.py` mean [104,117,123]).

Split semantics:
  - FlyingChairs: official `FlyingChairs_train_val.txt` (one marker per
    sample, 1=train 2=val, `flyingChairsLoader.py:47-55`). Zero-egress: no
    auto-download; if the file is absent the last 640 samples become val
    (documented divergence from the reference's wget at
    `flyingChairsLoader.py:31-34`).
  - Sintel: all T-frame sliding windows per clip
    (`sintelLoader.py:31-45`); val = the first window of each clip in
    sorted-clip order, plus one extra bamboo_2 window starting at frame
    `time_step` — the reference's exact membership and order
    (`sintelLoader.py:47-70`: 23 clips + 1 = 24 windows), so EPE numbers
    are protocol-comparable at the 24-window granularity.
  - UCF-101: clip group number > 7 -> train (`ucf101Loader.py:42-58`);
    train batch = one random frame-pair from each of B distinct random
    classes (`ucf101Loader.py:66-87`).
"""

from __future__ import annotations

import collections
import os
import re
import threading
import warnings
from typing import Protocol

import numpy as np

try:  # pragma: no cover - exercised implicitly
    import cv2
except Exception:  # noqa: BLE001
    cv2 = None

from ..core.config import DataConfig
from ..io.flo import read_flo

FLYINGCHAIRS_MEAN = (97.533, 99.238, 97.056)  # BGR, flyingChairsLoader.py:28
SINTEL_MEAN = (70.1433, 83.1915, 92.8827)  # sintelLoader.py:29
UCF101_MEAN = (104.0, 117.0, 123.0)  # version1/loader/ucf101Loader.py

DATASET_MEANS = {
    "flyingchairs": FLYINGCHAIRS_MEAN,
    "sintel": SINTEL_MEAN,
    "ucf101": UCF101_MEAN,
    "synthetic": (0.0, 0.0, 0.0),
}


_warned_native_fallback = False


def _warn_native_fallback(err: Exception) -> None:
    """One warning per process: native batch IO failed (mixed formats,
    corrupt file, ...) and the affected batches take the python path."""
    global _warned_native_fallback
    if not _warned_native_fallback:
        _warned_native_fallback = True
        warnings.warn(
            f"native IO batch failed ({err}); affected batches fall back "
            "to the python decode path", RuntimeWarning, stacklevel=3)


def _imread_bgr(path: str) -> np.ndarray:
    img = cv2.imread(path, cv2.IMREAD_COLOR)  # BGR, matches reference cv2 use
    if img is None:
        raise FileNotFoundError(path)
    return img


def _resize(img: np.ndarray, hw: tuple[int, int]) -> np.ndarray:
    if img.shape[:2] == tuple(hw):
        return img
    return cv2.resize(img, (hw[1], hw[0]), interpolation=cv2.INTER_LINEAR)


class Dataset(Protocol):
    """Batch-sampler protocol shared by all datasets.

    `sample_train` returns a dict with at least the network-input tensors;
    `num_train`/`num_val` drive the epoch loop like the reference's
    `trainNum`/`valNum` (`version1/loader/flyingChairsLoader.py:26-36`).
    """

    mean: tuple[float, float, float]
    num_train: int
    num_val: int

    def sample_train(self, batch_size: int, iteration: int | None = None,
                     rng: np.random.RandomState | None = None) -> dict: ...

    def sample_val(self, batch_size: int, batch_id: int) -> dict: ...

    def cache_stats(self) -> dict: ...


class _DecodedCache:
    """Byte-bounded decoded-image cache (SURVEY.md §7.3.4: per-step host
    decode starves a TPU). LRU eviction keeps host RAM bounded even on the
    full 22k-pair FlyingChairs set.

    Thread-safe: the multi-worker input pipeline (`data/pipeline.py`)
    shares one cache across decode workers. The OrderedDict is guarded by
    a lock; misses decode OUTSIDE it so workers never serialize on cv2 —
    two threads missing the same path decode it twice (benign: identical
    result, last insert wins, double-counted bytes corrected on insert).
    Hit/miss/eviction counters surface in train logs and `bench.py`.
    """

    def __init__(self, enabled: bool, reader, max_bytes: int = 4 << 30):
        self._enabled = enabled
        self._reader = reader
        self._max_bytes = max_bytes
        self._bytes = 0
        self._store: collections.OrderedDict[str, np.ndarray] = (
            collections.OrderedDict())
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._evictions = 0

    def __call__(self, path: str) -> np.ndarray:
        if not self._enabled:
            return self._reader(path)
        with self._lock:
            hit = self._store.pop(path, None)
            if hit is not None:
                self._hits += 1
                self._store[path] = hit  # re-insert as most recent
                return hit
            self._misses += 1
        decoded = self._reader(path)  # off-lock: decode is the slow part
        with self._lock:
            prev = self._store.pop(path, None)  # racing double-decode
            if prev is None:
                self._bytes += decoded.nbytes
            while self._bytes > self._max_bytes and self._store:
                _, old = self._store.popitem(last=False)
                self._bytes -= old.nbytes
                self._evictions += 1
            self._store[path] = decoded
        return decoded

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self._hits, "misses": self._misses,
                    "evictions": self._evictions, "bytes": self._bytes,
                    "entries": len(self._store)}


class FlyingChairsData:
    """FlyingChairs pairs: `XXXXX_img1.ppm`, `XXXXX_img2.ppm`, `XXXXX_flow.flo`.

    Images are resized to `cfg.image_size`; ground-truth flow stays at its
    native resolution (`flyingChairsLoader.py:71-81`). Supports both the
    gen-2 sequential batching (`iteration` arg, `flyingChairsLoader.py:57-62`)
    and gen-1 random sampling (`version1/loader/flyingChairsLoader.py:66-70`).
    """

    mean = FLYINGCHAIRS_MEAN

    def __init__(self, cfg: DataConfig):
        self.cfg = cfg
        root = cfg.data_path
        ids = sorted(
            m.group(1)
            for f in os.listdir(root)
            if (m := re.match(r"(\d+)_img1\.ppm$", f))
        )
        if not ids:
            raise FileNotFoundError(f"no *_img1.ppm under {root}")
        split_file = os.path.join(root, "FlyingChairs_train_val.txt")
        if not os.path.exists(split_file):
            split_file = os.path.join(os.path.dirname(root), "FlyingChairs_train_val.txt")
        if os.path.exists(split_file):
            markers = np.loadtxt(split_file, dtype=int)[: len(ids)]
        else:  # zero-egress fallback: last 640 (capped at 10%, min 1) are val
            n_val = min(640, max(1, len(ids) // 10))
            markers = np.ones(len(ids), dtype=int)
            markers[-n_val:] = 2
        self.train_ids = [i for i, m in zip(ids, markers) if m == 1]
        self.val_ids = [i for i, m in zip(ids, markers) if m == 2]
        self.num_train, self.num_val = len(self.train_ids), len(self.val_ids)
        self._root = root
        self._cache = _DecodedCache(cfg.cache_decoded, _imread_bgr,
                                    max_bytes=cfg.cache_bytes)
        self._flo_hw: tuple[int, int] | None = None  # native path probe

    def _load(self, sid: str, with_flow: bool) -> tuple[np.ndarray, np.ndarray, np.ndarray | None]:
        p = os.path.join(self._root, sid)
        src = _resize(self._cache(p + "_img1.ppm"), self.cfg.image_size)
        tgt = _resize(self._cache(p + "_img2.ppm"), self.cfg.image_size)
        flow = read_flo(p + "_flow.flo") if with_flow else None
        return src, tgt, flow

    def _batch(self, sids: list[str]) -> dict:
        native = self._native_batch(sids)
        if native is not None:
            return native
        srcs, tgts, flows = zip(*(self._load(s, True) for s in sids))
        return {
            "source": np.stack(srcs).astype(np.float32),
            "target": np.stack(tgts).astype(np.float32),
            "flow": np.stack(flows).astype(np.float32),
        }

    def _native_batch(self, sids: list[str]) -> dict | None:
        """Whole-batch parallel decode through the C++ IO library (thread
        pool outside the GIL; deepof_tpu/native).

        Only used in streaming mode (`cache_decoded=False` — the right
        setting when the dataset exceeds the decoded-image cache, e.g. the
        full 22k-pair FlyingChairs set): with the cache enabled, warm RAM
        hits beat a fresh parallel decode, so the cv2+cache path wins.
        Falls back to that path when the library is unavailable.
        """
        from .. import native

        if self.cfg.cache_decoded or not native.available():
            return None
        paths = [os.path.join(self._root, s) for s in sids]
        try:
            if self._flo_hw is None:
                self._flo_hw = native.flo_dims(paths[0] + "_flow.flo")
            imgs = native.decode_ppm_batch(
                [p + sfx for sfx in ("_img1.ppm", "_img2.ppm") for p in paths],
                self.cfg.image_size)
            flows = native.read_flo_batch([p + "_flow.flo" for p in paths],
                                          self._flo_hw)
        except (OSError, RuntimeError) as e:
            # a later-unsupported/corrupt file must degrade to the python
            # path for this batch, not fail it (ADVICE r02)
            _warn_native_fallback(e)
            return None
        n = len(paths)
        return {"source": imgs[:n], "target": imgs[n:], "flow": flows}

    def sample_train(self, batch_size, iteration=None, rng=None):
        if iteration is not None:  # sequential, gen-2
            # wrap like sample_val: a num_train below batch_size (or a
            # start near the tail) must still yield exactly batch_size
            # samples — a short batch breaks the compiled executable's
            # fixed shapes
            if not self.num_train:
                raise ValueError(
                    f"empty FlyingChairs train split under {self._root} "
                    "(split file marks every pair as val)")
            start = (iteration * batch_size) % self.num_train
            sids = [self.train_ids[(start + k) % self.num_train]
                    for k in range(batch_size)]
        else:
            rng = rng or np.random
            sids = [self.train_ids[i] for i in rng.randint(0, self.num_train, batch_size)]
        return self._batch(sids)

    def sample_val(self, batch_size, batch_id):
        start = (batch_id * batch_size) % max(self.num_val, 1)
        sids = [self.val_ids[(start + k) % self.num_val] for k in range(batch_size)]
        return self._batch(sids)

    def cache_stats(self) -> dict:
        return self._cache.stats()


class SintelData:
    """MPI-Sintel T-frame sliding-window volumes.

    Layout: `training/<pass>/<clip>/frame_XXXX.png`,
    `training/flow/<clip>/frame_XXXX.flo` (`sintelLoader.py:20-45`). Batches:
    volume (B, H, W, 3T) channel-stacked frames + flows (B, H, W, 2(T-1))
    at native GT resolution (`sintelLoader.py:77-93`). Optional random crop
    to `cfg.crop_size` of the network input (train only, `deepOF.py:14-16`).
    """

    mean = SINTEL_MEAN

    def __init__(self, cfg: DataConfig):
        self.cfg = cfg
        self.t = cfg.time_step
        if cfg.sintel_pair_split_file is not None and self.t != 2:
            raise ValueError(
                "data.sintel_pair_split_file is the gen-1 PAIR split "
                "(`version1/loader/sintelLoader.py:38-70`) and requires "
                f"time_step=2; got time_step={self.t}")
        img_root = os.path.join(cfg.data_path, "training", cfg.sintel_pass)
        flow_root = os.path.join(cfg.data_path, "training", "flow")
        clips = sorted(os.listdir(img_root))
        self.windows: list[list[str]] = []  # absolute frame paths per window
        self.flow_windows: list[list[str]] = []
        val: list[int] = []
        for clip in clips:
            frames = sorted(
                os.path.join(img_root, clip, f)
                for f in os.listdir(os.path.join(img_root, clip))
                if f.endswith(".png")
            )
            flows = sorted(
                os.path.join(flow_root, clip, f)
                for f in os.listdir(os.path.join(flow_root, clip))
                if f.endswith(".flo")
            )
            clip_start = len(self.windows)
            n_windows = len(frames) - self.t + 1
            for s in range(0, n_windows):
                self.windows.append(frames[s : s + self.t])
                self.flow_windows.append(flows[s : s + self.t - 1])
            # Reference val membership, exactly (`sintelLoader.py:47-70`):
            # the first window of every clip, and for bamboo_2 one extra
            # window starting at frame `time_step` (23 clips + 1 = 24).
            if n_windows > 0:
                val.append(clip_start)
            if clip == "bamboo_2" and n_windows > self.t:
                val.append(clip_start + self.t)
        if cfg.sintel_pair_split_file is not None:
            # Gen-1 membership (`sintelLoader.py:47-70`): the k-th line of
            # Sintel_train_val.txt labels the k-th consecutive frame pair
            # in sorted clip x frame order — with time_step=2 that order
            # IS self.windows' construction order. "1" = train, "2" = val.
            with open(cfg.sintel_pair_split_file) as sf:
                labels = [ln.strip()[:1] for ln in sf if ln.strip()]
            if len(labels) != len(self.windows):
                raise ValueError(
                    f"pair split file {cfg.sintel_pair_split_file!r} has "
                    f"{len(labels)} entries but the dataset has "
                    f"{len(self.windows)} consecutive pairs")
            bad = sorted({c for c in labels} - {"1", "2"})
            if bad:
                raise ValueError(
                    f"pair split file {cfg.sintel_pair_split_file!r} has "
                    f"entries {bad}; expected '1' (train) or '2' (val)")
            val = [i for i, c in enumerate(labels) if c == "2"]
        self.val_idx = val
        self.train_idx = [i for i in range(len(self.windows)) if i not in set(self.val_idx)]
        self.num_train, self.num_val = len(self.train_idx), len(self.val_idx)
        self._cache = _DecodedCache(cfg.cache_decoded, _imread_bgr,
                                    max_bytes=cfg.cache_bytes)
        self._flo_hw: tuple[int, int] | None = None  # native path probe
        self._native_ok: bool | None = None  # codec probe, once

    def _window(self, w: int, crop_rng: np.random.RandomState | None) -> tuple[np.ndarray, np.ndarray]:
        imgs = [_resize(self._cache(p), self.cfg.image_size) for p in self.windows[w]]
        vol = np.concatenate(imgs, axis=-1).astype(np.float32)  # (H,W,3T)
        if crop_rng is not None and self.cfg.crop_size is not None:
            ch, cw = self.cfg.crop_size
            h, w_ = vol.shape[:2]
            y = crop_rng.randint(0, h - ch + 1)
            x = crop_rng.randint(0, w_ - cw + 1)
            vol = vol[y : y + ch, x : x + cw]
        flows = np.concatenate(
            [read_flo(p) for p in self.flow_windows[w]], axis=-1
        ).astype(np.float32)  # native res, (H,W,2(T-1))
        return vol, flows

    def _batch(self, idxs, crop_rng=None):
        nb = self._native_batch(idxs, crop_rng)
        if nb is not None:
            return nb
        vols, flows = zip(*(self._window(i, crop_rng) for i in idxs))
        return {"volume": np.stack(vols), "flow": np.stack(flows)}

    def _native_batch(self, idxs, crop_rng=None) -> dict | None:
        """Whole-batch PNG decode + .flo read on the C++ thread pool
        (streaming mode; the decoded cache already amortizes the python
        path). Falls back to the cv2 path when unavailable. Identical
        output to `_window` per sample, including the crop rng draws."""
        from .. import native

        if self.cfg.cache_decoded:
            return None
        frame_paths = [p for i in idxs for p in self.windows[i]]
        if self._native_ok is None:  # probe the build's codecs once
            self._native_ok = (native.available()
                               and native.image_supported(frame_paths[0]))
        if not self._native_ok:
            return None
        t = self.t
        b = len(idxs)
        h, w = self.cfg.image_size
        # all native reads happen BEFORE any crop_rng draw, so a failed
        # batch falls back to `_window` with the rng stream intact (same
        # draw order as the python path)
        try:
            imgs = native.decode_image_batch(frame_paths, (h, w))
            flow_paths = [p for i in idxs for p in self.flow_windows[i]]
            if self._flo_hw is None:
                self._flo_hw = native.flo_dims(flow_paths[0])
            fh, fw = self._flo_hw
            flo = native.read_flo_batch(flow_paths, (fh, fw))
        except (OSError, RuntimeError) as e:
            _warn_native_fallback(e)
            return None
        # channel-stack each window's T frames (frame-major, BGR within)
        vols = (imgs.reshape(b, t, h, w, 3).transpose(0, 2, 3, 1, 4)
                .reshape(b, h, w, 3 * t))
        if crop_rng is not None and self.cfg.crop_size is not None:
            ch, cw = self.cfg.crop_size
            out = np.empty((b, ch, cw, 3 * t), np.float32)
            for k in range(b):  # same rng draw order as _window
                y = crop_rng.randint(0, h - ch + 1)
                x = crop_rng.randint(0, w - cw + 1)
                out[k] = vols[k, y : y + ch, x : x + cw]
            vols = out
        flows = (flo.reshape(b, t - 1, fh, fw, 2).transpose(0, 2, 3, 1, 4)
                 .reshape(b, fh, fw, 2 * (t - 1)))
        return {"volume": vols, "flow": flows}

    def sample_train(self, batch_size, iteration=None, rng=None):
        # no frame-sequential gen-2 mode exists for windows; a
        # sequential (`iteration`) caller still gets a DETERMINISTIC
        # exact-batch_size draw per iteration instead of a silently
        # unseeded one (same contract as the other dataset classes)
        if rng is None:
            rng = np.random.RandomState(iteration)  # None = OS entropy
        idxs = [self.train_idx[i] for i in rng.randint(0, self.num_train, batch_size)]
        return self._batch(idxs, crop_rng=rng)

    def sample_val(self, batch_size, batch_id):
        start = (batch_id * batch_size) % max(self.num_val, 1)
        idxs = [self.val_idx[(start + k) % self.num_val] for k in range(batch_size)]
        return self._batch(idxs)

    def cache_stats(self) -> dict:
        return self._cache.stats()


class UCF101Data:
    """UCF-101 frame pairs for joint flow + action learning.

    Layout: `frames/<class>/<clip>/<frame>.jpg`, clip names
    `v_<Class>_gNN_cMM`; group NN > 7 -> train (`ucf101Loader.py:42-58`).
    Train batch: one random consecutive pair from each of B distinct random
    classes, with the class index as the action label
    (`ucf101Loader.py:66-87`).
    """

    mean = UCF101_MEAN

    def __init__(self, cfg: DataConfig):
        self.cfg = cfg
        root = os.path.join(cfg.data_path, "frames")
        self.classes = sorted(os.listdir(root))
        self.train_clips: dict[int, list[list[str]]] = {}
        self.val_clips: dict[int, list[list[str]]] = {}
        for ci, cls in enumerate(self.classes):
            for clip in sorted(os.listdir(os.path.join(root, cls))):
                frames = sorted(
                    os.path.join(root, cls, clip, f)
                    for f in os.listdir(os.path.join(root, cls, clip))
                )
                if len(frames) < 2:
                    continue
                m = re.search(r"_g(\d+)_", clip)
                group = int(m.group(1)) if m else 99
                (self.train_clips if group > 7 else self.val_clips).setdefault(
                    ci, []
                ).append(frames)
        self.num_train = sum(len(v) for v in self.train_clips.values())
        self.num_val = sum(len(v) for v in self.val_clips.values())
        self._cache = _DecodedCache(cfg.cache_decoded, _imread_bgr,
                                    max_bytes=cfg.cache_bytes)
        self._native_ok: bool | None = None  # codec probe, once

    def _batch_from(self, clips: dict[int, list[list[str]]], class_ids, rng):
        # pick all (src, tgt) frame paths first (one rng draw order shared
        # by the native and python decode paths), then decode the whole
        # batch in one call
        paths, labels = [], []
        for ci in class_ids:
            pool = clips[ci]
            frames = pool[rng.randint(0, len(pool))]
            i = rng.randint(0, len(frames) - 1)
            paths += [frames[i], frames[i + 1]]
            labels.append(ci)
        imgs = self._decode_many(paths)
        return {
            "source": imgs[0::2],
            "target": imgs[1::2],
            "label": np.asarray(labels, np.int32),
        }

    def _decode_many(self, paths: list[str]) -> np.ndarray:
        """(N, H, W, 3) float32 BGR: JPEG decode on the C++ thread pool in
        streaming mode, cv2 + decoded cache otherwise."""
        from .. import native

        if not self.cfg.cache_decoded:
            if self._native_ok is None:  # probe the build's codecs once
                self._native_ok = (native.available()
                                   and native.image_supported(paths[0]))
            if self._native_ok:
                try:
                    return native.decode_image_batch(paths, self.cfg.image_size)
                except (OSError, RuntimeError) as e:
                    _warn_native_fallback(e)
        return np.stack([
            _resize(self._cache(p), self.cfg.image_size) for p in paths
        ]).astype(np.float32)

    def sample_train(self, batch_size, iteration=None, rng=None):
        # sequential callers: deterministic per-iteration draw (see
        # SintelData.sample_train)
        if rng is None:
            rng = np.random.RandomState(iteration)  # None = OS entropy
        avail = list(self.train_clips)
        replace = batch_size > len(avail)
        class_ids = rng.choice(avail, size=batch_size, replace=replace)
        return self._batch_from(self.train_clips, class_ids, rng)

    def sample_val(self, batch_size, batch_id):
        """One batch from a single class — the reference evaluates 101
        class-batches in turn (`ucf101train.py:210-223`)."""
        rng = np.random.RandomState(batch_id)
        avail = sorted(self.val_clips)
        ci = avail[batch_id % len(avail)]
        return self._batch_from(self.val_clips, [ci] * batch_size, rng)

    def cache_stats(self) -> dict:
        return self._cache.stats()


class SyntheticData:
    """Procedural dataset with exact ground-truth flow, for tests and the
    benchmark harness (no counterpart in the reference, which has no tests).

    Each sample: a smooth random image; the target is the source translated
    by a per-sample constant (u, v) — so GT flow is uniform and the
    unsupervised loss is minimized by the true flow. style="affine"
    generalizes to a spatially VARYING exact-GT field (rotation/scale/shear
    about a random center, magnitude bounded by max_shift): the source is
    constructed as the bilinear backward warp of the target canvas by the
    GT field, so the unsupervised objective's minimizer is still exactly
    the GT flow, but a network can no longer satisfy it with a single
    global translation — it must discriminate spatially.
    """

    mean = (0.0, 0.0, 0.0)
    #: bumped whenever the procedural generator's output changes for the
    #: same seed (e.g. the r04 multi-octave canvas rewrite = 2): fitting
    #: tools fingerprint it so a checkpoint lineage never silently
    #: resumes across a data-distribution change.
    CANVAS_VERSION = 2

    def __init__(self, cfg: DataConfig, num_train: int = 64, num_val: int = 16,
                 max_shift: float = 4.0, feature_scale: int = 8,
                 style: str = "noise", n_blobs: int = 8):
        self.cfg = cfg
        self.num_train, self.num_val = num_train, num_val
        self._max_shift = max_shift
        # pixels per random-noise feature: the photometric attraction basin
        # around the true flow is ~ a quarter feature wavelength, so
        # feature_scale must comfortably exceed max_shift for the
        # unsupervised objective to be optimizable from a zero-flow init
        self._feature_scale = feature_scale
        # "noise": upscaled random noise (quasi-periodic — its smoothed
        # autocorrelation has NEGATIVE lobes near the feature scale, so the
        # finest-level photometric gradient at zero flow can point away
        # from the true shift). "blobs": sparse Gaussian blobs on a smooth
        # gradient background — autocorrelation positive and monotone past
        # max_shift at every pyramid level, the optimizable regime for the
        # unsupervised objective.
        self._style = style
        # blob count controls how much of the image carries photometric
        # signal: with few blobs most pixels sit on the smooth background
        # where the aperture problem makes many flows reconstruct equally
        # well (observed: 12k-step runs settle at AEE ~3.9, WORSE than
        # the 3.45 zero-flow baseline, while the loss keeps improving —
        # artifacts/synthetic_fit_long.jsonl). Densify for fitting runs.
        self._n_blobs = n_blobs

    def _sample(self, seed: int, shift_bound: float | None = None
                ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """shift_bound overrides the DISPLACEMENT range only (curriculum
        training, tools/synthetic_fit.py); canvas statistics (blob sigma)
        always follow the constructor's max_shift so the train images
        stay distributionally identical to eval. Integer-shift styles
        quantize the bound to whole pixels (rounded)."""
        rng = np.random.RandomState(seed)
        h, w = self.cfg.image_size
        if self._style == "affine":
            return self._sample_affine(rng, h, w, shift_bound)
        if self._style == "blobs":
            img = self._blob_canvas(rng, h + 16, w + 16)
        else:
            fs = self._feature_scale
            base = rng.rand(h // fs + 2, w // fs + 2, 3).astype(np.float32) * 255.0
            img = cv2.resize(base, (w + 16, h + 16),
                             interpolation=cv2.INTER_CUBIC)
        bound = int(round(self._max_shift if shift_bound is None
                          else shift_bound))
        u, v = rng.randint(-bound, bound + 1, 2)
        src = img[8 : 8 + h, 8 : 8 + w]
        tgt = img[8 + v : 8 + v + h, 8 + u : 8 + u + w]
        # tgt[y, x] == src[y+v, x+u], so source content at p sits at
        # p + (-u, -v) in the target: GT flow (and the minimizer of the
        # backward-warp loss, recon[p] = tgt[p + f] == src[p]) is (-u, -v).
        flow = np.broadcast_to(
            np.asarray([-u, -v], np.float32), (h, w, 2)
        ).copy()
        return src, tgt, flow

    def _sample_affine(self, rng, h: int, w: int,
                       shift_bound: float | None = None):
        """Spatially varying exact-GT pair. GT field g = affine(p - c) + t,
        rescaled so max |g| <= max_shift (or the curriculum's shift_bound
        override — displacement only, canvas untouched). Construction: the
        TARGET is the blob canvas; the SOURCE is the exact bilinear
        backward warp of the target by g (cv2.remap) — i.e.
        src[p] = tgt[p + g(p)] by construction, which is precisely what
        the photometric loss's reconstruction computes, so its minimizer
        is g and AEE-vs-g is an exact learning metric (same convention as
        the shift styles: tgt[p + flow] == src[p])."""
        bound = self._max_shift if shift_bound is None else shift_bound
        tgt = self._blob_canvas(rng, h, w)
        yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
        cy, cx = rng.rand(2) * [h - 1, w - 1]
        # rotation + log-scale + shear, each small; plus translation
        ang = (rng.rand() - 0.5) * 0.2
        scale = 1.0 + (rng.rand() - 0.5) * 0.1
        shear = (rng.rand() - 0.5) * 0.1
        a = np.asarray([[np.cos(ang), -np.sin(ang)],
                        [np.sin(ang), np.cos(ang)]], np.float32)
        a = a @ np.asarray([[scale, shear], [0.0, 1.0 / scale]], np.float32)
        a -= np.eye(2, dtype=np.float32)
        tu, tv = (rng.rand(2) * 2 - 1) * bound * 0.5
        gu = a[0, 0] * (xx - cx) + a[0, 1] * (yy - cy) + tu
        gv = a[1, 0] * (xx - cx) + a[1, 1] * (yy - cy) + tv
        mag = float(np.sqrt(gu**2 + gv**2).max())
        if mag > bound:
            gu *= bound / mag
            gv *= bound / mag
        gu = gu.astype(np.float32)  # tu/tv are python floats -> f64 maps
        gv = gv.astype(np.float32)
        src = cv2.remap(tgt, xx + gu, yy + gv, cv2.INTER_LINEAR,
                        borderMode=cv2.BORDER_REPLICATE)
        flow = np.stack([gu, gv], axis=-1)
        return src.astype(np.float32), tgt, flow

    def _blob_canvas(self, rng, ch: int, cw: int) -> np.ndarray:
        """Smooth linear-gradient background + MULTI-OCTAVE Gaussian blobs:
        sigmas log-spaced from ~max_shift up to ~1/3 of the canvas, so the
        image has structure at every pyramid scale — the property natural
        images (1/f spectra) have and that coarse-to-fine estimation
        depends on. Single-octave blobs (sigma ~ max_shift only, the
        pre-r04 canvas) are invisible once downsampled 2-3 levels, which
        left the coarse pyramid losses featureless and made shifts beyond
        the finest levels' photometric basin unlearnable (DESIGN.md r04
        item 6/7)."""
        yy, xx = np.mgrid[0:ch, 0:cw].astype(np.float32)
        gdir = rng.rand(2) * 2 - 1
        bg = 60.0 + 60.0 * (gdir[0] * yy / ch + gdir[1] * xx / cw + 1.0)
        img = np.repeat(bg[..., None], 3, axis=-1)
        s_lo = max(self._max_shift, 3.0)
        s_hi = max(min(ch, cw) / 3.0, s_lo + 1.0)
        for _ in range(self._n_blobs):
            cy, cx = rng.rand(2) * [ch - 1, cw - 1]
            color = rng.rand(3) * 200.0 - 100.0
            # log-uniform sigma across the octaves; big blobs get muted
            # amplitude (like natural 1/f spectra) so small structure
            # stays visible on top of them
            s = float(np.exp(rng.uniform(np.log(s_lo), np.log(s_hi))))
            amp = (s_lo / s) ** 0.5
            blob = np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * s * s))
            img += blob[..., None] * color * amp
        return np.clip(img, 0.0, 255.0).astype(np.float32)

    def _batch(self, seeds, shift_bound: float | None = None) -> dict:
        srcs, tgts, flows = zip(*(self._sample(int(s), shift_bound)
                                  for s in seeds))
        t = self.cfg.time_step
        out = {
            "source": np.stack(srcs),
            "target": np.stack(tgts),
            "flow": np.stack(flows),
            "label": np.asarray([int(s) % 101 for s in seeds], np.int32),
        }
        if t > 2:  # volume mode: repeat the pair into a T-frame volume
            vol = [out["source"], out["target"]] * ((t + 1) // 2)
            out["volume"] = np.concatenate(vol[:t], axis=-1)
            out["flow"] = np.concatenate([out["flow"]] * (t - 1), axis=-1)
        return out

    def sample_train(self, batch_size, iteration=None, rng=None,
                     max_shift: float | None = None):
        """max_shift overrides the TRAIN displacement range only (shift
        curriculum); canvases and the val split are unaffected."""
        if iteration is not None:
            seeds = [(iteration * batch_size + k) % self.num_train for k in range(batch_size)]
        else:
            rng = rng or np.random
            seeds = rng.randint(0, self.num_train, batch_size)
        return self._batch(seeds, shift_bound=max_shift)

    def sample_val(self, batch_size, batch_id):
        seeds = [self.num_train + (batch_id * batch_size + k) % self.num_val
                 for k in range(batch_size)]
        return self._batch(seeds)

    def cache_stats(self) -> dict:
        """Procedural data decodes nothing; a zeroed record keeps the
        observability schema uniform across datasets."""
        return {"hits": 0, "misses": 0, "evictions": 0, "bytes": 0,
                "entries": 0}


def build_dataset(cfg: DataConfig) -> Dataset:
    builders = {
        "flyingchairs": FlyingChairsData,
        "sintel": SintelData,
        "ucf101": UCF101Data,
        "synthetic": SyntheticData,
    }
    if cfg.dataset not in builders:
        raise KeyError(f"unknown dataset {cfg.dataset!r}; available: {sorted(builders)}")
    return builders[cfg.dataset](cfg)
