"""Async double-buffered host->device prefetcher.

Replaces the reference's synchronous per-step disk->numpy->feed_dict path
(`sintelTrain.py:189-195`, SURVEY.md §3.1 hot loop): a background thread
decodes/assembles the next batches while the device runs the current step,
and batches are placed on device (optionally with a NamedSharding) ahead of
use so the train step never waits on host IO.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator

import jax


class Prefetcher:
    """Wraps a batch-producing callable into a prefetching iterator.

    next_batch: () -> dict[str, np.ndarray] (host numpy)
    sharding: optional jax.sharding.Sharding applied via device_put.
    """

    def __init__(
        self,
        next_batch: Callable[[], dict],
        depth: int = 2,
        sharding: jax.sharding.Sharding | None = None,
    ):
        self._next = next_batch
        self._sharding = sharding
        self._q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._exc: BaseException | None = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                batch = self._next()
                if self._sharding is not None:
                    # multi-process: producer yields this host's local rows
                    # and the global array is assembled shard-wise
                    from ..parallel.mesh import put_global

                    batch = put_global(batch, self._sharding)
                while not self._stop.is_set():
                    try:
                        self._q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 - surfaced on get()
            self._exc = e

    def get(self) -> dict:
        while True:
            if self._exc is not None:
                raise self._exc
            try:
                return self._q.get(timeout=0.1)
            except queue.Empty:
                if not self._thread.is_alive() and self._exc is None:
                    raise RuntimeError("prefetch thread died without error")

    def __iter__(self) -> Iterator[dict]:
        while True:
            yield self.get()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
