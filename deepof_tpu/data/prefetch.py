"""Async double-buffered host->device prefetcher.

Replaces the reference's synchronous per-step disk->numpy->feed_dict path
(`sintelTrain.py:189-195`, SURVEY.md §3.1 hot loop): a background thread
decodes/assembles the next batches while the device runs the current step,
and batches are placed on device (optionally with a NamedSharding) ahead of
use so the train step never waits on host IO.

With `stage=True` the producer thread additionally *blocks on transfer
completion* (`jax.block_until_ready`): the next super-batch is fully
resident in device memory while the current scan executes, so dispatching
the next call never overlaps its own input transfer with its compute
warm-up. The wait happens off the critical path (background thread), and
its wall time is reported to the StepTimer as the `put` phase — one of
the four dispatch-timeline phases (DESIGN.md "Execution layer").
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator

import jax

from ..obs import trace as obs_trace


class Prefetcher:
    """Wraps a batch-producing callable into a prefetching iterator.

    next_batch: () -> dict[str, np.ndarray] (host numpy)
    sharding: optional jax.sharding.Sharding applied via device_put.
    stage: block the producer thread until the device transfer completes
        (guarantees residency; only meaningful off the main thread).
    phase_cb: optional (name, seconds) sink for the `put` phase time
        (StepTimer.phase).
    """

    def __init__(
        self,
        next_batch: Callable[[], dict],
        depth: int = 2,
        sharding: jax.sharding.Sharding | None = None,
        stage: bool = False,
        phase_cb: Callable[[str, float], None] | None = None,
    ):
        self._next = next_batch
        self._sharding = sharding
        self._stage = stage
        self._phase_cb = phase_cb
        self._q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._exc: BaseException | None = None
        self._max_depth = 0  # peak staged-batch count (GIL-atomic update)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="prefetch")
        self._thread.start()

    def _place(self, batch: dict) -> dict:
        t0 = time.perf_counter()
        with obs_trace.span("put"):
            if self._sharding is not None:
                # multi-process: producer yields this host's local rows
                # and the global array is assembled shard-wise
                from ..parallel.mesh import put_global

                batch = put_global(batch, self._sharding)
            elif self._stage:
                batch = jax.device_put(batch)
            if self._stage:
                jax.block_until_ready(batch)
        if self._phase_cb is not None:
            self._phase_cb("put", time.perf_counter() - t0)
        return batch

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                batch = self._place(self._next())
                while not self._stop.is_set():
                    try:
                        self._q.put(batch, timeout=0.1)
                        self._max_depth = max(self._max_depth,
                                              self._q.qsize())
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 - surfaced on get()
            self._exc = e

    def get(self) -> dict:
        while True:
            if self._exc is not None:
                raise self._exc
            try:
                return self._q.get(timeout=0.1)
            except queue.Empty:
                if not self._thread.is_alive() and self._exc is None:
                    raise RuntimeError("prefetch thread died without error")

    def __iter__(self) -> Iterator[dict]:
        while True:
            yield self.get()

    def stats(self) -> dict:
        """Staging-queue observability: current and peak staged depth.
        A persistently empty staging queue while the device consumes
        points the bottleneck at the producer side (the InputPipeline's
        own stats say whether assembly or staging is the cause)."""
        return {"staged_depth": self._q.qsize(),
                "max_staged_depth": self._max_depth}

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
