"""Host-side data pipelines (L1).

Loaders re-implement the reference's dataset semantics (SURVEY.md §2.5) —
FlyingChairs ppm/flo pairs with the official split file, Sintel T-frame
sliding-window volumes, UCF-101 class-balanced pair sampling — plus a
synthetic dataset for tests/benchmarks, behind one `Dataset` protocol, with
a multi-worker batch-assembly pipeline with deterministic ordering
(`pipeline.py`), and an async double-buffered prefetcher replacing the
reference's synchronous per-step cv2 reads (`sintelTrain.py:190`).
"""

from .augmentation import (
    apply_geo,
    augment_batch,
    identity_geo_params,
    make_augment_fn,
    photometric_augment,
    sample_geo_params,
)
from .datasets import (
    Dataset,
    FlyingChairsData,
    SintelData,
    SyntheticData,
    UCF101Data,
    build_dataset,
)
from .mixture import MixtureDataset, build_mixture
from .pipeline import InputPipeline, derive_batch_rng
from .prefetch import Prefetcher

__all__ = [
    "apply_geo",
    "augment_batch",
    "identity_geo_params",
    "make_augment_fn",
    "photometric_augment",
    "sample_geo_params",
    "Dataset",
    "FlyingChairsData",
    "SintelData",
    "SyntheticData",
    "UCF101Data",
    "build_dataset",
    "MixtureDataset",
    "build_mixture",
    "InputPipeline",
    "derive_batch_rng",
    "Prefetcher",
]
