"""Spatial context parallelism + temporal pair parallelism.

The reference is single-GPU (`tf.device('/gpu:0')`, `flyingChairsTrain.py:99`)
with no parallelism of any kind; these are the TPU-native long-context
equivalents (SURVEY.md §5.7):

  - **Spatial CP** ("spatial" mesh axis): image batches are sharded over H
    with `P(("data",), "spatial")`. Convolutions under `jit` are then
    spatially partitioned by GSPMD, which inserts the boundary halo
    exchanges itself — the idiomatic formulation of the ring/halo pattern
    (annotate shardings, let XLA place collectives on ICI). This is what
    makes high-resolution flow (e.g. Sintel 436x1024 and beyond) scale
    past one chip's HBM.

  - **Explicit halo exchange** (`halo_exchange`): the `lax.ppermute`
    neighbor ring, for custom ops inside `shard_map` where GSPMD cannot
    infer the halo (e.g. windowed ops with data-dependent reach).

  - **Temporal pair parallelism** ("time" mesh axis): the Sintel T-frame
    volume loss warps T-1 consecutive pairs independently
    (`sintelWrapFlow.py:539-577` semantics); folding the pair axis into
    batch and sharding it over ("data", "time") spreads the warp/
    Charbonnier work across the mesh.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Trace-time mesh stack: `jax.sharding.get_abstract_mesh()` is EMPTY inside
# plain `jax.jit` tracing (even with in_shardings), so sharding constraints
# need the concrete mesh threaded to them explicitly. The step builders wrap
# the loss computation in `mesh_context(mesh)`; ops deep in the call tree
# (e.g. the folded pair axis inside `backward_warp_volume`) read it via
# `current_mesh()` at trace time.
_MESH_STACK: list[Mesh] = []


@contextlib.contextmanager
def mesh_context(mesh: Mesh | None):
    if mesh is None:
        yield
        return
    _MESH_STACK.append(mesh)
    try:
        yield
    finally:
        _MESH_STACK.pop()


def current_mesh() -> Mesh | None:
    return _MESH_STACK[-1] if _MESH_STACK else None


def image_sharding(mesh: Mesh) -> NamedSharding:
    """(B, H, W, C) batches: batch over "data", height over "spatial"."""
    return NamedSharding(mesh, P("data", "spatial"))


def batch_spec(mesh: Mesh) -> P:
    """PartitionSpec for image batches on this mesh (H sharded only when
    the spatial axis is populated)."""
    if mesh.shape.get("spatial", 1) > 1:
        return P("data", "spatial")
    return P("data")


# Spatial CP gradient-safety contract: every pyramid level must keep
# >= MIN_ROWS_PER_SHARD rows per spatial shard. Root cause (minimal repro:
# tools/halo_grad_repro.py): when a stride-2 SAME conv chain reaches a
# level with FEWER than 2 rows per shard, XLA's SPMD partitioner emits a
# degenerate backward halo exchange that mis-scales the input cotangent —
# every upstream conv's gradient comes back multiplied by a constant (x4
# at spatial=2 with a 1-row/shard level; x2 in some sub-row collapse
# regimes; exact factor depends on GSPMD's level-by-level partitioning
# choices) while downstream layers stay correct. At >= 2 rows per shard
# the backward is exact in every configuration tested (spatial 2 and 4,
# depths 2-5). The guard is therefore derived per model from its real
# downsample factor, not a blanket constant.
MIN_ROWS_PER_SHARD = 2


def min_spatial_height(max_downsample: int, spatial: int) -> int:
    """Smallest input H for which spatial CP is gradient-safe for a model
    whose deepest level is H / max_downsample: that level must keep
    MIN_ROWS_PER_SHARD rows on each of `spatial` shards."""
    return MIN_ROWS_PER_SHARD * max_downsample * spatial


def spatial_cp_active(h: int, max_downsample: int, spatial: int) -> bool:
    """True iff sharding H over `spatial` is gradient-safe for a model
    downsampling by `max_downsample` (stride-2 SAME chain: each level is
    ceil(previous/2)).

    Probed-exact configurations (tools/halo_grad_repro.py) all satisfy,
    probed-broken all violate: (a) the deepest level keeps >= 2 average
    rows per shard, and (b) GSPMD's ceil-partition of the deepest level
    leaves no shard with zero real rows (e.g. H=520 at downsample 64,
    spatial=4: deepest ceil-chain gives 9 rows -> shards 3,3,3,0 — the
    padded-empty shard re-enters the degenerate-halo regime and is
    refused even though 9 >= 2*4 holds on average).
    """
    if h % spatial:
        return False
    d = h
    for _ in range(max(max_downsample.bit_length() - 1, 0)):
        d = -(-d // 2)
    if d < MIN_ROWS_PER_SHARD * spatial:
        return False
    return d - (spatial - 1) * (-(-d // spatial)) > 0


def constrain_batch(batch: dict, mesh: Mesh | None = None,
                    max_downsample: int = 64) -> dict:
    """Apply the spatial-CP sharding constraint to every image-like leaf
    (rank >= 4: (B, H, W, C) images, volumes, GT flows) of a batch dict.

    With a mesh whose "spatial" axis is populated, GSPMD reshards H over it
    and spatially partitions all downstream convolutions (halo exchanges
    inserted by the compiler). No-op otherwise, when H does not divide, or
    when H is below `min_spatial_height` for the model's downsample factor
    (the gradient-safety fence above — and at low res spatial CP would
    only lose to pure DP anyway).
    """
    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None or mesh.shape.get("spatial", 1) <= 1:
        return batch
    spatial = mesh.shape["spatial"]
    sharding = NamedSharding(mesh, P(("data",), "spatial"))

    def put(v):
        # Uneven deep levels are fine (probed: 5 rows over 2 shards, 10
        # over 4 with a 1-real-row last shard — all exact); the precise
        # gradient-safety gate lives in `spatial_cp_active`.
        if (getattr(v, "ndim", 0) >= 4
                and spatial_cp_active(v.shape[1], max_downsample, spatial)):
            return lax.with_sharding_constraint(v, sharding)
        return v

    return {k: put(v) for k, v in batch.items()}


def pair_axis_constraint(x: jnp.ndarray) -> jnp.ndarray:
    """Constrain a (B*(T-1), H, W, C) folded pair-axis array to shard over
    ("data", "time") so the T-1 per-pair warps run pair-parallel.

    No-op outside a `mesh_context` or when the time axis is unpopulated or
    does not divide the folded axis.
    """
    mesh = current_mesh()
    if mesh is None or mesh.shape.get("time", 1) <= 1:
        return x
    shards = mesh.shape["time"] * mesh.shape.get("data", 1)
    if x.shape[0] % shards:
        return x
    return lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(("data", "time"),)))


def halo_exchange(x: jnp.ndarray, halo: int, axis_name: str = "spatial",
                  axis: int = 0) -> jnp.ndarray:
    """Pad a per-shard block with `halo` rows from each ring neighbor.

    Inside `shard_map` over `axis_name`: sends this shard's boundary rows
    to both neighbors via two `lax.ppermute` rings (the ICI-neighbor
    pattern) and concatenates the received halos. Edge shards receive
    zeros (clip-at-border ops should clamp indices instead of reading the
    zero halo).

    x: (..., H_shard, ...) -> (..., H_shard + 2*halo, ...) along `axis`.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)

    def take(arr, sl):
        ix = [slice(None)] * arr.ndim
        ix[axis] = sl
        return arr[tuple(ix)]

    top = take(x, slice(0, halo))  # first rows -> previous neighbor
    bot = take(x, slice(x.shape[axis] - halo, x.shape[axis]))

    fwd = [(i, (i + 1) % n) for i in range(n)]  # bottom rows travel down
    bwd = [(i, (i - 1) % n) for i in range(n)]
    from_prev = lax.ppermute(bot, axis_name, fwd)  # neighbor above's bottom
    from_next = lax.ppermute(top, axis_name, bwd)  # neighbor below's top

    zero = jnp.zeros_like(top)
    from_prev = jnp.where(idx == 0, zero, from_prev)  # ring wrap -> zeros
    from_next = jnp.where(idx == n - 1, zero, from_next)
    return jnp.concatenate([from_prev, x, from_next], axis=axis)
