"""Device-mesh construction and parallelism primitives.

The reference is single-process, single-GPU (`flyingChairsTrain.py:99`,
SURVEY.md §2.7) — everything here is new, TPU-native capability: named
meshes over ICI, sharding helpers for pjit data parallelism, and spatial
context-parallel convolution/warp with halo exchange.
"""

from .mesh import batch_sharding, build_mesh, local_mesh, replicated_sharding

__all__ = ["build_mesh", "local_mesh", "batch_sharding", "replicated_sharding"]
