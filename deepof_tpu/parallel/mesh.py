"""Named device meshes for pjit sharding.

Axes (SURVEY.md §5.8 build plan):
  - "data":    batch/data parallelism — gradients all-reduce over ICI;
  - "spatial": context parallelism over image height (halo exchange);
  - "time":    Sintel temporal pair parallelism (T-1 independent pair
               losses).

Multi-host: call `jax.distributed.initialize` before `build_mesh`; the mesh
uses the global device list, so the "data" axis spans hosts over DCN while
"spatial"/"time" should stay intra-slice (ICI).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.config import MeshConfig

AXES = ("data", "spatial", "time")


def build_mesh(cfg: MeshConfig | None = None, devices=None) -> Mesh:
    """Build a (data, spatial, time) mesh over `devices` (default: all).

    cfg.data == -1 means "all remaining devices" after spatial/time are
    allocated.
    """
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    spatial, time = max(cfg.spatial, 1), max(cfg.time, 1)
    if n % (spatial * time):
        raise ValueError(
            f"{n} devices not divisible by spatial*time={spatial * time}")
    data = n // (spatial * time) if cfg.data == -1 else cfg.data
    if data * spatial * time != n:
        raise ValueError(
            f"mesh {data}x{spatial}x{time} != {n} devices")
    arr = np.asarray(devices).reshape(data, spatial, time)
    return Mesh(arr, AXES)


def local_mesh(n: int | None = None) -> Mesh:
    """Pure-data-parallel mesh over the first n devices (test helper)."""
    devices = jax.devices()[: n or len(jax.devices())]
    return build_mesh(MeshConfig(), devices)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis over "data"; replicate the rest."""
    return NamedSharding(mesh, P("data"))


def stacked_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for K stacked batches [K, B, ...] (steps_per_call > 1):
    the scan axis is replicated, the batch axis sharded over "data"."""
    return NamedSharding(mesh, P(None, "data"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def process_data_coords(mesh: Mesh) -> list[int]:
    """Sorted "data"-axis coordinates with devices addressable from this
    process (single-host: all of them)."""
    local = set(jax.local_devices())
    arr = mesh.devices
    return sorted(d for d in range(arr.shape[0])
                  if any(dev in local for dev in arr[d].flat))


def local_batch_rows(mesh: Mesh, global_batch: int) -> tuple[int, list[int]]:
    """(local_batch_size, owned global row indices) for this process under
    `batch_sharding`: P("data") places contiguous row blocks in data-axis
    coordinate order, so process-local rows are the blocks of its coords.

    When a data coordinate's devices span several processes those processes
    are *replicas* of that batch shard and must supply identical data
    (jax's make_array contract) — `process_seed` makes their host rng
    streams identical. The one unsupported layout is a process owning
    several coords of which only some span processes (rows would differ
    between the replica peers): rejected explicitly.
    """
    data = mesh.shape["data"]
    if global_batch % data:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"data axis {data}")
    per = global_batch // data
    coords = process_data_coords(mesh)
    local = set(jax.local_devices())
    spans = [d for d in coords
             if any(dev not in local for dev in mesh.devices[d].flat)]
    if spans and len(coords) > 1:
        raise ValueError(
            f"data coords {spans} span processes while this process owns "
            f"{coords}: replica peers would load different rows. Pick a "
            "mesh where spatial*time divides the per-host device count")
    rows = [r for d in coords for r in range(d * per, (d + 1) * per)]
    return len(rows), rows


def elastic_stream_seed(seed: int, host_index: int, num_hosts: int,
                        generation: int, start_step: int) -> np.ndarray:
    """Base seed of one elastic trainer host's data-sampling stream
    (train/elastic.py; the elastic counterpart of `data_stream_seed`).

    The world re-forms when a host is lost: the survivors respawn with a
    new ``num_hosts`` and a bumped ``generation``, and every host's
    stream must (a) stay a pure function of the config — the whole run
    reproduces from (seed, fault schedule) alone — and (b) decorrelate
    from every other (host, world-size, generation) stream, so no
    survivor replays draws the old world already trained on and the
    post-reform shards are disjoint by construction. All five components
    are folded in losslessly as uint32 words (MT19937 ``init_by_array``
    via `data/pipeline.py::derive_batch_rng`, which derives one sibling
    rng per batch index from this base): the seed as a 64-bit word pair,
    then host, world size, generation, and the resume step — any
    differing component yields an unrelated stream. The layout is also
    longer than `data_stream_seed`'s two words, so an elastic host never
    collides with a plain single-host run at the same seed.

    ``host_index`` may EXCEED ``num_hosts``: survivors keep their
    original identity across re-forms (host 2 of original 3 stays
    "host 2" in the shrunken 2-host world — renumbering would let a
    host-indexed fault schedule re-fire on an innocent neighbor), so
    the index is an identity, not a coordinate.
    """
    if int(host_index) < 0 or int(num_hosts) < 1:
        raise ValueError(f"invalid elastic identity: host_index "
                         f"{host_index}, num_hosts {num_hosts}")
    s = int(seed)
    return np.array([s & 0xFFFFFFFF, (s >> 32) & 0xFFFFFFFF,
                     int(host_index), int(num_hosts), int(generation),
                     int(start_step)], dtype=np.uint32)


def process_seed(mesh: Mesh, seed: int) -> int:
    """Host-sampling seed: decorrelated across data shards, *identical*
    for processes that are replicas of the same data coordinate (their
    devices share coords, so they must feed identical batches)."""
    coords = process_data_coords(mesh)
    return seed + (min(coords) if coords else 0)


def put_global(batch: dict, sharding: NamedSharding) -> dict:
    """Place a host-local numpy batch under `sharding`.

    Single-process: plain device_put. Multi-process (hosts spanning the
    mesh over DCN): each process contributes only its local rows
    (`local_batch_rows`) and the global array is assembled without any
    cross-host copy of the full batch — this is what lets each host load
    1/num_hosts of the data (SURVEY.md §5.8). Leaves that are already
    device-resident jax.Arrays (on-device augmentation output) are
    split into per-device shards and moved device-to-device — no
    host readback on the hot input path.
    """
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)

    def place(x):
        if isinstance(x, jax.Array):
            return _assemble_from_local_array(x, sharding)
        return jax.make_array_from_process_local_data(sharding, np.asarray(x))

    return jax.tree_util.tree_map(place, batch)


def _assemble_from_local_array(x: jax.Array, sharding: NamedSharding):
    """Build the global batch array from this process's already-on-device
    local-rows array without a device->host roundtrip."""
    mesh = sharding.mesh
    gshape = (_global_rows(mesh, x.shape[0]),) + x.shape[1:]
    _, rows = local_batch_rows(mesh, gshape[0])
    row_pos = {r: i for i, r in enumerate(rows)}
    shards = []
    for dev, idx in sharding.addressable_devices_indices_map(gshape).items():
        rsl = idx[0] if idx else slice(None)
        start, stop = rsl.start or 0, rsl.stop if rsl.stop is not None else gshape[0]
        lsl = slice(row_pos[start], row_pos[stop - 1] + 1)
        shards.append(jax.device_put(x[lsl], dev))
    return jax.make_array_from_single_device_arrays(gshape, sharding, shards)


def _global_rows(mesh: Mesh, local_rows: int) -> int:
    """Global batch size implied by this process's local row count."""
    n_coords = len(process_data_coords(mesh))
    if local_rows % max(n_coords, 1):
        raise ValueError(f"local batch {local_rows} not divisible by "
                         f"owned data coords {n_coords}")
    return (local_rows // max(n_coords, 1)) * mesh.shape["data"]


def put_global_from_full(batch: dict, mesh: Mesh,
                         sharding: NamedSharding) -> dict:
    """Like `put_global`, but every process holds the SAME full batch
    (deterministic val loading): each contributes only its own rows."""
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)

    def place(x):
        x = np.asarray(x)
        _, rows = local_batch_rows(mesh, x.shape[0])
        return jax.make_array_from_process_local_data(sharding, x[rows])

    return jax.tree_util.tree_map(place, batch)
