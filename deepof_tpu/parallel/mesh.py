"""Named device meshes for pjit sharding.

Axes (SURVEY.md §5.8 build plan):
  - "data":    batch/data parallelism — gradients all-reduce over ICI;
  - "spatial": context parallelism over image height (halo exchange);
  - "time":    Sintel temporal pair parallelism (T-1 independent pair
               losses).

Multi-host: call `jax.distributed.initialize` before `build_mesh`; the mesh
uses the global device list, so the "data" axis spans hosts over DCN while
"spatial"/"time" should stay intra-slice (ICI).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.config import MeshConfig

AXES = ("data", "spatial", "time")


def build_mesh(cfg: MeshConfig | None = None, devices=None) -> Mesh:
    """Build a (data, spatial, time) mesh over `devices` (default: all).

    cfg.data == -1 means "all remaining devices" after spatial/time are
    allocated.
    """
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    spatial, time = max(cfg.spatial, 1), max(cfg.time, 1)
    if n % (spatial * time):
        raise ValueError(
            f"{n} devices not divisible by spatial*time={spatial * time}")
    data = n // (spatial * time) if cfg.data == -1 else cfg.data
    if data * spatial * time != n:
        raise ValueError(
            f"mesh {data}x{spatial}x{time} != {n} devices")
    arr = np.asarray(devices).reshape(data, spatial, time)
    return Mesh(arr, AXES)


def local_mesh(n: int | None = None) -> Mesh:
    """Pure-data-parallel mesh over the first n devices (test helper)."""
    devices = jax.devices()[: n or len(jax.devices())]
    return build_mesh(MeshConfig(), devices)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis over "data"; replicate the rest."""
    return NamedSharding(mesh, P("data"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
