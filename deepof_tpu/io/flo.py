"""Middlebury `.flo` optical-flow file IO.

Format (behavior parity with reference `utils.py:4-52`):
  - 4-byte float32 magic tag 202021.25 ("PIEH" when read as ASCII)
  - int32 width, int32 height (little endian)
  - h*w*2 float32 values, interleaved (u, v) row-major.

The reference's `writeFlow` references an undefined ``TAG_CHAR``
(`utils.py:44`) and is therefore dead code; this module provides a working
round-trippable writer.
"""

from __future__ import annotations

import os

import numpy as np

FLO_TAG = 202021.25
_TAG_BYTES = np.float32(FLO_TAG).tobytes()


def read_flo(path: str | os.PathLike) -> np.ndarray:
    """Read a `.flo` file -> float32 array of shape (H, W, 2), channels (u, v).

    Raises ValueError on a bad magic tag (same sanity check the reference
    performs at `utils.py:12-14`).
    """
    with open(path, "rb") as f:
        tag = np.frombuffer(f.read(4), np.float32)
        if tag.size != 1 or tag[0] != np.float32(FLO_TAG):
            raise ValueError(f"{path}: invalid .flo magic tag {tag!r}")
        w, h = np.frombuffer(f.read(8), np.int32)
        if w <= 0 or h <= 0 or w > 99999 or h > 99999:
            raise ValueError(f"{path}: implausible dims {w}x{h}")
        data = np.frombuffer(f.read(int(w) * int(h) * 2 * 4), np.float32)
        if data.size != w * h * 2:
            raise ValueError(f"{path}: truncated flow data")
        return data.reshape(int(h), int(w), 2).copy()


def flo_bytes(flow: np.ndarray) -> bytes:
    """(H, W, 2) float32 flow -> Middlebury `.flo` bytes (the single
    owner of the serialization — `write_flo` and the serving HTTP
    response body both use it)."""
    flow = np.ascontiguousarray(flow, dtype=np.float32)
    if flow.ndim != 3 or flow.shape[-1] != 2:
        raise ValueError(f"flow must be (H, W, 2), got {flow.shape}")
    h, w = flow.shape[:2]
    return _TAG_BYTES + np.array([w, h], np.int32).tobytes() + flow.tobytes()


def write_flo(path: str | os.PathLike, flow: np.ndarray) -> None:
    """Write (H, W, 2) float32 flow to Middlebury `.flo`."""
    with open(path, "wb") as f:
        f.write(flo_bytes(flow))
