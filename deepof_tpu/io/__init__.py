from .flo import read_flo, write_flo, FLO_TAG  # noqa: F401
