"""Unsupervised photometric warp losses.

Pure-function re-design of the reference's `loss_interp` family, which is
duplicated with variations across five files (SURVEY.md §2.4):

  - canonical 2-frame (`flyingChairsWrapFlow.py:752-876`)
  - UCF variant: border mask also applied to the smoothness term
    (`ucf101wrapFlow.py:471-472`)
  - depthwise/gen-1 variant: both-direction gradients per flow component,
    optional Sobel edge-aware weighting (`version1/model/warpflow.py:4-173`,
    `flyingChairsWrapFlow_vgg.py:135-317`)
  - multi-frame volume variant (`sintelWrapFlow.py:492-630`)

All variants here are vectorized jnp (no python loops over batch/channels)
and driven by `core.config.LossConfig`. Loss dict keys mirror the reference:
total / Charbonnier_reconstruct / U_loss / V_loss.

Replicated behavioral details (deliberate, for numeric parity):
  - the Charbonnier normalizer is the count of border-mask-interior *image*
    elements (B * interior * C), reused for the smoothness normalizer
    (canonical) or scaled by 2/3 (depthwise variant);
  - masks multiply the *gradient* before the Charbonnier power, so masked
    pixels still contribute (eps^2)^alpha_s (a constant offset) — except in
    the depthwise variant where the border mask multiplies after;
  - photometric diff is scaled by 255 before the Charbonnier power.
"""

from __future__ import annotations

import math
from typing import Any

import jax.numpy as jnp

from ..core.config import LossConfig
from ..ops.warp import backward_warp, backward_warp_volume
from ..ops.smoothness import (
    forward_diff_x,
    forward_diff_y,
    second_diff_x,
    second_diff_y,
    sobel_gradients,
    to_grayscale,
)

LossDict = dict[str, Any]


def charbonnier(x: jnp.ndarray, eps: float, alpha: float) -> jnp.ndarray:
    """(x^2 + eps^2)^alpha — the generalized Charbonnier penalty."""
    return jnp.power(jnp.square(x) + eps * eps, alpha)


def border_mask(h: int, w: int, ratio: float = 0.1,
                min_width: int = 0) -> jnp.ndarray:
    """(H, W) float mask: 0 in a ceil(ratio*H)-wide border, 1 inside.

    The border width derives from H only ("shortestDim",
    `flyingChairsWrapFlow.py:763-765`). min_width widens the border for
    penalties whose neighborhoods exceed it (census windows at coarse
    levels).
    """
    bw = max(int(math.ceil(h * ratio)), min_width)
    m = jnp.zeros((h, w))
    return m.at[bw : h - bw, bw : w - bw].set(1.0)


def smoothness_mask_x(h: int, w: int) -> jnp.ndarray:
    """(H, W) mask zeroing the last *column* (x-gradient invalid there)."""
    return jnp.ones((h, w)).at[:, -1].set(0.0)


def smoothness_mask_y(h: int, w: int) -> jnp.ndarray:
    """(H, W) mask zeroing the last *row* (y-gradient invalid there)."""
    return jnp.ones((h, w)).at[-1, :].set(0.0)


def _normalized_sobel(inputs: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Shared edge-mask preprocessing: per-sample min-max normalize to
    integer [0, 255], grayscale, Sobel x/y (`version1/model/warpflow.py:
    93-108`, `flyingChairsWrapFlow_vgg.py:226-246`). Returns raw
    (gx, gy), each (B,H,W,1)."""
    mn = jnp.min(inputs, axis=(1, 2, 3), keepdims=True)
    mx = jnp.max(inputs, axis=(1, 2, 3), keepdims=True)
    img = 255.0 * (inputs - mn) / jnp.maximum(mx - mn, 1e-12)
    img = jnp.clip(jnp.floor(img), 0.0, 255.0)
    return sobel_gradients(to_grayscale(img))


def _edge_aware_masks(inputs: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sobel-based smoothness down-weighting near image edges.

    Reference `version1/model/warpflow.py:93-117`: normalized Sobel x/y,
    each normalized by its global max magnitude, mask = 1 - |grad|.
    Returns (mask_x, mask_y), each (B,H,W,1).
    """
    gx, gy = _normalized_sobel(inputs)
    gx = gx / jnp.maximum(jnp.max(jnp.abs(gx)), 1e-12)
    gy = gy / jnp.maximum(jnp.max(jnp.abs(gy)), 1e-12)
    return 1.0 - jnp.abs(gx), 1.0 - jnp.abs(gy)


def _photo_gradient_mask(inputs: jnp.ndarray) -> jnp.ndarray:
    """Per-sample Sobel gradient-magnitude weight for the photometric term.

    Reference `flyingChairsWrapFlow_vgg.py:226-255` (needImageGradients):
    min-max normalize each sample to integer [0, 255], grayscale, Sobel
    x/y, gradient magnitude, then per-sample min-max normalize to [0, 1].
    HIGH at image edges — unlike the smoothness masks (1 - |grad|), this
    *emphasizes* structured pixels in the Charbonnier sum. Returns
    (B, H, W, 1).
    """
    gx, gy = _normalized_sobel(inputs)
    mag = jnp.sqrt(jnp.square(gx) + jnp.square(gy))
    mmn = jnp.min(mag, axis=(1, 2, 3), keepdims=True)
    mmx = jnp.max(mag, axis=(1, 2, 3), keepdims=True)
    return jnp.clip((mag - mmn) / jnp.maximum(mmx - mmn, 1e-12), 0.0, 1.0)


def occlusion_mask(flow_fw: jnp.ndarray, flow_bw: jnp.ndarray,
                   cfg: LossConfig) -> jnp.ndarray:
    """Forward-backward consistency visibility mask (1 = visible).

    flow_fw/flow_bw: (B, h, w, 2) already flow_scale-multiplied. A pixel
    is occluded when the backward flow sampled at its forward-displaced
    position does not cancel the forward flow:
        |f_fw + warp(f_bw, f_fw)|^2 >= occ_alpha*(|f_fw|^2 + |warp(f_bw)|^2)
                                       + occ_beta
    (UnFlow eq. 2 lineage). Returns (B, h, w, 1).
    """
    bw_at_fw = backward_warp(flow_bw, flow_fw, impl=cfg.warp_impl)
    sq = jnp.sum(jnp.square(flow_fw + bw_at_fw), axis=-1, keepdims=True)
    bound = cfg.occ_alpha * (
        jnp.sum(jnp.square(flow_fw), axis=-1, keepdims=True)
        + jnp.sum(jnp.square(bw_at_fw), axis=-1, keepdims=True)
    ) + cfg.occ_beta
    return (sq < bound).astype(flow_fw.dtype)


def _warp_operand(x: jnp.ndarray, cfg: LossConfig) -> jnp.ndarray:
    """Warp-operand dtype policy (loss.gather_dtype): bf16 halves the
    gathered bytes on the fine-level XLA path (an opt-in throughput
    lever); default f32 preserves exact reference numerics. Validated
    here like the module's other enum fields."""
    if cfg.gather_dtype == "bfloat16":
        return x.astype(jnp.bfloat16)
    if cfg.gather_dtype != "float32":
        raise ValueError(
            f"unknown loss.gather_dtype {cfg.gather_dtype!r}; "
            "use 'float32' or 'bfloat16'")
    return x


def _smoothness_diffs(cfg: LossConfig, h: int, w: int):
    """(diff_x, diff_y, mask_x, mask_y) for the configured prior order.

    Order 2 penalizes curvature (affine motion fields are free) and
    invalidates BOTH edge columns/rows of the centered stencil.
    """
    if cfg.smoothness_order == 2:
        mx = (smoothness_mask_x(h, w) * smoothness_mask_x(h, w)[:, ::-1])[None, :, :, None]
        my = (smoothness_mask_y(h, w) * smoothness_mask_y(h, w)[::-1, :])[None, :, :, None]
        return second_diff_x, second_diff_y, mx, my
    if cfg.smoothness_order == 1:
        mx = smoothness_mask_x(h, w)[None, :, :, None]
        my = smoothness_mask_y(h, w)[None, :, :, None]
        return forward_diff_x, forward_diff_y, mx, my
    raise ValueError(f"unknown smoothness_order {cfg.smoothness_order!r}")


def loss_interp(
    flow: jnp.ndarray,
    inputs: jnp.ndarray,
    outputs: jnp.ndarray,
    flow_scale: float,
    cfg: LossConfig,
    smooth_border_mask: bool = False,
    occ_mask: jnp.ndarray | None = None,
) -> tuple[LossDict, jnp.ndarray]:
    """Two-frame photometric + smoothness loss at one pyramid scale.

    flow: (B, h, w, 2) raw head output; inputs/outputs: (B, h, w, C)
    LRN-normalized prev/next frames resized to this scale. occ_mask:
    optional (B, h, w, 1) visibility weights multiplying the photometric
    term (occluded pixels drop out of both the sum and the normalizer).
    Returns (loss dict, reconstructed prev frame).
    """
    b, h, w, c = inputs.shape
    scaled = flow * flow_scale
    # Byte-halving bf16 warp operand iff the gather is byte-bound —
    # perf_probe warpscan answers which; the Pallas path upcasts
    # internally either way (see _warp_operand).
    recon = backward_warp(_warp_operand(outputs, cfg), scaled,
                          impl=cfg.warp_impl).astype(inputs.dtype)
    # needImageGradients (`flyingChairsWrapFlow_vgg.py:226-301`): the same
    # per-sample gradient-magnitude mask weights the photometric term by
    # |grad| and BOTH smoothness terms by 1-|grad| (edges may move freely).
    if cfg.edge_aware_photo and cfg.photometric != "charbonnier":
        raise ValueError(
            "loss.edge_aware_photo pairs only with photometric='charbonnier' "
            f"(got {cfg.photometric!r}); the census branch would silently "
            "skip the photometric weighting")
    gmask = _photo_gradient_mask(inputs) if cfg.edge_aware_photo else None

    bmask = border_mask(h, w, cfg.border_ratio)  # (h, w)
    # guard: at very coarse pyramid levels (h <= 2) the border mask has no
    # interior (the reference never ran levels this small); such a level
    # contributes exactly 0 to photometric AND smoothness terms.
    n_interior = jnp.sum(bmask)
    level_on = (n_interior > 0).astype(inputs.dtype)
    num_valid = jnp.maximum(b * c * n_interior, 1.0)
    if cfg.photometric == "census":
        from ..ops.census import census_distance, census_transform

        # census neighborhoods reach window//2 pixels: widen the mask so
        # edge-replicated descriptor components never enter the loss
        # (at coarse levels ceil(0.1*h) can be narrower than the window)
        cmask = jnp.broadcast_to(
            border_mask(h, w, cfg.border_ratio,
                        min_width=cfg.census_window // 2)[None, :, :, None],
            (b, h, w, 1))
        vis = cmask
        if occ_mask is not None:
            vis = cmask * occ_mask
        dist = census_distance(census_transform(recon, cfg.census_window),
                               census_transform(inputs, cfg.census_window))
        photo = jnp.sum(dist * vis) / jnp.maximum(jnp.sum(vis), 1.0)
        if occ_mask is not None:
            # occluded pixels must not be free (see LossConfig.occ_penalty)
            photo = photo + cfg.occ_penalty * (
                jnp.sum(cmask * (1.0 - occ_mask))
                / jnp.maximum(jnp.sum(cmask), 1.0))
    elif cfg.photometric == "charbonnier":
        pmask = bmask[None, :, :, None]
        if occ_mask is not None:
            pmask = pmask * occ_mask
            photo_norm = jnp.maximum(c * jnp.sum(pmask), 1.0)
        else:
            photo_norm = num_valid
        diff = 255.0 * (recon - inputs)
        ele = charbonnier(diff, cfg.epsilon, cfg.alpha_c) * pmask
        if gmask is not None:
            # normalizer stays numValidPixels — the weight reduces the sum
            # only (`flyingChairsWrapFlow_vgg.py:269-276`)
            ele = ele * gmask
        photo = jnp.sum(ele) / photo_norm
        if occ_mask is not None:
            photo = photo + cfg.occ_penalty * (
                jnp.sum(bmask[None, :, :, None] * (1.0 - occ_mask))
                / jnp.maximum(b * n_interior, 1.0))
    else:
        raise ValueError(f"unknown photometric variant {cfg.photometric!r}")

    sflow = scaled if cfg.smooth_scaled_flow else flow
    diff_x, diff_y, mx, my = _smoothness_diffs(cfg, h, w)

    if cfg.smoothness == "canonical":
        if cfg.edge_aware:
            raise ValueError(
                "loss.edge_aware pairs only with smoothness='depthwise' "
                "(the gen-1 variant it comes from, `version1/model/"
                "warpflow.py:93-157`); the canonical branch would silently "
                "skip the Sobel weighting")
        # x-diff of U masked at last col, y-diff of V masked at last row;
        # optional border mask pre-Charbonnier (UCF variant).
        du = diff_x(sflow[..., 0:1]) * mx
        dv = diff_y(sflow[..., 1:2]) * my
        if smooth_border_mask:
            du = du * bmask[None, :, :, None]
            dv = dv * bmask[None, :, :, None]
        ele_u = charbonnier(du, cfg.epsilon, cfg.alpha_s)
        ele_v = charbonnier(dv, cfg.epsilon, cfg.alpha_s)
        if gmask is not None:
            ele_u = ele_u * (1.0 - gmask)
            ele_v = ele_v * (1.0 - gmask)
        u_loss = jnp.sum(ele_u) / num_valid
        v_loss = jnp.sum(ele_v) / num_valid
    elif cfg.smoothness == "depthwise":
        # both-direction gradients per component; border mask multiplies
        # *after* the Charbonnier power; normalizer is 2/3 of the image one
        # (`version1/model/warpflow.py:133-163`).
        num_valid_flow = num_valid / 3.0 * 2.0
        gx = diff_x(sflow)  # (B,h,w,2): dU/dx, dV/dx
        gy = diff_y(sflow)
        u_delta = jnp.stack([gx[..., 0] * mx[..., 0], gy[..., 0] * my[..., 0]], axis=-1)
        v_delta = jnp.stack([gx[..., 1] * mx[..., 0], gy[..., 1] * my[..., 0]], axis=-1)
        ele_u = charbonnier(u_delta, cfg.epsilon, cfg.alpha_s)
        ele_v = charbonnier(v_delta, cfg.epsilon, cfg.alpha_s)
        if cfg.edge_aware:
            emx, emy = _edge_aware_masks(inputs)
            emask = jnp.concatenate([emx, emy], axis=-1)  # (B,h,w,2)
            ele_u = ele_u * emask
            ele_v = ele_v * emask
        if gmask is not None:
            # vgg-variant pairing: 1 - magnitude mask, identical for the
            # x- and y-gradient channels (`flyingChairsWrapFlow_vgg.py:
            # 259-260,293-301`) — distinct from `edge_aware`'s directional
            # 1-|gx| / 1-|gy| masks
            ele_u = ele_u * (1.0 - gmask)
            ele_v = ele_v * (1.0 - gmask)
        bflow = bmask[None, :, :, None]
        u_loss = jnp.sum(ele_u * bflow) / num_valid_flow
        v_loss = jnp.sum(ele_v * bflow) / num_valid_flow
    else:
        raise ValueError(f"unknown smoothness variant {cfg.smoothness!r}")

    u_loss = u_loss * level_on
    v_loss = v_loss * level_on
    total = photo + cfg.lambda_smooth * (u_loss + v_loss)
    return (
        # "smooth" aliases U+V as one number — the per-scale training
        # telemetry's smoothness component ("Models Matter, So Does
        # Training": the loss-term decomposition is what predicts EPE);
        # the reference-named keys stay untouched for parity consumers
        {"total": total, "Charbonnier_reconstruct": photo,
         "U_loss": u_loss, "V_loss": v_loss, "smooth": u_loss + v_loss},
        recon,
    )


def loss_interp_multi(
    flows: jnp.ndarray,
    volume: jnp.ndarray,
    flow_scale: float,
    cfg: LossConfig,
) -> tuple[LossDict, jnp.ndarray]:
    """T-frame volume loss (reference `sintelWrapFlow.py:492-630`).

    flows: (B, h, w, 2*(T-1)) raw head output; volume: (B, h, w, 3*T)
    LRN-normalized channel-stacked frames. Each consecutive pair (t, t+1) is
    warped with its own flow pair; photometric penalty over all T-1
    reconstructed frames (Charbonnier elementwise, or per-pair census —
    the frames fold into the batch axis for the descriptor transform);
    smoothness per pair with both smoothness and border masks applied
    pre-Charbonnier; U from even flow channels, V from odd.

    Knobs the volume path cannot honor raise by NAME here (the silent-drop
    failure class, VERDICT r04 weak #4): `edge_aware_photo` / `edge_aware`
    exist only in the reference's 2-frame gen-1/vgg variants, `occlusion`
    needs backward flows no volume head produces (also rejected at
    `train/step.py::make_train_step`), and the volume smoothness shape is
    the reference's own per-pair form (`sintelWrapFlow.py:565-600`), not
    the depthwise variant.
    """
    if cfg.edge_aware_photo:
        raise ValueError(
            "loss.edge_aware_photo is two-frame only (the reference's "
            "needImageGradients exists only in the vgg 2-frame variant); "
            "the multi-frame volume loss would silently skip it")
    if cfg.edge_aware:
        raise ValueError(
            "loss.edge_aware is two-frame depthwise only "
            "(`version1/model/warpflow.py:93-157`); the multi-frame volume "
            "loss would silently skip the Sobel smoothness weighting")
    if cfg.occlusion:
        raise ValueError(
            "loss.occlusion=true is unsupported by the multi-frame volume "
            "loss (no backward flows per pair); the masking would be "
            "silently skipped")
    if cfg.smoothness != "canonical":
        raise ValueError(
            f"loss.smoothness={cfg.smoothness!r} is unsupported by the "
            "multi-frame volume loss, whose per-pair smoothness shape is "
            "fixed by the reference (`sintelWrapFlow.py:565-600`); use "
            "'canonical'")
    b, h, w, c3t = volume.shape
    t = c3t // 3
    scaled = flows * flow_scale
    recon = backward_warp_volume(_warp_operand(volume, cfg), scaled,
                                 impl=cfg.warp_impl).astype(volume.dtype)

    bmask = border_mask(h, w, cfg.border_ratio)
    n_interior = jnp.sum(bmask)
    level_on = (n_interior > 0).astype(recon.dtype)
    num_valid = jnp.maximum(b * 3 * (t - 1) * n_interior, 1.0)
    if cfg.photometric == "census":
        from ..ops.census import census_distance, census_transform

        # Per-pair census: the descriptor is per-image (grayscale over a
        # 3-channel frame), so fold the T-1 reconstructed frames into the
        # batch axis and compare each against its source frame. Same
        # widened border mask as the 2-frame census branch.
        cmask = border_mask(h, w, cfg.border_ratio,
                            min_width=cfg.census_window // 2)[None, :, :, None]
        rec_f = jnp.moveaxis(
            recon.reshape(b, h, w, t - 1, 3), 3, 1
        ).reshape(b * (t - 1), h, w, 3)
        src_f = jnp.moveaxis(
            volume[..., : 3 * (t - 1)].reshape(b, h, w, t - 1, 3), 3, 1
        ).reshape(b * (t - 1), h, w, 3)
        dist = census_distance(
            census_transform(rec_f, cfg.census_window),
            census_transform(src_f, cfg.census_window))
        vis = jnp.broadcast_to(cmask, dist.shape)
        photo = jnp.sum(dist * vis) / jnp.maximum(jnp.sum(vis), 1.0)
    elif cfg.photometric == "charbonnier":
        diff = 255.0 * (recon - volume[..., : 3 * (t - 1)])
        ele = charbonnier(diff, cfg.epsilon, cfg.alpha_c) * bmask[None, :, :, None]
        photo = jnp.sum(ele) / num_valid
    else:
        raise ValueError(f"unknown photometric variant {cfg.photometric!r}")

    sflow = scaled if cfg.smooth_scaled_flow else flows
    diff_x, diff_y, mx, my = _smoothness_diffs(cfg, h, w)
    bflow = bmask[None, :, :, None]
    du = diff_x(sflow[..., 0::2]) * mx * bflow  # (B,h,w,T-1)
    dv = diff_y(sflow[..., 1::2]) * my * bflow
    u_loss = jnp.sum(charbonnier(du, cfg.epsilon, cfg.alpha_s)) / num_valid * level_on
    v_loss = jnp.sum(charbonnier(dv, cfg.epsilon, cfg.alpha_s)) / num_valid * level_on

    total = photo + cfg.lambda_smooth * (u_loss + v_loss)
    return (
        {"total": total, "Charbonnier_reconstruct": photo,
         "U_loss": u_loss, "V_loss": v_loss, "smooth": u_loss + v_loss},
        recon,
    )
