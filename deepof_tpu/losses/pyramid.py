"""Multi-scale pyramid loss orchestration.

The reference entangles preprocessing, per-scale resizing, and loss calls
inside each model graph (`flyingChairsWrapFlow.py:16-124`). Here the model
only predicts a flow pyramid; this module owns:

  - preprocessing: BGR dataset-mean subtraction, /255 scaling, and the LRN
    copy used exclusively inside the photometric loss
    (`flyingChairsWrapFlow.py:16-26`);
  - resizing the LRN images to every pyramid resolution (bilinear; the
    reference uses TF1's legacy asymmetric resize_bilinear — we use
    half-pixel-centered bilinear, which matches cv2/`check_loss.py` and is
    the modern convention; divergence documented);
  - per-scale `loss_interp` and the weighted total
    (`flyingChairsWrapFlow.py:122-124`), weights ordered finest (pr1) first.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.config import LossConfig
from ..ops.lrn import local_response_normalization
from .photometric import (
    LossDict,
    loss_interp,
    loss_interp_multi,
    occlusion_mask,
)


def preprocess(images: jnp.ndarray, mean) -> jnp.ndarray:
    """(images - BGR mean) / 255 — the network input scaling."""
    return (images - jnp.asarray(mean)) / 255.0


def lrn_normalize(scaled: jnp.ndarray) -> jnp.ndarray:
    """LRN copy of preprocessed images for the photometric loss."""
    return local_response_normalization(scaled, depth_radius=4, beta=0.7)


def _resize(img: jnp.ndarray, h: int, w: int) -> jnp.ndarray:
    if img.shape[1] == h and img.shape[2] == w:
        return img
    return jax.image.resize(img, (img.shape[0], h, w, img.shape[3]), "bilinear")


def pyramid_loss(
    flow_pyramid: list[tuple[jnp.ndarray, float]],
    inputs_norm: jnp.ndarray,
    outputs_norm: jnp.ndarray,
    cfg: LossConfig,
    smooth_border_mask: bool = False,
    flow_pyramid_bw: list[jnp.ndarray] | None = None,
) -> tuple[jnp.ndarray, list[LossDict], jnp.ndarray]:
    """flow_pyramid: [(flow_k, flow_scale_k)] finest first.

    flow_pyramid_bw: optional matching backward-flow pyramid (raw head
    outputs, same scales) enabling per-scale fw/bw occlusion masking of
    the photometric term (`LossConfig.occlusion`).

    Returns (weighted_total, per-scale loss dicts finest first, finest
    reconstruction).
    """
    losses: list[LossDict] = []
    recon_finest = None
    total = jnp.zeros(())
    for k, (flow, scale) in enumerate(flow_pyramid):
        h, w = flow.shape[1:3]
        li = _resize(inputs_norm, h, w)
        lo = _resize(outputs_norm, h, w)
        occ = None
        if flow_pyramid_bw is not None:
            occ = occlusion_mask(flow * scale, flow_pyramid_bw[k] * scale, cfg)
        ld, recon = loss_interp(flow, li, lo, scale, cfg, smooth_border_mask,
                                occ_mask=occ)
        losses.append(ld)
        if k == 0:
            recon_finest = recon
        weight = cfg.weights[k] if k < len(cfg.weights) else cfg.weights[-1]
        total = total + weight * ld["total"]
    return total, losses, recon_finest


def pyramid_loss_multi(
    flow_pyramid: list[tuple[jnp.ndarray, float]],
    volume_norm: jnp.ndarray,
    cfg: LossConfig,
) -> tuple[jnp.ndarray, list[LossDict], jnp.ndarray]:
    """Multi-frame (Sintel T-volume) pyramid loss; flows have 2*(T-1) ch."""
    losses = []
    recon_finest = None
    total = jnp.zeros(())
    for k, (flow, scale) in enumerate(flow_pyramid):
        h, w = flow.shape[1:3]
        vol = _resize(volume_norm, h, w)
        ld, recon = loss_interp_multi(flow, vol, scale, cfg)
        losses.append(ld)
        if k == 0:
            recon_finest = recon
        weight = cfg.weights[k] if k < len(cfg.weights) else cfg.weights[-1]
        total = total + weight * ld["total"]
    return total, losses, recon_finest
