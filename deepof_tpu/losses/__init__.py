from .photometric import (  # noqa: F401
    border_mask,
    smoothness_mask_x,
    smoothness_mask_y,
    charbonnier,
    loss_interp,
    loss_interp_multi,
)
from .pyramid import pyramid_loss, pyramid_loss_multi  # noqa: F401
