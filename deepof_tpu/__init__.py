"""deepof_tpu — TPU-native framework for Guided Optical Flow Learning.

A from-scratch JAX/XLA/Pallas/pjit re-design with the capabilities of the
reference TF1 implementation (bryanyzhu/deepOF): unsupervised optical-flow
training via multi-scale photometric warp losses over FlowNet-S / VGG16 /
Inception-v3 encoder-decoders, multi-frame Sintel volumes, UCF-101 two-stream
action models, plus TPU-first additions (data-parallel pjit over device
meshes, spatial context parallelism with halo exchange, Pallas fused kernels,
FlowNet-C correlation).

Layout:
  core/     config dataclasses, train-state pytrees, PRNG plumbing
  io/       .flo Middlebury IO, split files, image decode
  data/     dataset pipelines + on-device augmentation + prefetch
  models/   flax.linen model zoo
  ops/      warp / smoothness / LRN / correlation ops (+ ops/pallas kernels)
  losses/   multi-scale unsupervised pyramid losses
  parallel/ mesh construction, sharding rules, halo exchange
  train/    pjit train step, schedules, checkpointing, eval, logging
  utils/    metrics (EPE/AAE), flow color viz
"""

__version__ = "0.1.0"
