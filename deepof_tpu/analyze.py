"""Offline training-log analysis — the reference's `analyze_test_loss.py`
(grep stdout for `***Test:` lines + matplotlib, `analyze_test_loss.py:12-24`)
rebuilt over the structured JSONL metrics log.

Prints per-kind summaries (train loss trajectory, eval AEE/AAE curve,
throughput) and, when matplotlib is importable, writes loss/AEE curves as
PNGs next to the log.

Deliberately imports NOTHING from the training stack (no jax): analyzing a
log must not initialize an accelerator backend — especially not against a
TPU a live trainer already holds. Lives at the package top level so the
import chain stays `json`/`os`-only.
"""

from __future__ import annotations

import json
import math
import os
from collections import defaultdict


def _finite(records: list[dict], key: str) -> list[dict]:
    return [r for r in records
            if isinstance(r.get(key), (int, float))
            and math.isfinite(r[key])]


def load_records(log_dir: str, filename: str = "metrics.jsonl") -> list[dict]:
    path = os.path.join(log_dir, filename)
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # tolerate torn writes from a killed run
    return records


def summarize(records: list[dict]) -> dict:
    by_kind: dict[str, list[dict]] = defaultdict(list)
    for r in records:
        by_kind[r.get("kind", "?")].append(r)

    out: dict = {"counts": {k: len(v) for k, v in by_kind.items()}}

    raw_train = [r for r in by_kind.get("train", []) if "loss" in r]
    train = _finite(raw_train, "loss")
    if len(train) != len(raw_train):  # NaN losses break min() and JSON
        out["non_finite_train_records"] = len(raw_train) - len(train)
    if train:
        first, last = train[0], train[-1]
        best = min(train, key=lambda r: r["loss"])
        out["train"] = {
            "steps": last["step"],
            "first_loss": first["loss"],
            "last_loss": last["loss"],
            "best_loss": best["loss"],
            "best_step": best["step"],
            "last_lr": last.get("lr"),
            "items_per_sec_per_chip": last.get("items_per_sec_per_chip"),
        }

    evals = _finite(by_kind.get("eval", []), "aee")
    if evals:
        best = min(evals, key=lambda r: r["aee"])
        out["eval"] = {
            "evals": len(evals),
            "last_aee": evals[-1]["aee"],
            "best_aee": best["aee"],
            "best_step": best["step"],
            "last_aae": evals[-1].get("aae"),
        }
    accs = _finite(by_kind.get("eval", []), "accuracy")
    if accs:
        best = max(accs, key=lambda r: r["accuracy"])
        out["accuracy"] = {"last": accs[-1]["accuracy"],
                          "best": best["accuracy"], "best_step": best["step"]}

    warns = by_kind.get("warn", [])
    if warns:
        out["warnings"] = [r.get("message", "") for r in warns[-5:]]
    return out


def plot_curves(records: list[dict], out_dir: str) -> list[str]:
    """Write loss/AEE PNGs when matplotlib is available; returns paths."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:  # noqa: BLE001 - plotting is strictly optional
        return []

    written = []
    series = {
        "train_loss": [(r["step"], r["loss"]) for r in records
                       if r.get("kind") == "train" and "loss" in r],
        "eval_aee": [(r["step"], r["aee"]) for r in records
                     if r.get("kind") == "eval" and "aee" in r],
    }
    for name, pts in series.items():
        if len(pts) < 2:
            continue
        xs, ys = zip(*pts)
        fig, ax = plt.subplots(figsize=(8, 4))
        ax.plot(xs, ys)
        ax.set_xlabel("step")
        ax.set_ylabel(name)
        ax.grid(True, alpha=0.3)
        path = os.path.join(out_dir, f"{name}.png")
        fig.savefig(path, dpi=100, bbox_inches="tight")
        plt.close(fig)
        written.append(path)
    return written


def analyze(log_dir: str, plot: bool = True) -> dict:
    records = load_records(log_dir)
    summary = summarize(records)
    if plot:
        summary["plots"] = plot_curves(records, log_dir)
    return summary
