"""Offline training-log analysis — the reference's `analyze_test_loss.py`
(grep stdout for `***Test:` lines + matplotlib, `analyze_test_loss.py:12-24`)
rebuilt over the structured JSONL metrics log.

Prints per-kind summaries (train loss trajectory, eval AEE/AAE curve,
throughput) and, when matplotlib is importable, writes loss/AEE curves as
PNGs next to the log.

Deliberately imports NOTHING from the training stack (no jax): analyzing a
log must not initialize an accelerator backend — especially not against a
TPU a live trainer already holds. Lives at the package top level so the
import chain stays `json`/`os`-only.
"""

from __future__ import annotations

import json
import math
import os
import statistics
import time
from collections import defaultdict

from .obs.registry import merge_stats_blocks, resilience_keys


def _finite(records: list[dict], key: str) -> list[dict]:
    return [r for r in records
            if isinstance(r.get(key), (int, float))
            and math.isfinite(r[key])]


def load_records(log_dir: str, filename: str = "metrics.jsonl") -> list[dict]:
    path = os.path.join(log_dir, filename)
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # tolerate torn writes from a killed run
    return records


def _phase_breakdown(rec: dict) -> dict | None:
    """Host-phase share of accounted loop time from ONE train record.

    `phase_<name>_s` fields are cumulative totals (StepTimer), so the
    freshest record carries the whole run so far; shares are each
    phase's fraction of the summed phase time (assemble / put / dispatch
    / fetch — note put+fetch run on background threads, so shares answer
    "where does host work go", not "what serializes the main thread").
    """
    phases = {k[len("phase_"):-len("_s")]: r
              for k, r in rec.items()
              if k.startswith("phase_") and k.endswith("_s")
              and isinstance(r, (int, float)) and math.isfinite(r)}
    total = sum(phases.values())
    if not phases or total <= 0:
        return None
    return {
        "seconds": {k: round(v, 4) for k, v in sorted(phases.items())},
        "share": {k: round(v / total, 4) for k, v in sorted(phases.items())},
    }


def _counter_summary(rec: dict) -> dict | None:
    """Starvation + input-pipeline counters from one (cumulative) train
    record. `starvation_rate` approximates starved dispatches per
    trained step (with steps_per_call=K one dispatch serves K steps, so
    the per-dispatch rate is at most 1/K of the per-step figure)."""
    out: dict = {}
    step = rec.get("step", 0)
    starved = rec.get("starved")
    if isinstance(starved, (int, float)):
        out["starved"] = starved
        if isinstance(step, int) and step > 0:
            out["starvation_rate"] = round(starved / step, 6)
    res = _resilience_counters(rec)
    if res:
        out["resilience"] = res
    data = {k[len("data_"):]: v for k, v in rec.items()
            if k.startswith("data_")}
    if data:
        out["data"] = data
    return out or None


#: Resilience-layer counters (cumulative, in train records AND the
#: heartbeat): recovery activity an operator should see at a glance.
#: Driven from the observability schema (obs/registry.py — the single
#: owner of which keys exist and how they surface), not a hand-kept
#: list: registering a counter with resilience=True adds it here.
_RESILIENCE_KEYS = resilience_keys()


def _resilience_counters(rec: dict) -> dict:
    """Nonzero resilience counters from one record (zero counters are
    the healthy steady state and would only be noise)."""
    out = {k: rec[k] for k in _RESILIENCE_KEYS
           if isinstance(rec.get(k), (int, float)) and rec[k]}
    out.update({k: v for k, v in rec.items()
                if k.startswith("fault_") and isinstance(v, (int, float))
                and v})
    return out


def _serve_counters(rec: dict) -> dict:
    """`serve_*` counters from one record or heartbeat sample (the
    serving subsystem's block: requests/responses/errors, batch
    occupancy, latency percentiles, queue depths, and the per-precision
    `requests_by_tier`/`responses_by_tier` maps — a tier nobody asks
    for shows up as a zero here, not as silence)."""
    return {k[len("serve_"):]: v for k, v in rec.items()
            if k.startswith("serve_") and v is not None}


def _fleet_counters(rec: dict) -> dict:
    """`fleet_*` counters from one record or heartbeat sample (the
    serving-fleet block: replica states, evictions/respawns, circuit
    breaker, failover retries, shed counts)."""
    return {k[len("fleet_"):]: v for k, v in rec.items()
            if k.startswith("fleet_") and v is not None}


def _degrade_counters(rec: dict) -> dict:
    """`degrade_*` counters from one record or heartbeat sample (the
    brownout plane, serve/degrade.py: the live level, escalation/
    recovery ledger, L3 age, and the tier/bucket downgrade + low-
    priority shed counts the level drove). `tail` exits 10 when the
    block shows sustained L3."""
    return {k[len("degrade_"):]: v for k, v in rec.items()
            if k.startswith("degrade_") and v is not None}


def _deadline_counters(rec: dict) -> dict:
    """`deadline_*` counters from one record or heartbeat sample (the
    propagated-deadline plane: budgeted arrivals and where expired
    budgets died — router admission, engine enqueue/flush, the server's
    response wait)."""
    return {k[len("deadline_"):]: v for k, v in rec.items()
            if k.startswith("deadline_") and v is not None}


def _elastic_counters(rec: dict) -> dict:
    """`elastic_*` counters from one record or heartbeat sample (the
    elastic-training block, train/elastic.py: generation, re-forms,
    lost hosts, resumed step, steps lost, per-host states). `tail`
    exits 5 when the block shows the run had to re-form."""
    return {k[len("elastic_"):]: v for k, v in rec.items()
            if k.startswith("elastic_") and v is not None}


def _exec_counters(rec: dict) -> dict:
    """`exec_*` counters from one record or heartbeat sample (the
    executable-ledger block, obs/ledger.py: lowerings, recompiles,
    compile seconds, cache hits/misses, per-executable fingerprints,
    nominal-roofline MFU)."""
    return {k[len("exec_"):]: v for k, v in rec.items()
            if k.startswith("exec_") and v is not None}


def _recipe_counters(rec: dict) -> dict:
    """`recipe_*` counters from one record or heartbeat sample (the
    staged-recipe engine, train/recipe.py: active stage index/count,
    stage advances, the deterministic mixture's per-dataset draw
    counts, and the newest advance trigger's cause)."""
    return {k[len("recipe_"):]: v for k, v in rec.items()
            if k.startswith("recipe_") and v is not None}


def _ledger_rows(log_dir: str) -> list[dict]:
    """The run dir's ledger.jsonl rows, [] when it recorded none —
    loaded ONCE per tail/analyze pass and shared by the condensed
    summary and the drift verdict (a `tail --follow` tick must not
    parse the same file twice forever)."""
    from .obs.ledger import load_ledger

    try:
        return load_ledger(log_dir)
    except OSError:
        return []


def ledger_drift(log_dir: str, baseline: str | None = None,
                 fleet: bool = False, run_rows: list | None = None,
                 **bounds) -> dict | None:
    """The perf-regression sentinel's verdict for a run dir: the run's
    ledger.jsonl diffed against its baseline ledger (an explicit path,
    or the committed-by-convention <log_dir>/ledger_baseline.jsonl).
    With fleet=True, every supervised child dir's ledger is diffed
    against the SAME baseline (a fleet's replicas share one lattice)
    and condensed per child; `failed` then covers root and children —
    `tail` maps it to exit code 8. None when there is no baseline or no
    ledger to compare."""
    from .obs.ledger import diff_ledgers, find_baseline, ledger_verdict

    base_path = find_baseline(log_dir, baseline)
    if base_path is None:
        return None
    # the baseline is shared by the root and every fleet child — load
    # it ONCE per pass, not once per process per --follow tick
    from .obs.ledger import load_ledger

    try:
        base_rows = load_ledger(base_path)
    except OSError:
        base_rows = None
    out = ledger_verdict(log_dir, base_path, run_rows=run_rows,
                         base_rows=base_rows, **bounds)
    if fleet:
        children: dict[str, dict] = {}
        for name, d in discover_process_dirs(log_dir).items():
            v = ledger_verdict(d, base_path, base_rows=base_rows,
                               **bounds)
            if v is None:
                continue
            children[name] = {
                "failed": v["failed"],
                **{k: len(v[k]) for k in
                   ("fingerprint_drift", "unexpected_recompiles",
                    "compile_blowups", "memory_growth")}}
        if children:
            if out is None:
                # the root process lowered nothing but its children
                # did: a zero-comparison diff keeps the full documented
                # verdict schema (failure-class lists, bounds, new/
                # missing) instead of a bare {"failed": ...} whose
                # shape depends on whether the root had a ledger
                out = diff_ledgers([], [], **bounds)
            out["children"] = children
            out["failed"] = bool(out["failed"]
                                 or any(c["failed"]
                                        for c in children.values()))
    return out


#: Per-pyramid-scale loss-decomposition record fields (train/loop.py
#: writes them into every periodic train record, finest scale first).
_SCALE_FIELDS = ("loss_total_by_scale", "loss_photo_by_scale",
                 "loss_smooth_by_scale")


def eval_trend(evals: list[dict], window: int = 8,
               regress_tol: float = 0.02) -> dict | None:
    """Eval-EPE trend over the newest `window` eval records: the
    least-squares slope of AEE vs step (per 1000 steps — a readable
    unit at any eval cadence) plus a regression flag. `regressing` is
    True when the recent slope is positive AND the newest AEE sits more
    than `regress_tol` above the run's best — one noisy eval above best
    does not flag, a sustained climb does. This is the signal an
    EPE-driven curriculum switch point consumes (ROADMAP item 3): a
    plateaued-or-regressing stage is what triggers the next stage."""
    pts = [(r["step"], r["aee"]) for r in evals
           if isinstance(r.get("step"), int)
           and isinstance(r.get("aee"), (int, float))
           and math.isfinite(r["aee"])]
    if len(pts) < 3:
        return None
    recent = pts[-max(int(window), 3):]
    xs = [p[0] for p in recent]
    ys = [p[1] for p in recent]
    n = len(xs)
    mx = sum(xs) / n
    my = sum(ys) / n
    denom = sum((x - mx) ** 2 for x in xs)
    if denom <= 0:
        return None
    slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom
    best = min(y for _, y in pts)
    last = pts[-1][1]
    return {
        "window": n,
        "slope_aee_per_kstep": round(slope * 1e3, 6),
        "last_aee": last,
        "best_aee": best,
        "regressing": bool(slope > 0
                           and last > best * (1.0 + float(regress_tol))),
    }


def _scale_event_summary(scales: list[dict]) -> dict:
    """Condensed view of the autoscaler's kind="fleet" scale records:
    how many times the pool moved, which way, and the newest event."""
    last = scales[-1]
    return {
        "events": len(scales),
        "ups": sum(1 for r in scales if r.get("event") == "scale_up"),
        "downs": sum(1 for r in scales if r.get("event") == "scale_down"),
        "last": {k: last.get(k) for k in
                 ("event", "reason", "replica", "replicas_before",
                  "replicas_after", "time") if last.get(k) is not None},
    }


def summarize(records: list[dict]) -> dict:
    by_kind: dict[str, list[dict]] = defaultdict(list)
    for r in records:
        by_kind[r.get("kind", "?")].append(r)

    out: dict = {"counts": {k: len(v) for k, v in by_kind.items()}}

    raw_train = [r for r in by_kind.get("train", []) if "loss" in r]
    train = _finite(raw_train, "loss")
    if len(train) != len(raw_train):  # NaN losses break min() and JSON
        out["non_finite_train_records"] = len(raw_train) - len(train)
    if train:
        first, last = train[0], train[-1]
        best = min(train, key=lambda r: r["loss"])
        out["train"] = {
            "steps": last["step"],
            "first_loss": first["loss"],
            "last_loss": last["loss"],
            "best_loss": best["loss"],
            "best_step": best["step"],
            "last_lr": last.get("lr"),
            "items_per_sec_per_chip": last.get("items_per_sec_per_chip"),
        }
        # per-pyramid-scale loss decomposition from the newest record
        # (finest first): where the objective's mass sits — photometric
        # vs smoothness, coarse vs fine — not just its total
        for field in _SCALE_FIELDS:
            if isinstance(last.get(field), list):
                out["train"][field] = last[field]
        # phase/counter aggregation rides on the freshest train record
        # (phase_*_s / starved / data_* fields are cumulative totals)
        newest = raw_train[-1]
        phases = _phase_breakdown(newest)
        if phases:
            out["phases"] = phases
        counters = _counter_summary(newest)
        if counters:
            out["counters"] = counters
        # staged-recipe block (train/recipe.py extra_stats ride every
        # periodic train record): stage index, advances, mixture draws
        recipe = _recipe_counters(newest)
        if recipe:
            out["recipe"] = recipe

    evals = _finite(by_kind.get("eval", []), "aee")
    if evals:
        best = min(evals, key=lambda r: r["aee"])
        out["eval"] = {
            "evals": len(evals),
            "last_aee": evals[-1]["aee"],
            "best_aee": best["aee"],
            "best_step": best["step"],
            "last_aae": evals[-1].get("aae"),
        }
        trend = eval_trend(evals)
        if trend:
            out["eval_trend"] = trend
    accs = _finite(by_kind.get("eval", []), "accuracy")
    if accs:
        best = max(accs, key=lambda r: r["accuracy"])
        out["accuracy"] = {"last": accs[-1]["accuracy"],
                          "best": best["accuracy"], "best_step": best["step"]}

    serves = by_kind.get("serve", [])
    if serves:
        # cumulative counters: the newest serve record carries the whole
        # serving session (server.py / fleet.py append one at shutdown)
        serve = _serve_counters(serves[-1])
        if serve:
            out["serve"] = serve
        fleet = _fleet_counters(serves[-1])
        if fleet:
            out["fleet"] = fleet
        degrade = _degrade_counters(serves[-1])
        if degrade:
            out["degrade"] = degrade
        deadline = _deadline_counters(serves[-1])
        if deadline:
            out["deadline"] = deadline
        execs = _exec_counters(serves[-1])
        if execs:
            out["exec"] = execs

    scales = by_kind.get("fleet", [])
    if scales:
        # the autoscaler's pool-size timeline (serve/autoscale.py
        # appends one kind="fleet" record per scale event): the event
        # count plus the newest event's what/why/when
        out["scale_events"] = _scale_event_summary(scales)

    elastics = by_kind.get("elastic", [])
    if elastics:
        # cumulative: the newest elastic record carries the whole run's
        # re-form history (train/elastic.py appends one per re-form and
        # one at shutdown)
        elastic = _elastic_counters(elastics[-1])
        if elastic:
            out["elastic"] = elastic

    warns = by_kind.get("warn", [])
    if warns:
        out["warnings"] = [r.get("message", "") for r in warns[-5:]]
    return out


def load_heartbeat(log_dir: str) -> dict | None:
    """The run's heartbeat.json (obs/heartbeat.py), or None. The file is
    atomically rewritten, so a read never sees a torn record."""
    try:
        with open(os.path.join(log_dir, "heartbeat.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ----------------------------------------------- multi-process run dirs


def discover_process_dirs(log_dir: str) -> dict[str, str]:
    """{child name -> dir} for a supervised run's per-process subdirs
    (fleet replicas / elastic trainer hosts) that actually hold
    observability artifacts. Empty for a plain single-process run.
    Delegates to obs/aggregate.py's walker — ONE definition of "a child
    process dir", shared with `trace_summary --merge`, so the two views
    can never disagree about which processes a drill contains."""
    from .obs.aggregate import discover_processes  # stdlib-only chain

    out: dict[str, str] = {}
    for p in discover_processes(log_dir):
        if not p["rel"]:
            continue  # the supervisor itself: the caller's own summary
        out[p["rel"].replace(os.sep, "/")] = p["dir"]
    return out


def _process_summary(d: str, now: float) -> dict:
    """One child process's condensed health block: record counts, the
    live heartbeat verdict, and whichever counter blocks (serve / fleet
    / elastic / resilience) the process emits."""
    out: dict = {}
    try:
        records = load_records(d)
    except FileNotFoundError:
        records = []
    out["records"] = len(records)
    hb = load_heartbeat(d)
    newest: dict = {}
    for kind in ("serve", "elastic"):
        kinds = [r for r in records if r.get("kind") == kind]
        if kinds:
            newest.update(kinds[-1])
    if hb is not None:
        newest.update(hb)  # fresher than any record, wins per key
        out["step"] = hb.get("step")
        out["wedged"] = hb.get("wedged")
        t = hb.get("time")
        if isinstance(t, (int, float)):
            out["heartbeat_age_s"] = round(now - t, 1)
    for name, extract in (("serve", _serve_counters),
                          ("fleet", _fleet_counters),
                          ("degrade", _degrade_counters),
                          ("deadline", _deadline_counters),
                          ("elastic", _elastic_counters),
                          ("exec", _exec_counters),
                          ("recipe", _recipe_counters)):
        block = extract(newest)
        if block:
            out[name] = block
    res = _resilience_counters(newest)
    if res:
        out["resilience"] = res
    warns = [r for r in records if r.get("kind") == "warn"]
    if warns:
        out["warnings"] = len(warns)
    return out


def aggregate_processes(log_dir: str, now: float | None = None) -> dict | None:
    """The whole-drill view of a multi-process run dir: one condensed
    block per child (replica-N / host-N) plus a `merged` block — summed
    serve counters and the EXACT fixed-bucket latency-histogram merge
    (obs/export.py) across every child that reports one. None when the
    dir has no supervised children (plain run)."""
    dirs = discover_process_dirs(log_dir)
    if not dirs:
        return None
    now = time.time() if now is None else now
    children = {name: _process_summary(d, now) for name, d in dirs.items()}
    # registry-driven merge (obs/registry.py): every serve-owned counter
    # combines by its declared kind — sums add, high-water marks max,
    # per-tier maps merge key-wise, histograms merge EXACTLY per key
    # (request latency and per-session-frame latency are separate
    # stories), gauges/bools/derived values are dropped. A counter
    # registered tomorrow joins this block with no edit here — the
    # hand-kept sum list this replaces missed one in four of the last
    # six PRs.
    merged = merge_stats_blocks(
        [child.get("serve") or {} for child in children.values()],
        prefix="serve_")  # child blocks store serve_* keys stripped
    out = {"processes": children}
    if merged:
        out["merged"] = merged
    # the fleet-wide executable-ledger view: per-replica exec_* blocks
    # merged by their registry kinds (compile seconds and cache counters
    # sum; fingerprints and MFU stay per-process — state/derived)
    merged_exec = merge_stats_blocks(
        [child.get("exec") or {} for child in children.values()],
        prefix="exec_")
    if merged_exec:
        out["merged_exec"] = merged_exec
    return out


def tail_summary(log_dir: str, recent: int = 10,
                 now: float | None = None, fleet: bool = False,
                 ledger_baseline: str | None = None,
                 ledger_bounds: dict | None = None) -> dict:
    """One-glance health of a LIVE or finished run (`deepof_tpu tail`):
    where it is, whether it is moving, how fast recently vs overall,
    where host time goes, and how stale the heartbeat is.

    recent: train records in the throughput-trend window. The per-record
    `steps_per_sec` is a since-start cumulative average, so the recent
    rate is recomputed from the newest records' (step, time) gaps —
    median of per-gap slopes, robust to one eval/ckpt pause inside the
    window — the number that answers "is it slowing down?".
    fleet: also aggregate the run dir's supervised children (fleet
    replicas / elastic hosts) into a `processes` + `merged` block
    (`tail --fleet`) — the whole drill in one read.
    """
    records = load_records(log_dir)
    now = time.time() if now is None else now
    out: dict = {"log_dir": log_dir, "records": len(records)}
    if records:
        t = records[-1].get("time")
        if isinstance(t, (int, float)):
            out["last_record_age_s"] = round(now - t, 1)

    train = [r for r in records if r.get("kind") == "train"]
    if train:
        last = train[-1]
        out["step"] = last.get("step")
        out["loss"] = last.get("loss")
        out["steps_per_sec"] = last.get("steps_per_sec")
        out["items_per_sec_per_chip"] = last.get("items_per_sec_per_chip")
        for k in ("model_tflops", "mfu_nominal", "dev_mem_bytes_in_use",
                  "dev_mem_peak_bytes", "rss_bytes"):
            if last.get(k) is not None:
                out[k] = last[k]
        window = [r for r in train[-max(recent, 2):]
                  if isinstance(r.get("time"), (int, float))
                  and isinstance(r.get("step"), int)]
        if len(window) >= 2:
            # median of per-gap slopes, not one end-to-end slope: an
            # eval sweep / checkpoint inside the window stretches ONE
            # gap's wall time (the cumulative steps_per_sec excludes
            # those pauses via StepTimer), and a single stretched gap
            # must not read as a run-wide slowdown
            gap_rates = []
            for a, b in zip(window, window[1:]):
                dt, dstep = b["time"] - a["time"], b["step"] - a["step"]
                if dt > 0 and dstep > 0:
                    gap_rates.append(dstep / dt)
            if gap_rates:
                rsps = statistics.median(gap_rates)
                out["recent_steps_per_sec"] = round(rsps, 4)
                overall = last.get("steps_per_sec")
                if isinstance(overall, (int, float)) and overall > 0:
                    # >1: speeding up; <1: the recent window is slower
                    # than the run's average
                    out["throughput_trend"] = round(rsps / overall, 3)
        phases = _phase_breakdown(last)
        if phases:
            out["phase_share"] = phases["share"]
        counters = _counter_summary(last)
        if counters:
            out.update({k: v for k, v in counters.items() if k != "data"})
        recipe = _recipe_counters(last)
        if recipe:
            out["recipe"] = recipe

    evals = [r for r in records if r.get("kind") == "eval"]
    if evals:
        out["last_eval"] = {k: evals[-1][k] for k in ("step", "aee", "aae",
                                                      "accuracy")
                            if k in evals[-1]}
    warns = [r for r in records if r.get("kind") == "warn"]
    if warns:
        out["warnings"] = len(warns)
        out["last_warning"] = str(warns[-1].get("message", ""))[:200]

    hb = load_heartbeat(log_dir)
    if hb is not None:
        entry = {"step": hb.get("step"), "wedged": hb.get("wedged"),
                 "wedges": hb.get("wedges"),
                 "last_step_age_s": hb.get("last_step_age_s")}
        t = hb.get("time")
        if isinstance(t, (int, float)):
            # fresh: age < ~2x the period => the writer thread is alive
            entry["age_s"] = round(now - t, 1)
            entry["period_s"] = hb.get("heartbeat_period_s")
        out["heartbeat"] = entry
        # heartbeat-carried resilience counters are fresher than the last
        # train record (they update every period, records every
        # log_every): merge per key with the heartbeat winning, so a
        # recovery burst between log points surfaces within one period
        res = {**out.get("resilience", {}), **_resilience_counters(hb)}
        if res:
            out["resilience"] = res
        # a serving process's heartbeat carries the live serve_* block
        # (queue depth, occupancy, p50/p99 latency, requests/s)
        serve = _serve_counters(hb)
        if serve:
            out["serve"] = serve
        # a fleet supervisor's heartbeat carries the live fleet_* block
        # (replica states, evictions/respawns/broken, failovers, shed) —
        # `tail` exits 4 when it shows evictions or a broken replica
        # (fleet_block, not fleet: the parameter must stay visible)
        fleet_block = _fleet_counters(hb)
        if fleet_block:
            out["fleet"] = fleet_block
        # the brownout/deadline planes (serve/degrade.py + the deadline
        # gates): the live level, shed/downgrade ledger, and where
        # expired budgets died — `tail` exits 10 on sustained L3
        degrade = _degrade_counters(hb)
        if degrade:
            out["degrade"] = degrade
        deadline = _deadline_counters(hb)
        if deadline:
            out["deadline"] = deadline
        # an elastic coordinator's heartbeat carries the live elastic_*
        # block (generation, re-forms, lost hosts, steps lost, per-host
        # states) — `tail` exits 5 when the run had to re-form
        elastic = _elastic_counters(hb)
        if elastic:
            out["elastic"] = elastic
        # a ledgered process's heartbeat carries the live exec_* block
        # (lowerings, recompiles, compile seconds, cache hit/miss,
        # fingerprints, roofline MFU — obs/ledger.py)
        execs = _exec_counters(hb)
        if execs:
            out["exec"] = execs
        # a recipe-driven trainer's heartbeat carries the live recipe_*
        # block (stage, advances, mixture draws) — fresher than the
        # newest train record, wins per block
        recipe = _recipe_counters(hb)
        if recipe:
            out["recipe"] = recipe

    serves = [r for r in records if r.get("kind") == "serve"]
    if serves:
        if "serve" not in out:
            serve = _serve_counters(serves[-1])
            if serve:
                out["serve"] = serve
        if "fleet" not in out:
            fleet_block = _fleet_counters(serves[-1])
            if fleet_block:
                out["fleet"] = fleet_block
        if "degrade" not in out:
            degrade = _degrade_counters(serves[-1])
            if degrade:
                out["degrade"] = degrade
        if "deadline" not in out:
            deadline = _deadline_counters(serves[-1])
            if deadline:
                out["deadline"] = deadline
        if "exec" not in out:
            execs = _exec_counters(serves[-1])
            if execs:
                out["exec"] = execs
    scales = [r for r in records if r.get("kind") == "fleet"]
    if scales:
        # autoscale pool-size timeline (one kind="fleet" record per
        # scale event) — the live fleet block above already carries the
        # fleet_autoscale_* counters; this names the newest move
        out["scale_events"] = _scale_event_summary(scales)
    if "elastic" not in out:
        elastics = [r for r in records if r.get("kind") == "elastic"]
        if elastics:
            elastic = _elastic_counters(elastics[-1])
            if elastic:
                out["elastic"] = elastic
    if fleet:
        agg = aggregate_processes(log_dir, now=now)
        if agg:
            out.update(agg)
    # executable-ledger surfaces (obs/ledger.py): the run's condensed
    # ledger.jsonl, and — when a baseline ledger exists (explicit path
    # or the committed <log_dir>/ledger_baseline.jsonl) — the drift
    # verdict the CLI maps to exit code 8
    from .obs.ledger import summarize_ledger

    rows = _ledger_rows(log_dir)
    if rows:
        ledger = summarize_ledger(rows)
        if ledger:
            out["ledger"] = ledger
    drift = ledger_drift(log_dir, ledger_baseline, fleet=fleet,
                         run_rows=rows, **(ledger_bounds or {}))
    if drift is not None:
        out["ledger_diff"] = drift
    # incident-plane surface (obs/incident.py): the committed bundle
    # summary the CLI maps to exit code 9 (unacked critical) — absent
    # entirely when the run recorded no incidents
    from .obs.incident import incident_summary

    inc = incident_summary(log_dir)
    if inc is not None:
        out["incidents"] = inc
    return out


def plot_curves(records: list[dict], out_dir: str) -> list[str]:
    """Write loss/AEE PNGs when matplotlib is available; returns paths."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:  # noqa: BLE001 - plotting is strictly optional
        return []

    written = []
    series = {
        "train_loss": [(r["step"], r["loss"]) for r in records
                       if r.get("kind") == "train" and "loss" in r],
        "eval_aee": [(r["step"], r["aee"]) for r in records
                     if r.get("kind") == "eval" and "aee" in r],
    }
    for name, pts in series.items():
        if len(pts) < 2:
            continue
        xs, ys = zip(*pts)
        fig, ax = plt.subplots(figsize=(8, 4))
        ax.plot(xs, ys)
        ax.set_xlabel("step")
        ax.set_ylabel(name)
        ax.grid(True, alpha=0.3)
        path = os.path.join(out_dir, f"{name}.png")
        fig.savefig(path, dpi=100, bbox_inches="tight")
        plt.close(fig)
        written.append(path)
    return written


def analyze(log_dir: str, plot: bool = True) -> dict:
    records = load_records(log_dir)
    summary = summarize(records)
    # a supervised run dir (fleet replicas / elastic hosts) aggregates
    # its children too: one `analyze` summarizes the whole drill
    agg = aggregate_processes(log_dir)
    if agg:
        summary.update(agg)
    from .obs.ledger import summarize_ledger

    rows = _ledger_rows(log_dir)
    if rows:
        ledger = summarize_ledger(rows)
        if ledger:
            summary["ledger"] = ledger
    drift = ledger_drift(log_dir, fleet=True, run_rows=rows)
    if drift is not None:
        summary["ledger_diff"] = drift
    from .obs.incident import incident_summary

    inc = incident_summary(log_dir)
    if inc is not None:
        summary["incidents"] = inc
    if plot:
        summary["plots"] = plot_curves(records, log_dir)
    return summary
