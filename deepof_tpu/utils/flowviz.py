"""Middlebury flow-color visualization.

Capability parity with reference `utils.py:209-350` (`flowToColor` /
`computeColor` / `makecolorwheel`), vectorized (no per-color python loops over
pixels) and with the color wheel built once at module load.

Convention: hue encodes direction (red at 3 o'clock, rotating through
yellow/green/cyan/blue/magenta), saturation encodes magnitude normalized by
the max radius in the field.
"""

from __future__ import annotations

import numpy as np

_UNKNOWN_FLOW_THRESH = 1e9


def make_colorwheel() -> np.ndarray:
    """55-color Middlebury wheel, float in [0, 1], shape (55, 3)."""
    ry, yg, gc, cb, bm, mr = 15, 6, 4, 11, 13, 6
    ncols = ry + yg + gc + cb + bm + mr
    wheel = np.zeros((ncols, 3))
    col = 0
    wheel[col : col + ry, 0] = 1
    wheel[col : col + ry, 1] = np.arange(ry) / ry
    col += ry
    wheel[col : col + yg, 0] = 1 - np.arange(yg) / yg
    wheel[col : col + yg, 1] = 1
    col += yg
    wheel[col : col + gc, 1] = 1
    wheel[col : col + gc, 2] = np.arange(gc) / gc
    col += gc
    wheel[col : col + cb, 1] = 1 - np.arange(cb) / cb
    wheel[col : col + cb, 2] = 1
    col += cb
    wheel[col : col + bm, 2] = 1
    wheel[col : col + bm, 0] = np.arange(bm) / bm
    col += bm
    wheel[col : col + mr, 2] = 1 - np.arange(mr) / mr
    wheel[col : col + mr, 0] = 1
    return wheel


_WHEEL = make_colorwheel()


def compute_color(u: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Map normalized (u, v) (radius<=1 in-range) to uint8 RGB image."""
    ncols = _WHEEL.shape[0]
    radius = np.sqrt(u**2 + v**2)
    rot = np.arctan2(-v, -u) / np.pi  # [-1, 1]
    fk = (rot + 1) / 2 * (ncols - 1)
    k0 = fk.astype(np.int32)
    k1 = (k0 + 1) % ncols
    f = (fk - k0)[..., None]
    col = (1 - f) * _WHEEL[k0] + f * _WHEEL[k1]  # (..., 3)
    in_range = (radius <= 1)[..., None]
    rad = radius[..., None]
    col = np.where(in_range, 1 - rad * (1 - col), col * 0.75)
    return np.floor(255 * col).astype(np.uint8)


def flow_to_color(flow: np.ndarray, max_flow: float | None = None) -> np.ndarray:
    """(H, W, 2) flow -> (H, W, 3) uint8 RGB, normalized by max radius."""
    u = np.array(flow[..., 0], dtype=np.float64)
    v = np.array(flow[..., 1], dtype=np.float64)
    unknown = (np.abs(u) > _UNKNOWN_FLOW_THRESH) | (np.abs(v) > _UNKNOWN_FLOW_THRESH)
    u[unknown] = 0
    v[unknown] = 0
    maxrad = float(np.max(np.sqrt(u**2 + v**2))) if max_flow is None else float(max_flow)
    eps = 2.22e-16
    img = compute_color(u / (maxrad + eps), v / (maxrad + eps))
    img[unknown] = 0
    return img
