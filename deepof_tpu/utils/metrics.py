"""Optical-flow evaluation metrics.

Behavior parity with reference `utils.py:64-80` (`flow_ee` / `flow_ae`).
Host-side (numpy) eval utilities; inputs are (..., H, W, 2) flow fields,
channel 0 = u (horizontal), channel 1 = v (vertical).
"""

from __future__ import annotations

import numpy as np


def flow_epe(pred, gt, mask=None):
    """Average endpoint error (AEE / EPE).

    mean over all pixels of sqrt((u-u_gt)^2 + (v-v_gt)^2); with `mask`
    (broadcastable to (..., H, W)), a masked mean.
    """
    pred = np.asarray(pred)
    gt = np.asarray(gt)
    d = pred - gt
    ee = np.sqrt(d[..., 0] ** 2 + d[..., 1] ** 2)
    if mask is None:
        return ee.mean()
    mask = np.broadcast_to(np.asarray(mask, dtype=ee.dtype), ee.shape)
    return (ee * mask).sum() / np.maximum(mask.sum(), 1)


def flow_aae(pred, gt, mask=None):
    """Average angular error in radians (reference `utils.py:70-80`).

    Treats flows as 3D vectors (u, v, 1) and measures the angle between them.
    """
    u, v = pred[..., 0], pred[..., 1]
    ug, vg = gt[..., 0], gt[..., 1]
    num = 1.0 + u * ug + v * vg
    den = np.sqrt(1.0 + u**2 + v**2) * np.sqrt(1.0 + ug**2 + vg**2)
    ae = np.arccos(np.clip(num / den, -1.0, 1.0))
    if mask is None:
        return ae.mean()
    mask = np.broadcast_to(np.asarray(mask, dtype=ae.dtype), ae.shape)
    return (ae * mask).sum() / np.maximum(mask.sum(), 1)
