from .metrics import flow_epe, flow_aae  # noqa: F401
from .flowviz import flow_to_color  # noqa: F401
