"""Lint framework: findings, the rule registry, inline waivers, and the
file/tree runners. Rules live in `lint/rules.py`; this module is
mechanism only.

A rule is a function ``(ctx: FileContext) -> Iterable[Finding]``
registered with the ``@rule(name)`` decorator. Findings carry (rule,
path, line, col, message); the runner applies inline waivers before
returning them.

Waivers: a finding is waived by a comment on ITS line, or on the line
directly above (for lines too long to carry a trailing comment)::

    self._devmem = snap  # lint: lock-discipline-ok(atomic rebind)

    # lint: determinism-ok(wall-clock only feeds the report header)
    stamp = time.time()

The syntax is ``# lint: <rule>-ok(<reason>)``; the reason is REQUIRED —
a bare ``<rule>-ok`` does not waive (an unexplained suppression is the
reviewer-vigilance regression this linter exists to end). Multiple
waivers may share one comment, comma-separated. Waived findings are
still reported (marked ``waived``) so ``--json`` consumers can audit
them, but they do not affect the exit code.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Iterable

#: rule name -> (fn, one-line doc). Populated by @rule at import of
#: lint/rules.py.
RULES: dict[str, tuple[Callable, str]] = {}

_WAIVER_RE = re.compile(r"#\s*lint:\s*(.+)$")
#: reason may contain one level of nested parens ("... (see DESIGN.md)")
_WAIVER_ITEM_RE = re.compile(
    r"([a-z][a-z0-9-]*)-ok\(((?:[^()]|\([^()]*\))*)\)")


def rule(name: str, doc: str = ""):
    """Register a rule function under `name` (kebab-case)."""
    def deco(fn: Callable) -> Callable:
        if name in RULES:
            raise ValueError(f"lint rule {name!r} registered twice")
        RULES[name] = (fn, doc or (fn.__doc__ or "").strip().split("\n")[0])
        return fn
    return deco


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    waived: bool = False
    waive_reason: str | None = None

    def format(self) -> str:
        mark = " [waived]" if self.waived else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}: {self.message}{mark}")

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "waived": self.waived, "waive_reason": self.waive_reason}


@dataclass
class FileContext:
    """Everything a rule gets to look at: one parsed file."""

    path: str
    source: str
    tree: ast.AST
    lines: list[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.source.splitlines()


def _waivers(source: str,
             lines: list[str]) -> dict[int, dict[str, str]]:
    """{1-based line -> {rule -> reason}} of lines each waiver covers
    (its own line, plus the next line when the comment stands alone).
    Only REAL comment tokens count — a string literal that happens to
    contain the waiver syntax (docs, test fixtures) must never
    suppress a finding."""
    out: dict[int, dict[str, str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(t.start[0], t.start[1], t.string)
                    for t in tokens if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out  # unparseable files already yield a `parse` finding
    for lineno, col, text in comments:
        m = _WAIVER_RE.search(text)
        if m is None:
            continue
        items = {r: reason.strip()
                 for r, reason in _WAIVER_ITEM_RE.findall(m.group(1))
                 if reason.strip()}  # reason REQUIRED
        if not items:
            continue
        covered = [lineno]
        line_text = lines[lineno - 1] if lineno <= len(lines) else ""
        if not line_text[:col].strip():
            covered.append(lineno + 1)  # standalone: waives next line
        for ln in covered:
            out.setdefault(ln, {}).update(items)
    return out


def lint_source(source: str, path: str = "<string>",
                rules: Iterable[str] | None = None) -> list[Finding]:
    """Lint one source string. Unknown rule names raise ValueError (the
    CLI turns that into its usage-error exit code). A syntax error in
    the target file is itself a finding (rule ``parse``), never a
    linter crash."""
    selected = _validate_rules(rules)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("parse", path, e.lineno or 1, e.offset or 0,
                        f"syntax error: {e.msg}")]
    ctx = FileContext(path=path, source=source, tree=tree)
    waivers = _waivers(source, ctx.lines)
    findings: list[Finding] = []
    for name in selected:
        fn, _ = RULES[name]
        for f in fn(ctx):
            line_waivers = waivers.get(f.line, {})
            if f.rule in line_waivers:
                f.waived = True
                f.waive_reason = line_waivers[f.rule]
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_python_files(paths: Iterable[str]) -> list[str]:
    """Expand files/directories into a sorted list of .py files
    (skipping __pycache__ and hidden dirs). Missing paths raise
    FileNotFoundError — a typo'd path must not lint 'clean'."""
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d != "__pycache__" and not d.startswith(".")]
                out.extend(os.path.join(root, f) for f in files
                           if f.endswith(".py"))
        else:
            raise FileNotFoundError(f"lint: no such file or directory: {p}")
    return sorted(set(out))


def _validate_rules(rules: Iterable[str] | None) -> list[str]:
    """Selected rule names, validated. Unknown names raise ValueError —
    the CLI's usage-error exit — and are checked UP FRONT, not per
    file: a typo'd --rule over a path set that happens to hold no .py
    files must still fail loudly, never report 'clean'."""
    selected = list(rules) if rules is not None else sorted(RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(
            f"unknown lint rule(s) {unknown} — available: {sorted(RULES)}")
    return selected


def lint_paths(paths: Iterable[str],
               rules: Iterable[str] | None = None) -> list[Finding]:
    """Lint every .py file under `paths`."""
    rules = _validate_rules(rules)
    findings: list[Finding] = []
    for path in iter_python_files(paths):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        findings.extend(lint_source(source, path=path, rules=rules))
    return findings
