"""graftlint — project-invariant static analysis (`deepof_tpu lint`).

An AST-based, jax-free linter for the defect classes PRs 1-11 kept
hand-fixing in review: counters missing from merge lists, config typos
only caught at runtime, unseeded randomness in the determinism-pinned
data path, side effects inside traced code, and cross-thread writes
outside the class lock. DESIGN.md "Static analysis" documents each
rule; `obs/registry.py` is the schema the counter rule checks against.

Import discipline: stdlib + `core.config` + `obs.registry` only — the
linter must run on a machine (or in a CI stage) with no jax installed,
and must never initialize an accelerator backend.
"""

from .core import (Finding, RULES, lint_paths, lint_source,  # noqa: F401
                   rule)
from . import rules as _rules  # noqa: F401 - registers the rule set
