"""The five graftlint rules (DESIGN.md "Static analysis").

Each rule encodes a project invariant that previously lived in reviewer
vigilance; every one of them has at least one shipped-and-later-fixed
defect behind it (see the per-rule docstrings). Rules are pure
functions over one parsed file — no cross-file state beyond the two
jax-free schema imports (`obs.registry`, `core.config`).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

from .core import Finding, FileContext, rule
from ..obs import registry as obs_registry

# --------------------------------------------------------------------
# rule: counter-registry
# --------------------------------------------------------------------


def _literal_stat_keys(tree: ast.AST) -> Iterator[tuple[str, ast.AST]]:
    """(key, node) for every string-literal stats-dict WRITE with a
    linted prefix: dict-literal keys and `d["key"] = ...` subscript
    assignments. Reads (`.get("serve_x")`, membership tuples) are
    deliberately not matched — the registry polices what gets WRITTEN
    into a stats block; the merge paths are registry-driven and have no
    per-key read lists left to drift."""
    prefixes = obs_registry.LINTED_PREFIXES
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            for key in node.keys:
                if (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)
                        and key.value.startswith(prefixes)):
                    yield key.value, key
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.slice, ast.Constant)
                        and isinstance(tgt.slice.value, str)
                        and tgt.slice.value.startswith(prefixes)):
                    yield tgt.slice.value, tgt.slice


@rule("counter-registry",
      "every serve_*/fleet_*/elastic_*/data_*/fault_* stats key written "
      "anywhere must be declared in obs/registry.py")
def counter_registry(ctx: FileContext) -> Iterator[Finding]:
    """PRs 4/6/7/9/10/11 each hand-patched a merge list after a new
    counter silently missed the heartbeat/analyze/tail/scrape surface.
    The merge paths are now driven from obs/registry.py, so the ONE
    remaining way to lose a counter is writing a key the registry does
    not know — which is exactly what this rule makes a CI failure."""
    if ctx.path.endswith(("obs/registry.py", "obs\\registry.py")):
        return  # the schema's own declarations are not "writes"
    for key, node in _literal_stat_keys(ctx.tree):
        if obs_registry.lookup(key) is None:
            yield Finding(
                "counter-registry", ctx.path, node.lineno, node.col_offset,
                f"stats key {key!r} is not declared in obs/registry.py — "
                "register it (name, merge kind, owner) so the fleet "
                "scrape and analyze/tail merges pick it up")


# --------------------------------------------------------------------
# rule: config-key
# --------------------------------------------------------------------

#: methods legal on any frozen config dataclass
_CONFIG_METHODS = frozenset(("replace",))


def _config_schema():
    """{class name -> {field -> nested class name | None}} for the whole
    config tree, resolved once from the real dataclasses (so this rule
    can never drift from core/config.py)."""
    import typing

    from ..core import config as config_mod
    from ..resilience.faults import FaultConfig

    classes: dict[str, type] = {"FaultConfig": FaultConfig}
    for name in dir(config_mod):
        obj = getattr(config_mod, name)
        if isinstance(obj, type) and dataclasses.is_dataclass(obj):
            classes[name] = obj
    schema: dict[str, dict[str, str | None]] = {}
    for cname, cls in classes.items():
        hints = typing.get_type_hints(cls)
        fields: dict[str, str | None] = {}
        for f in dataclasses.fields(cls):
            hint = hints.get(f.name)
            fields[f.name] = (hint.__name__
                              if isinstance(hint, type)
                              and dataclasses.is_dataclass(hint) else None)
        schema[cname] = fields
    return schema


_SCHEMA_CACHE: dict | None = None


def _schema() -> dict:
    global _SCHEMA_CACHE
    if _SCHEMA_CACHE is None:
        _SCHEMA_CACHE = _config_schema()
    return _SCHEMA_CACHE


def _annotation_class(node: ast.AST | None, schema: dict) -> str | None:
    """Config class named by an annotation: `ExperimentConfig`,
    `"ExperimentConfig"`, `X | None`, `Optional[X]`."""
    if node is None:
        return None
    if isinstance(node, ast.Name) and node.id in schema:
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value.strip().split(".")[-1]
        return name if name in schema else None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return (_annotation_class(node.left, schema)
                or _annotation_class(node.right, schema))
    if isinstance(node, ast.Subscript):  # Optional[X]
        return _annotation_class(node.slice, schema)
    if isinstance(node, ast.Attribute):  # config.ExperimentConfig
        return node.attr if node.attr in schema else None
    return None


def _chain(node: ast.Attribute) -> tuple[ast.AST, list[str]]:
    """Attribute chain -> (base node, [attr names outermost-last])."""
    attrs: list[str] = []
    cur: ast.AST = node
    while isinstance(cur, ast.Attribute):
        attrs.append(cur.attr)
        cur = cur.value
    attrs.reverse()
    return cur, attrs


def _resolve_chain(start: str, attrs: list[str],
                   schema: dict) -> tuple[str | None, str | None]:
    """Walk `attrs` from config class `start`.

    Returns (error_attr, final_class): error_attr is the first attr
    that is not a field (None = chain valid); final_class is the config
    class the full chain lands on (None when it ends at a leaf field or
    a method)."""
    cls: str | None = start
    for a in attrs:
        if cls is None:
            return None, None  # past a leaf: not ours to judge
        fields = schema[cls]
        if a in fields:
            cls = fields[a]
        elif a in _CONFIG_METHODS or a.startswith("__"):
            return None, None
        else:
            return a, None
    return None, cls


class _ConfigScope(ast.NodeVisitor):
    """Per-function validation scope: parameter/alias roots + chain
    checks. Nested defs share the parent's roots (closures read them)."""

    def __init__(self, ctx: FileContext, schema: dict,
                 roots: dict[str, str], self_attrs: dict[str, str]):
        self.ctx = ctx
        self.schema = schema
        self.roots = dict(roots)        # local name -> config class
        self.self_attrs = self_attrs    # self.<attr> -> config class
        self.findings: list[Finding] = []
        self._seen: set[int] = set()

    # ------------------------------------------------- chain resolution
    def _root_class(self, base: ast.AST,
                    attrs: list[str]) -> tuple[str | None, list[str]]:
        """(config class, remaining attrs) for a chain's base."""
        if isinstance(base, ast.Name):
            cls = self.roots.get(base.id)
            if cls is not None:
                return cls, attrs
        if (isinstance(base, ast.Name) and base.id == "self" and attrs):
            cls = self.self_attrs.get(attrs[0])
            if cls is not None:
                return cls, attrs[1:]
        return None, attrs

    def _check(self, node: ast.Attribute) -> tuple[str | None, bool]:
        """Validate one full chain; returns (final config class, known)
        and records a finding on the first unknown field."""
        base, attrs = _chain(node)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute):
                self._seen.add(id(sub))
        cls, attrs = self._root_class(base, attrs)
        if cls is None:
            return None, False
        bad, final = _resolve_chain(cls, attrs, self.schema)
        if bad is not None:
            self.findings.append(Finding(
                "config-key", self.ctx.path, node.lineno, node.col_offset,
                f"{cls}.{'.'.join(attrs)}: {bad!r} is not a declared "
                f"field on the config path (typo'd config access would "
                "silently read nothing at runtime)"))
        return final, True

    # ------------------------------------------------------- visitors
    def visit_Attribute(self, node: ast.Attribute) -> None:
        if id(node) not in self._seen:
            self._check(node)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # alias tracking: `sc = cfg.serve.session` makes `sc` a root
        self.generic_visit(node)
        final: str | None = None
        known = False
        if isinstance(node.value, ast.Attribute):
            final, known = (self._final_of(node.value))
        elif isinstance(node.value, ast.Name):
            final = self.roots.get(node.value.id)
            known = final is not None
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                if final is not None:
                    self.roots[tgt.id] = final
                elif known is False and tgt.id in self.roots:
                    del self.roots[tgt.id]  # rebound to something else
            elif (isinstance(tgt, ast.Attribute)
                  and isinstance(tgt.value, ast.Name)
                  and tgt.value.id == "self" and final is not None):
                self.self_attrs[tgt.attr] = final

    def _final_of(self, node: ast.Attribute) -> tuple[str | None, bool]:
        base, attrs = _chain(node)
        cls, attrs = self._root_class(base, attrs)
        if cls is None:
            return None, False
        bad, final = _resolve_chain(cls, attrs, self.schema)
        return (final, True) if bad is None else (None, True)


def _collect_roots(fn: ast.AST, schema: dict) -> dict[str, str]:
    """Config-typed roots from a function's signature: annotations win;
    the bare names `cfg`/`config` and `<section>_cfg` are conventions
    this codebase follows everywhere."""
    roots: dict[str, str] = {}
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return roots
    section_classes = {f"{name}_cfg": cls
                       for name, cls in schema["ExperimentConfig"].items()
                       if cls is not None}
    args = list(fn.args.posonlyargs) + list(fn.args.args) \
        + list(fn.args.kwonlyargs)
    for a in args:
        cls = _annotation_class(a.annotation, schema)
        if cls is not None:
            roots[a.arg] = cls
        elif a.annotation is None:
            if a.arg in ("cfg", "config"):
                roots[a.arg] = "ExperimentConfig"
            elif a.arg in section_classes:
                roots[a.arg] = section_classes[a.arg]
    return roots


def _self_attr_aliases(cls_node: ast.ClassDef,
                       schema: dict) -> dict[str, str]:
    """{self.<attr> -> config class} from every `self.x = <chain>`
    assignment in the class (two-pass: methods may be defined before
    __init__'s aliases lexically)."""
    out: dict[str, str] = {}
    for method in cls_node.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        roots = _collect_roots(method, schema)
        for node in ast.walk(method):
            if not isinstance(node, ast.Assign):
                continue
            final: str | None = None
            if isinstance(node.value, ast.Name):
                final = roots.get(node.value.id)
            elif isinstance(node.value, ast.Attribute):
                base, attrs = _chain(node.value)
                if isinstance(base, ast.Name) and base.id in roots:
                    bad, fin = _resolve_chain(roots[base.id], attrs, schema)
                    final = fin if bad is None else None
            if final is None:
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    out[tgt.attr] = final
    return out


@rule("config-key",
      "attribute access on config dataclasses must resolve to a "
      "declared field")
def config_key(ctx: FileContext) -> Iterator[Finding]:
    """`config_from_dict` rejects typo'd KEYS at load time, but a typo'd
    READ (`cfg.serve.sesion.ttl_s`) only explodes when the line runs —
    which for error paths is production. This rule resolves every
    attribute chain rooted at a config-typed name against the real
    dataclass tree, so the typo is a lint finding, not a 3 a.m.
    AttributeError."""
    schema = _schema()

    def lint_function(fn, extra_roots, self_attrs):
        roots = {**extra_roots, **_collect_roots(fn, schema)}
        scope = _ConfigScope(ctx, schema, roots, self_attrs)
        for stmt in fn.body:
            scope.visit(stmt)
        return scope.findings

    for node in ctx.tree.body if isinstance(ctx.tree, ast.Module) else []:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield from lint_function(node, {}, {})
        elif isinstance(node, ast.ClassDef):
            self_attrs = _self_attr_aliases(node, schema)
            for method in node.body:
                if isinstance(method,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from lint_function(method, {}, self_attrs)


# --------------------------------------------------------------------
# rule: determinism
# --------------------------------------------------------------------

#: module subtrees under the determinism contract (derive_batch_rng's
#: bit-identical-stream pin, PRs 2/4/8): path fragments relative to the
#: PACKAGE root — matched against the path from the `deepof_tpu/`
#: segment on, never against the checkout prefix (a repo cloned under
#: /data/... must not put every file in scope).
_DETERMINISM_SCOPES = (
    "/data/", "/models/", "/losses/", "/ops/", "/train/step.py",
)


def _package_relative(path: str) -> str | None:
    """The path from the `deepof_tpu/` package segment on (leading
    slash kept so scope fragments anchor on directory boundaries), or
    None for files outside the package — the determinism contract is
    package-internal by definition."""
    norm = path.replace("\\", "/")
    idx = norm.rfind("/deepof_tpu/")
    if idx >= 0:
        return norm[idx:]
    if norm.startswith("deepof_tpu/"):
        return "/" + norm
    return None

#: seeded constructors: legal when called WITH at least one argument
_SEEDED_CTORS = frozenset(("RandomState", "default_rng", "Generator",
                           "Random", "SeedSequence", "PRNGKey", "key"))


def _dotted(node: ast.AST) -> str | None:
    """`a.b.c` -> "a.b.c" for plain name/attribute chains."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


@rule("determinism",
      "no unseeded random.*/np.random.*/time.time() in data/models/"
      "losses/ops/train-step modules")
def determinism(ctx: FileContext) -> Iterator[Finding]:
    """The pinned contract: the sample/augment stream is bit-identical
    for any worker count, any steps_per_call regrouping, any elastic
    re-shard (derive_batch_rng). One module-level `np.random.shuffle`
    or `time.time()`-derived seed silently voids all of it. Only the
    contract-bearing module subtrees are in scope; obs/timing helpers
    (`time.perf_counter`, `time.monotonic`) are always legal."""
    rel = _package_relative(ctx.path)
    if rel is None or not any(s in rel for s in _DETERMINISM_SCOPES):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name is None:
            continue
        if name in ("time.time", "time.time_ns"):
            yield Finding(
                "determinism", ctx.path, node.lineno, node.col_offset,
                f"{name}() in a determinism-scoped module: wall-clock "
                "values void the bit-identical-stream contract (use "
                "time.perf_counter/monotonic for durations, or seed "
                "from config)")
            continue
        parts = name.split(".")
        unseeded = None
        if parts[0] == "random" and len(parts) == 2:
            unseeded = parts[1] not in _SEEDED_CTORS or not (
                node.args or node.keywords)
        elif (len(parts) >= 3 and parts[-3] in ("np", "numpy")
              and parts[-2] == "random"):
            unseeded = parts[-1] not in _SEEDED_CTORS or not (
                node.args or node.keywords)
        if unseeded:
            yield Finding(
                "determinism", ctx.path, node.lineno, node.col_offset,
                f"unseeded {name}() in a determinism-scoped module: "
                "draw from a derive_batch_rng-derived RandomState (or "
                "seed explicitly) so the stream stays bit-identical "
                "for any worker count")


# --------------------------------------------------------------------
# rule: jit-purity
# --------------------------------------------------------------------

_JIT_NAMES = frozenset(("jit", "pjit", "eval_shape"))
_JIT_ATTRS = frozenset(("jit", "pjit", "eval_shape", "scan"))


def _is_jit_expr(node: ast.AST) -> bool:
    """`jit` / `pjit` / `jax.jit` / `jax.lax.scan` / ... as a bare
    expression (no call parens)."""
    if isinstance(node, ast.Name):
        return node.id in _JIT_NAMES
    return (isinstance(node, ast.Attribute) and node.attr in _JIT_ATTRS
            and (_dotted(node) or "").split(".")[0] in ("jax", "lax"))


def _jit_callees(tree: ast.AST) -> Iterator[tuple[ast.AST, ast.AST]]:
    """(jit-like site, traced-function node) pairs, covering BOTH forms
    this repo uses: the call form `jax.jit(fn)` / `lax.scan(fn, ...)`
    and the decorator form `@jax.jit` / `@partial(jax.jit, ...)` —
    the latter is the dominant idiom in the model/ops code, and a rule
    that misses it would pass exactly the prints it advertises to
    catch."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and node.args \
                and _is_jit_expr(node.func):
            yield node, node.args[0]
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    yield dec, node  # @jax.jit
                elif isinstance(dec, ast.Call):
                    if _is_jit_expr(dec.func):
                        yield dec, node  # @jax.jit(static_argnums=...)
                    elif ((_dotted(dec.func) or "").split(".")[-1]
                          == "partial" and dec.args
                          and _is_jit_expr(dec.args[0])):
                        yield dec, node  # @partial(jax.jit, ...)


def _impure_statements(fn_node: ast.AST) -> Iterator[tuple[ast.AST, str]]:
    """(node, what) for prints, file opens, and module-global mutation
    inside a traced function body (nested defs included)."""
    global_names: set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Global):
            global_names.update(node.names)
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            if node.func.id == "print":
                yield node, "calls print()"
            elif node.func.id == "open":
                yield node, "opens a file"
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                if isinstance(tgt, ast.Name) and tgt.id in global_names:
                    yield node, f"mutates module global {tgt.id!r}"


@rule("jit-purity",
      "functions passed to jit/pjit/lax.scan/eval_shape must not "
      "print, open files, or mutate module globals")
def jit_purity(ctx: FileContext) -> Iterator[Finding]:
    """Side effects in traced code run ONCE, at trace time, then never
    again — a print inside a jitted step 'works' in the first dispatch
    and silently vanishes for the rest of the run (and a mutated
    global desynchronizes retrace decisions across processes). Only
    statically resolvable callees (same-module defs, lambdas) are
    checked; `jax.debug.print` is the supported escape hatch."""
    # module-level function table for resolving Name references
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    for call, arg in _jit_callees(ctx.tree):
        target: ast.AST | None = None
        label = ""
        if isinstance(arg, (ast.FunctionDef, ast.AsyncFunctionDef)):
            target, label = arg, arg.name  # decorator form
        elif isinstance(arg, ast.Lambda):
            target, label = arg, "lambda"
        elif isinstance(arg, ast.Name) and arg.id in defs:
            target, label = defs[arg.id], arg.id
        if target is None:
            continue
        for node, what in _impure_statements(target):
            yield Finding(
                "jit-purity", ctx.path, node.lineno, node.col_offset,
                f"traced function {label!r} (passed to jit-like call at "
                f"line {call.lineno}) {what}: side effects in traced "
                "code run once at trace time and never again (use "
                "jax.debug.print / host_callback, or hoist the effect)")


# --------------------------------------------------------------------
# rule: lock-discipline
# --------------------------------------------------------------------

_LOCK_CTORS = frozenset(("Lock", "RLock", "Condition"))


def _lock_attrs(cls_node: ast.ClassDef) -> set[str]:
    """self.<attr> names assigned a threading.Lock/RLock/Condition
    anywhere in the class."""
    out: set[str] = set()
    for node in ast.walk(cls_node):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        ctor = None
        if isinstance(v, ast.Call):
            if isinstance(v.func, ast.Attribute):
                ctor = v.func.attr
            elif isinstance(v.func, ast.Name):
                ctor = v.func.id
        if ctor not in _LOCK_CTORS:
            continue
        for tgt in node.targets:
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                out.add(tgt.attr)
    return out


def _spawns_thread(cls_node: ast.ClassDef) -> bool:
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name in ("threading.Thread", "Thread") \
                    or (name or "").endswith(".Thread"):
                return True
    return False


def _self_writes(method: ast.AST, locks: set[str]):
    """(attr, node, locked) for every `self.<attr> = ...` /
    `self.<attr> += ...` in the method, where `locked` means the write
    is lexically inside a `with self.<lock>:` block."""

    def walk(node: ast.AST, locked: bool):
        if isinstance(node, ast.With):
            holds = any(
                isinstance(item.context_expr, ast.Attribute)
                and isinstance(item.context_expr.value, ast.Name)
                and item.context_expr.value.id == "self"
                and item.context_expr.attr in locks
                for item in node.items)
            for child in node.body:
                walk(child, locked or holds)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    yield_list.append((tgt.attr, node, locked))
        for child in ast.iter_child_nodes(node):
            walk(child, locked)

    yield_list: list = []
    walk(method, False)
    return yield_list


@rule("lock-discipline",
      "in thread-spawning classes, self attributes written from "
      "multiple methods must be written under the class lock")
def lock_discipline(ctx: FileContext) -> Iterator[Finding]:
    """The PR 10 torn-heartbeat race in one rule: a class that spawns a
    thread AND owns a lock has declared its mutable state shared;
    a `self._x` written from two different methods (one of them on the
    spawned thread) without the lock is a data race — GIL atomicity
    does not cover read-modify-write or multi-field invariants.
    Writes in __init__ are exempt (they happen before the thread
    exists). Deliberate lock-free handoffs (atomic rebinds, Events)
    carry a waiver with the reason."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        locks = _lock_attrs(node)
        if not locks or not _spawns_thread(node):
            continue
        writes_by_attr: dict[str, list] = {}
        for method in node.body:
            if not isinstance(method,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if method.name == "__init__":
                continue
            for attr, wnode, locked in _self_writes(method, locks):
                if attr in locks:
                    continue
                writes_by_attr.setdefault(attr, []).append(
                    (method.name, wnode, locked))
        for attr, writes in writes_by_attr.items():
            methods = {m for m, _, _ in writes}
            if len(methods) < 2:
                continue
            for mname, wnode, locked in writes:
                if not locked:
                    yield Finding(
                        "lock-discipline", ctx.path, wnode.lineno,
                        wnode.col_offset,
                        f"{node.name}.{mname} writes self.{attr} outside "
                        f"the class lock, but self.{attr} is also "
                        f"written by "
                        f"{sorted(methods - {mname}) or [mname]} — in a "
                        "thread-spawning class that is a data race "
                        "(hold the lock, or waive with the reason the "
                        "lock-free write is safe)")
