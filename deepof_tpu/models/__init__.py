from .common import count_params, bilinear_kernel_init, load_vgg16_npz  # noqa: F401
from .flownet_s import FlowNetS  # noqa: F401
from .vgg16_flow import VGG16Flow, VGG16Trunk  # noqa: F401
from .inception_v3_flow import InceptionV3Flow  # noqa: F401
from .flownet_c import FlowNetC  # noqa: F401
from .flownet2 import FlowNetCS  # noqa: F401
from .two_stream import UCF101Spatial, STSingle, STBaseline  # noqa: F401
from .registry import build_model, MODELS  # noqa: F401
