"""FlowNet-Simple flow model.

Architecture parity with reference `flowNet` (`flyingChairsWrapFlow.py:31-118`):
10-conv contracting trunk (strides 2 at conv1/2/3_1/4_1/5_1/6_1), ELU
activations, 6 pyramid heads with flow scales 20/2^k (finest pr1 scale 10.0
... coarsest pr6 scale 0.3125), decoder deconvs of widths 512/256/128/64/32.

Input: preprocessed image pair concatenated on channels (B, H, W, 6) — or a
(B, H, W, 3T) multi-frame volume with `flow_channels=2(T-1)`.
Output: list of flow predictions finest-first; `flow_scales` finest-first.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from .common import FlowDecoder, flownet_trunk, scaled_width

FLOW_SCALES = (10.0, 5.0, 2.5, 1.25, 0.625, 0.3125)  # finest (pr1) first


class FlowNetS(nn.Module):
    flow_channels: int = 2
    dtype: Any = jnp.float32
    # Thin-variant channel multiplier (same topology / flow semantics,
    # scaled widths — the standard FlowNet "/N" family). 1.0 = exact
    # reference widths; tests use 0.25 for cheap wiring checks.
    width_mult: float = 1.0

    flow_scales: tuple[float, ...] = FLOW_SCALES
    max_downsample = 64  # six stride-2 stages; spatial-CP gradient-safety bound

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> list[jnp.ndarray]:
        taps = flownet_trunk(x, self.dtype, width_mult=self.width_mult)
        flows = FlowDecoder(
            upconv_features=tuple(scaled_width(f, self.width_mult)
                                  for f in (512, 256, 128, 64, 32)),
            flow_channels=self.flow_channels,
            dtype=self.dtype,
            name="decoder",
        )(taps[::-1])
        return flows[::-1]  # finest first
