"""Model registry — replaces the reference's string-dispatch in
`version1/trainOF.py:76-90` and the per-dataset trainer imports.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from .flownet_s import FlowNetS
from .vgg16_flow import VGG16Flow
from .inception_v3_flow import InceptionV3Flow
from .flownet_c import FlowNetC
from .flownet2 import FlowNetCS
from .two_stream import STBaseline, STSingle, UCF101Spatial

MODELS = {
    "flownet_s": FlowNetS,
    "vgg16": VGG16Flow,
    "inception_v3": InceptionV3Flow,
    "flownet_c": FlowNetC,
    "flownet_cs": FlowNetCS,
    "st_single": STSingle,
    "st_baseline": STBaseline,
    "ucf101_spatial": UCF101Spatial,
}


def build_model(name: str, flow_channels: int = 2, dtype: Any = jnp.float32,
                width_mult: float = 1.0, **kw):
    if name not in MODELS:
        raise KeyError(f"unknown model {name!r}; available: {sorted(MODELS)}")
    cls = MODELS[name]
    if width_mult != 1.0:
        # honored only by models that declare the field; the parity
        # backbones keep exact reference widths — reject with a named
        # error instead of a dataclass TypeError deep in __init__
        import dataclasses

        if "width_mult" not in {f.name for f in dataclasses.fields(cls)}:
            supported = sorted(
                n for n, c in MODELS.items()
                if "width_mult" in {f.name for f in dataclasses.fields(c)})
            raise ValueError(
                f"model {name!r} does not support width_mult "
                f"(={width_mult}); thin variants exist for {supported}")
        kw["width_mult"] = width_mult
    if name == "ucf101_spatial":
        return cls(dtype=dtype, **kw)
    return cls(flow_channels=flow_channels, dtype=dtype, **kw)
