"""Model registry — replaces the reference's string-dispatch in
`version1/trainOF.py:76-90` and the per-dataset trainer imports.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from .flownet_s import FlowNetS
from .vgg16_flow import VGG16Flow
from .inception_v3_flow import InceptionV3Flow
from .flownet_c import FlowNetC
from .flownet2 import FlowNetCS
from .two_stream import STBaseline, STSingle, UCF101Spatial

MODELS = {
    "flownet_s": FlowNetS,
    "vgg16": VGG16Flow,
    "inception_v3": InceptionV3Flow,
    "flownet_c": FlowNetC,
    "flownet_cs": FlowNetCS,
    "st_single": STSingle,
    "st_baseline": STBaseline,
    "ucf101_spatial": UCF101Spatial,
}


def build_model(name: str, flow_channels: int = 2, dtype: Any = jnp.float32, **kw):
    if name not in MODELS:
        raise KeyError(f"unknown model {name!r}; available: {sorted(MODELS)}")
    cls = MODELS[name]
    if name == "ucf101_spatial":
        return cls(dtype=dtype, **kw)
    return cls(flow_channels=flow_channels, dtype=dtype, **kw)
