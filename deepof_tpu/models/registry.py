"""Model registry — replaces the reference's string-dispatch in
`version1/trainOF.py:76-90` and the per-dataset trainer imports.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from .flownet_s import FlowNetS
from .vgg16_flow import VGG16Flow
from .inception_v3_flow import InceptionV3Flow
from .flownet_c import FlowNetC
from .flownet2 import FlowNetCS
from .two_stream import STBaseline, STSingle, UCF101Spatial

MODELS = {
    "flownet_s": FlowNetS,
    "vgg16": VGG16Flow,
    "inception_v3": InceptionV3Flow,
    "flownet_c": FlowNetC,
    "flownet_cs": FlowNetCS,
    "st_single": STSingle,
    "st_baseline": STBaseline,
    "ucf101_spatial": UCF101Spatial,
}


#: (config-surface name, model-field name, model-family default): knobs
#: honored only by models that declare the field. Non-default values for
#: a model without the field raise a NAMED error instead of silently
#: dropping (the "displacements invisible to the correlation" class of
#: silent failure, DESIGN.md r04) or a dataclass TypeError.
_OPTIONAL_KNOBS = (
    ("width_mult", "width_mult", 1.0),
    ("corr_max_disp", "max_disp", 20),
    ("corr_stride", "corr_stride", 2),
)


def build_model(name: str, flow_channels: int = 2, dtype: Any = jnp.float32,
                width_mult: float = 1.0, corr_max_disp: int = 20,
                corr_stride: int = 2, **kw):
    if name not in MODELS:
        raise KeyError(f"unknown model {name!r}; available: {sorted(MODELS)}")
    cls = MODELS[name]
    import dataclasses

    fields = {f.name for f in dataclasses.fields(cls)}
    passed = {"width_mult": width_mult, "corr_max_disp": corr_max_disp,
              "corr_stride": corr_stride}
    for knob, field, default in _OPTIONAL_KNOBS:
        value = passed[knob]
        if field in fields and field not in kw:
            kw[field] = value
        elif value != default and field not in fields:
            supported = sorted(
                n for n, c in MODELS.items()
                if field in {f.name for f in dataclasses.fields(c)})
            raise ValueError(
                f"model {name!r} does not support {knob} (={value}); "
                f"models honoring it: {supported}")
    if name == "ucf101_spatial":
        return cls(dtype=dtype, **kw)
    return cls(flow_channels=flow_channels, dtype=dtype, **kw)
