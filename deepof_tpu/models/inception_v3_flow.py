"""Inception-v3-backbone flow model — the reference's flagship trainer model
(`flyingChairsTrain.py:103`, `sintelTrain.py:112`).

Base: standard Inception-v3 with *all-SAME* padding (the reference edited
slim's base so every stage halves cleanly, `flyingChairsWrapFlow.py:145-467`)
and slim-default ReLU activations — the trainers call the model without the
batch-norm arg-scope, so the base has conv+bias only, no normalization.

Head (`flyingChairsWrapFlow.py:471-595`): 6 pyramid levels tapped at
Conv2d_1a_3x3 / MaxPool_3a_3x3 / MaxPool_5a_3x3 / Mixed_5d / Mixed_6e /
Mixed_7c, ELU decoder deconvs of widths 512/256/128/64/32, and a stride-1
2x2 deconv between the Mixed_5d and MaxPool_5a taps because they share a
spatial size (`:551-556`). Flow scales finest-first:
10 / 5 / 2.5 / 2.5 / 1.25 / 0.625 — note the repeated 2.5.

Multi-frame Sintel volumes (`sintelWrapFlow.py:342-453`) use the same
architecture with `flow_channels=2*(T-1)`; unlike the reference, the decoder
propagates *all* flow channels through `up_pr*` (the reference's 2-channel
truncation is a known bug per SURVEY.md §7.3, not replicated).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from .common import FlowDecoder, conv_init, scaled_width

FLOW_SCALES = (10.0, 5.0, 2.5, 2.5, 1.25, 0.625)  # finest (pr1) first


class _Conv(nn.Module):
    """conv + bias + ReLU, SAME padding (slim default in the base).

    `width_mult` scales the channel count (thin variants, same role as
    FlowNetS.width_mult); 1.0 keeps the exact reference widths, so the
    44.55M param-parity pin is untouched.
    """

    features: int
    kernel: tuple[int, int] = (1, 1)
    stride: int = 1
    dtype: Any = jnp.float32
    width_mult: float = 1.0

    @nn.compact
    def __call__(self, x):
        feats = scaled_width(self.features, self.width_mult)
        x = nn.Conv(feats, self.kernel, strides=(self.stride, self.stride),
                    padding="SAME", kernel_init=conv_init, dtype=self.dtype)(x)
        return nn.relu(x)


def _avg_pool(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


def _max_pool(x, stride=2):
    return nn.max_pool(x, (3, 3), strides=(stride, stride), padding="SAME")


class _InceptionA(nn.Module):
    """Mixed_5b/5c/5d: 1x1 + 5x5 + double-3x3 + pool-proj branches."""

    pool_features: int
    dtype: Any = jnp.float32
    width_mult: float = 1.0

    @nn.compact
    def __call__(self, x):
        dt = self.dtype
        wm = self.width_mult
        b0 = _Conv(64, dtype=dt, width_mult=wm, name="b0_1x1")(x)
        b1 = _Conv(48, dtype=dt, width_mult=wm, name="b1_1x1")(x)
        b1 = _Conv(64, (5, 5), dtype=dt, width_mult=wm, name="b1_5x5")(b1)
        b2 = _Conv(64, dtype=dt, width_mult=wm, name="b2_1x1")(x)
        b2 = _Conv(96, (3, 3), dtype=dt, width_mult=wm, name="b2_3x3a")(b2)
        b2 = _Conv(96, (3, 3), dtype=dt, width_mult=wm, name="b2_3x3b")(b2)
        b3 = _Conv(self.pool_features, dtype=dt, width_mult=wm, name="b3_proj")(_avg_pool(x))
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class _ReductionA(nn.Module):
    """Mixed_6a: stride-2 reduction to 768."""

    dtype: Any = jnp.float32
    width_mult: float = 1.0

    @nn.compact
    def __call__(self, x):
        dt = self.dtype
        wm = self.width_mult
        b0 = _Conv(384, (3, 3), 2, dtype=dt, width_mult=wm, name="b0_3x3")(x)
        b1 = _Conv(64, dtype=dt, width_mult=wm, name="b1_1x1")(x)
        b1 = _Conv(96, (3, 3), dtype=dt, width_mult=wm, name="b1_3x3a")(b1)
        b1 = _Conv(96, (3, 3), 2, dtype=dt, width_mult=wm, name="b1_3x3b")(b1)
        return jnp.concatenate([b0, b1, _max_pool(x)], axis=-1)


class _InceptionB(nn.Module):
    """Mixed_6b..6e: factorized 7x7 branches, 768 out."""

    mid: int  # 128 / 160 / 192
    dtype: Any = jnp.float32
    width_mult: float = 1.0

    @nn.compact
    def __call__(self, x):
        dt, m = self.dtype, self.mid
        wm = self.width_mult
        b0 = _Conv(192, dtype=dt, width_mult=wm, name="b0_1x1")(x)
        b1 = _Conv(m, dtype=dt, width_mult=wm, name="b1_1x1")(x)
        b1 = _Conv(m, (1, 7), dtype=dt, width_mult=wm, name="b1_1x7")(b1)
        b1 = _Conv(192, (7, 1), dtype=dt, width_mult=wm, name="b1_7x1")(b1)
        b2 = _Conv(m, dtype=dt, width_mult=wm, name="b2_1x1")(x)
        b2 = _Conv(m, (7, 1), dtype=dt, width_mult=wm, name="b2_7x1a")(b2)
        b2 = _Conv(m, (1, 7), dtype=dt, width_mult=wm, name="b2_1x7a")(b2)
        b2 = _Conv(m, (7, 1), dtype=dt, width_mult=wm, name="b2_7x1b")(b2)
        b2 = _Conv(192, (1, 7), dtype=dt, width_mult=wm, name="b2_1x7b")(b2)
        b3 = _Conv(192, dtype=dt, width_mult=wm, name="b3_proj")(_avg_pool(x))
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class _ReductionB(nn.Module):
    """Mixed_7a: stride-2 reduction to 1280."""

    dtype: Any = jnp.float32
    width_mult: float = 1.0

    @nn.compact
    def __call__(self, x):
        dt = self.dtype
        wm = self.width_mult
        b0 = _Conv(192, dtype=dt, width_mult=wm, name="b0_1x1")(x)
        b0 = _Conv(320, (3, 3), 2, dtype=dt, width_mult=wm, name="b0_3x3")(b0)
        b1 = _Conv(192, dtype=dt, width_mult=wm, name="b1_1x1")(x)
        b1 = _Conv(192, (1, 7), dtype=dt, width_mult=wm, name="b1_1x7")(b1)
        b1 = _Conv(192, (7, 1), dtype=dt, width_mult=wm, name="b1_7x1")(b1)
        b1 = _Conv(192, (3, 3), 2, dtype=dt, width_mult=wm, name="b1_3x3")(b1)
        return jnp.concatenate([b0, b1, _max_pool(x)], axis=-1)


class _InceptionC(nn.Module):
    """Mixed_7b/7c: expanded-filter-bank blocks, 2048 out."""

    dtype: Any = jnp.float32
    width_mult: float = 1.0

    @nn.compact
    def __call__(self, x):
        dt = self.dtype
        wm = self.width_mult
        b0 = _Conv(320, dtype=dt, width_mult=wm, name="b0_1x1")(x)
        b1 = _Conv(384, dtype=dt, width_mult=wm, name="b1_1x1")(x)
        b1 = jnp.concatenate(
            [_Conv(384, (1, 3), dtype=dt, width_mult=wm, name="b1_1x3")(b1),
             _Conv(384, (3, 1), dtype=dt, width_mult=wm, name="b1_3x1")(b1)], axis=-1)
        b2 = _Conv(448, dtype=dt, width_mult=wm, name="b2_1x1")(x)
        b2 = _Conv(384, (3, 3), dtype=dt, width_mult=wm, name="b2_3x3")(b2)
        b2 = jnp.concatenate(
            [_Conv(384, (1, 3), dtype=dt, width_mult=wm, name="b2_1x3")(b2),
             _Conv(384, (3, 1), dtype=dt, width_mult=wm, name="b2_3x1")(b2)], axis=-1)
        b3 = _Conv(192, dtype=dt, width_mult=wm, name="b3_proj")(_avg_pool(x))
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class InceptionV3Base(nn.Module):
    """Stem + Mixed blocks; returns the 6 decoder tap activations."""

    dtype: Any = jnp.float32
    width_mult: float = 1.0

    @nn.compact
    def __call__(self, x) -> dict[str, jnp.ndarray]:
        dt = self.dtype
        wm = self.width_mult
        taps = {}
        net = _Conv(32, (3, 3), 2, dtype=dt, width_mult=wm, name="Conv2d_1a_3x3")(x)
        taps["Conv2d_1a_3x3"] = net
        net = _Conv(32, (3, 3), dtype=dt, width_mult=wm, name="Conv2d_2a_3x3")(net)
        net = _Conv(64, (3, 3), dtype=dt, width_mult=wm, name="Conv2d_2b_3x3")(net)
        net = _max_pool(net)
        taps["MaxPool_3a_3x3"] = net
        net = _Conv(80, dtype=dt, width_mult=wm, name="Conv2d_3b_1x1")(net)
        net = _Conv(192, (3, 3), dtype=dt, width_mult=wm, name="Conv2d_4a_3x3")(net)
        net = _max_pool(net)
        taps["MaxPool_5a_3x3"] = net
        net = _InceptionA(32, dtype=dt, width_mult=wm, name="Mixed_5b")(net)
        net = _InceptionA(64, dtype=dt, width_mult=wm, name="Mixed_5c")(net)
        net = _InceptionA(64, dtype=dt, width_mult=wm, name="Mixed_5d")(net)
        taps["Mixed_5d"] = net
        net = _ReductionA(dtype=dt, width_mult=wm, name="Mixed_6a")(net)
        net = _InceptionB(128, dtype=dt, width_mult=wm, name="Mixed_6b")(net)
        net = _InceptionB(160, dtype=dt, width_mult=wm, name="Mixed_6c")(net)
        net = _InceptionB(160, dtype=dt, width_mult=wm, name="Mixed_6d")(net)
        net = _InceptionB(192, dtype=dt, width_mult=wm, name="Mixed_6e")(net)
        taps["Mixed_6e"] = net
        net = _ReductionB(dtype=dt, width_mult=wm, name="Mixed_7a")(net)
        net = _InceptionC(dtype=dt, width_mult=wm, name="Mixed_7b")(net)
        net = _InceptionC(dtype=dt, width_mult=wm, name="Mixed_7c")(net)
        taps["Mixed_7c"] = net
        return taps


class InceptionV3Flow(nn.Module):
    flow_channels: int = 2
    dtype: Any = jnp.float32
    # Thin-variant channel multiplier (1.0 = exact reference widths,
    # 44.55M params — the param-parity pin). Sub-1 variants make the
    # flagship's learning properties affordable to rerun (DESIGN.md
    # "Learning evidence, r05").
    width_mult: float = 1.0

    flow_scales: tuple[float, ...] = FLOW_SCALES
    max_downsample = 32  # five stride-2 stages; spatial-CP gradient-safety bound

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> list[jnp.ndarray]:
        taps = InceptionV3Base(dtype=self.dtype, width_mult=self.width_mult,
                               name="encoder")(x)
        flows = FlowDecoder(
            upconv_features=tuple(scaled_width(f, self.width_mult)
                                  for f in (512, 256, 128, 64, 32)),
            scales=(2, 2, 1, 2, 2),  # Mixed_5d and MaxPool_5a share a size
            flow_channels=self.flow_channels,
            dtype=self.dtype,
            name="decoder",
        )([taps["Mixed_7c"], taps["Mixed_6e"], taps["Mixed_5d"],
           taps["MaxPool_5a_3x3"], taps["MaxPool_3a_3x3"], taps["Conv2d_1a_3x3"]])
        return flows[::-1]
