"""FlowNetCS — stacked flow refinement (FlowNet 2.0, arXiv:1612.01925 §3).

New capability beyond the reference (which stops at single-stage nets):
a FlowNet-C base estimate is upsampled to input resolution, frame 2 is
backward-warped by it (reusing `ops.warp.backward_warp`, the framework's
loss kernel), and a FlowNet-S refinement stage consumes
[img1, img2, warped img2, flow, brightness error] (12 channels) to
predict the residual-corrected pyramid.

Adaptation notes (documented divergences from the paper):
  - trained end-to-end with the unsupervised pyramid loss on the
    refinement stage's outputs — gradients reach the base network through
    the warp's flow input (the paper trains stages sequentially with
    supervised EPE; there is no ground truth in this framework's
    training regime);
  - 2-frame only (the multi-frame volume path pairs naturally with the
    single-stage models).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.warp import backward_warp
from .flownet_c import FlowNetC
from .flownet_s import FLOW_SCALES, FlowNetS


class FlowNetCS(nn.Module):
    flow_channels: int = 2
    max_disp: int = 20
    corr_stride: int = 2
    dtype: Any = jnp.float32

    flow_scales: tuple[float, ...] = FLOW_SCALES
    max_downsample = 64

    @nn.compact
    def __call__(self, pair: jnp.ndarray) -> list[jnp.ndarray]:
        if pair.shape[-1] != 6 or self.flow_channels != 2:
            raise ValueError(
                "FlowNetCS is a 2-frame model (6 input channels, 2 flow "
                f"channels); got input {pair.shape[-1]}ch / "
                f"{self.flow_channels} flow channels")
        b, h, w, _ = pair.shape
        img1, img2 = pair[..., :3], pair[..., 3:]

        base = FlowNetC(flow_channels=2, max_disp=self.max_disp,
                        corr_stride=self.corr_stride, dtype=self.dtype,
                        flow_scales=self.flow_scales, name="base")(pair)
        # finest base level lives at half resolution; x2 the vectors when
        # upsampling to input resolution (the eval-amplifier convention,
        # `flyingChairsTrain.py:264`)
        flow = base[0].astype(jnp.float32) * self.flow_scales[0]
        flow = jax.image.resize(flow, (b, h, w, 2), "bilinear") * 2.0

        warped = backward_warp(img2.astype(jnp.float32), flow)
        err = jnp.sqrt(jnp.sum(jnp.square(img1.astype(jnp.float32) - warped),
                               axis=-1, keepdims=True) + 1e-12)
        refine_in = jnp.concatenate(
            [img1, img2, warped.astype(self.dtype), flow.astype(self.dtype),
             err.astype(self.dtype)], axis=-1)
        return FlowNetS(flow_channels=2, dtype=self.dtype,
                        flow_scales=self.flow_scales,
                        name="refine")(refine_in)
