"""FlowNetCS — stacked flow refinement (FlowNet 2.0, arXiv:1612.01925 §3).

New capability beyond the reference (which stops at single-stage nets):
a FlowNet-C base estimate is upsampled to input resolution, frame 2 is
backward-warped by it (reusing `ops.warp.backward_warp`, the framework's
loss kernel), and a FlowNet-S refinement stage consumes
[img1, img2, warped img2, flow, brightness error] (12 channels) to
predict the residual-corrected pyramid.

The refinement stage is also exposed STANDALONE as `FlowNetRefine`: a
module that accepts an externally supplied prior flow instead of running
the base network — the serving warm-start path (serve/engine.py,
DESIGN.md "Temporal warm-start") feeds it the previous video frame's
flow so a streamed step skips the full cold network. `FlowNetRefine`
applies the SAME stage (same `refine`-scoped FlowNetS, same stacked
input built by `refinement_inputs`), so a trained FlowNetCS checkpoint's
`refine` subtree drops in as its params unchanged.

Adaptation notes (documented divergences from the paper):
  - trained end-to-end with the unsupervised pyramid loss on the
    refinement stage's outputs — gradients reach the base network through
    the warp's flow input (the paper trains stages sequentially with
    supervised EPE; there is no ground truth in this framework's
    training regime);
  - 2-frame only (the multi-frame volume path pairs naturally with the
    single-stage models);
  - `FlowNetRefine(residual=True)` (the standalone/warm-serving variant
    for models WITHOUT a trained refinement stage) follows FlowNet 2.0's
    warped-input increment formulation: the stage's pyramid is a gated
    correction ADDED to the prior, with the gate zero-initialized so an
    untrained stage is exactly the identity on its prior — the serving
    quality gate (`epe_vs_cold`) then measures temporal drift, never
    random-init noise, and training can grow the correction from a safe
    starting point.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.warp import backward_warp
from .flownet_c import FlowNetC
from .flownet_s import FLOW_SCALES, FlowNetS


def refinement_inputs(img1: jnp.ndarray, img2: jnp.ndarray,
                      flow: jnp.ndarray, dtype: Any) -> jnp.ndarray:
    """The FlowNet 2.0 stacked refinement input: [img1, img2,
    warp(img2, flow), flow, brightness error] — 12 channels at input
    resolution. `flow` must be at input resolution in input pixel units
    (already scale-applied). ONE definition shared by FlowNetCS (prior
    from its own base stage) and FlowNetRefine (prior supplied by the
    caller — the serving warm path), so the two stages see bitwise the
    same stacked input for the same (pair, prior)."""
    warped = backward_warp(img2.astype(jnp.float32), flow)
    err = jnp.sqrt(jnp.sum(jnp.square(img1.astype(jnp.float32) - warped),
                           axis=-1, keepdims=True) + 1e-12)
    return jnp.concatenate(
        [img1, img2, warped.astype(dtype), flow.astype(dtype),
         err.astype(dtype)], axis=-1)


class FlowNetCS(nn.Module):
    flow_channels: int = 2
    max_disp: int = 20
    corr_stride: int = 2
    dtype: Any = jnp.float32

    flow_scales: tuple[float, ...] = FLOW_SCALES
    max_downsample = 64

    @nn.compact
    def __call__(self, pair: jnp.ndarray) -> list[jnp.ndarray]:
        if pair.shape[-1] != 6 or self.flow_channels != 2:
            raise ValueError(
                "FlowNetCS is a 2-frame model (6 input channels, 2 flow "
                f"channels); got input {pair.shape[-1]}ch / "
                f"{self.flow_channels} flow channels")
        b, h, w, _ = pair.shape
        img1, img2 = pair[..., :3], pair[..., 3:]

        base = FlowNetC(flow_channels=2, max_disp=self.max_disp,
                        corr_stride=self.corr_stride, dtype=self.dtype,
                        flow_scales=self.flow_scales, name="base")(pair)
        # finest base level lives at half resolution; x2 the vectors when
        # upsampling to input resolution (the eval-amplifier convention,
        # `flyingChairsTrain.py:264`)
        flow = base[0].astype(jnp.float32) * self.flow_scales[0]
        flow = jax.image.resize(flow, (b, h, w, 2), "bilinear") * 2.0

        refine_in = refinement_inputs(img1, img2, flow, self.dtype)
        return FlowNetS(flow_channels=2, dtype=self.dtype,
                        flow_scales=self.flow_scales,
                        name="refine")(refine_in)


class FlowNetRefine(nn.Module):
    """The FlowNetCS refinement stage, standalone: (pair, prior flow) ->
    refined pyramid, no base network.

    `pair` is the engine's 6-channel preprocessed input; `prior` is a
    finest-head-resolution scaled flow — a previous dispatch's raw
    output, stored verbatim by the serving session (serve/session.py);
    see __call__. Params scope the inner FlowNetS as `refine`, so:

      residual=False — the stage predicts the corrected flow directly
          (FlowNetCS semantics); a trained `flownet_cs` checkpoint's
          `refine` subtree is exactly this module's params (the engine
          reuses it for warm serving of flownet_cs).
      residual=True  — the stage predicts a GATED correction added to
          the prior at every pyramid level (FlowNet 2.0's warped-input
          increment), with the scalar gate zero-initialized: an
          untrained stage reproduces its prior exactly. This is the
          variant the engine builds (deterministic seeded init, width
          scaled by serve.session.warm_width) for models without a
          trained refinement stage.
    """

    flow_channels: int = 2
    dtype: Any = jnp.float32
    width_mult: float = 1.0
    residual: bool = False

    flow_scales: tuple[float, ...] = FLOW_SCALES
    max_downsample = 64

    @nn.compact
    def __call__(self, pair: jnp.ndarray,
                 prior: jnp.ndarray) -> list[jnp.ndarray]:
        """`prior` is a FINEST-HEAD-resolution scaled flow — exactly a
        previous dispatch's `flows[0] * flow_scales[0]` (the serving
        session stores it verbatim, serve/session.py), the same
        half-resolution scale space FlowNetCS's base estimate lives in.
        The stage upsamples it to input resolution for the warp (the
        FlowNetCS x2 convention); keeping the prior on the head grid
        makes the residual identity EXACT at the finest level — no
        down/up resample loss can accumulate along a video walk."""
        if pair.shape[-1] != 6 or self.flow_channels != 2:
            raise ValueError(
                "FlowNetRefine is a 2-frame stage (6 input channels, 2 "
                f"flow channels); got input {pair.shape[-1]}ch / "
                f"{self.flow_channels} flow channels")
        if prior.shape[-1] != 2 or prior.shape[0] != pair.shape[0]:
            raise ValueError(
                f"prior flow must be (B, h, w, 2); got {prior.shape} "
                f"for pair {pair.shape}")
        b, h, w, _ = pair.shape
        ph, pw = prior.shape[1:3]
        img1, img2 = pair[..., :3], pair[..., 3:]
        prior = prior.astype(jnp.float32)
        # finest head lives at half resolution; x2 the vectors when
        # upsampling to input resolution (identical to FlowNetCS's
        # handling of its base estimate)
        flow_full = jax.image.resize(prior, (b, h, w, 2),
                                     "bilinear") * 2.0
        refine_in = refinement_inputs(img1, img2, flow_full, self.dtype)
        flows = FlowNetS(flow_channels=2, dtype=self.dtype,
                         width_mult=self.width_mult,
                         flow_scales=self.flow_scales,
                         name="refine")(refine_in)
        if not self.residual:
            return flows
        gate = self.param("gate", nn.initializers.zeros, (), jnp.float32)
        out = []
        for k, f in enumerate(flows):
            hk, wk = f.shape[1:3]
            if (hk, wk) == (ph, pw):
                # the finest level shares the prior's grid: no resample,
                # so gate=0 reproduces the prior exactly
                p = prior / self.flow_scales[k]
            else:
                # coarser levels: resize to the level's grid, rescale
                # vectors to level pixels, divide out the level's scale
                p = jax.image.resize(prior, (b, hk, wk, 2), "bilinear")
                p = p * (jnp.asarray([wk / pw, hk / ph], jnp.float32)
                         / self.flow_scales[k])
            out.append(gate * f.astype(jnp.float32) + p)
        return out
