"""UCF-101 action models: spatial classifier, STsingle, STbaseline.

Parity with `ucf101wrapFlow.py`:
  - `UCF101Spatial` (`:7-60`): plain VGG16 (ReLU) on a single frame +
    fc6(4096)/fc7(4096)/fc8(101) with dropout keep-prob 0.9; supervised
    cross-entropy only.
  - `STSingle` (`:62-194`): ONE shared VGG16 trunk (ELU) over the
    concatenated frame pair; spatial branch = fc head on pool5; temporal
    branch = 5 flow heads pr5..pr1 on pool5..pool1 (flow scales
    10/5/2.5/1.25/0.625 finest-first). Joint loss = weighted flow losses +
    weight[0] * action cross-entropy (`:186-188`) — assembled by the
    trainer, the model returns (flows, action_logits).
  - `STBaseline` (`:197-363`): independent FlowNet-S temporal trunk (6 flow
    heads) + VGG16 spatial trunk (ReLU, single frame); classifier consumes
    concat(pool5, Tconv5_2) -> 2x2 maxpool -> concat(., Tconv6_2) -> 1x1
    conv 512 -> fc head (`:330-337`).

Cross-entropy itself lives in `losses` land (optax), not in the model.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from .common import FlowDecoder, conv_init, flownet_trunk
from .flownet_s import FLOW_SCALES as FLOWNET_SCALES
from .vgg16_flow import FLOW_SCALES as VGG_SCALES
from .vgg16_flow import VGG16Trunk

_fc_init = nn.initializers.truncated_normal(0.01)


class _VGGReLUTrunk(nn.Module):
    """VGG16 conv trunk with ReLU + truncated-normal init (the classifier
    flavor, `ucf101wrapFlow.py:13-49`); returns [pool1..pool5]."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        pools = []
        for block, (feat, n) in enumerate(
            ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3)), start=1
        ):
            for i in range(1, n + 1):
                x = nn.Conv(feat, (3, 3), padding="SAME", kernel_init=_fc_init,
                            dtype=self.dtype, name=f"conv{block}_{i}")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="SAME")
            pools.append(x)
        return pools


class _FCHead(nn.Module):
    """flatten -> fc6 -> drop -> fc7 -> drop -> fc8(num_classes) logits."""

    num_classes: int = 101
    act: str = "relu"  # STsingle uses elu (arg_scope), classifier uses relu
    dropout_rate: float = 0.1  # slim keep_prob 0.9
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        act = nn.elu if self.act == "elu" else nn.relu
        init = _fc_init if self.act == "relu" else conv_init
        x = x.reshape(x.shape[0], -1)
        x = act(nn.Dense(4096, kernel_init=init, dtype=self.dtype, name="fc6")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = act(nn.Dense(4096, kernel_init=init, dtype=self.dtype, name="fc7")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, kernel_init=init, dtype=self.dtype,
                        name="fc8")(x)


class UCF101Spatial(nn.Module):
    num_classes: int = 101
    dtype: Any = jnp.float32

    classifier_only = True  # step dispatch: logits, no flow pyramid
    max_downsample = 32

    @nn.compact
    def __call__(self, frame: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        pools = _VGGReLUTrunk(dtype=self.dtype, name="encoder")(frame)
        return _FCHead(self.num_classes, dtype=self.dtype, name="head")(pools[-1], train)


class STSingle(nn.Module):
    """Shared-encoder two-stream model. Input: (B, H, W, 6) frame pair."""

    num_classes: int = 101
    flow_channels: int = 2
    dtype: Any = jnp.float32

    flow_scales: tuple[float, ...] = VGG_SCALES
    max_downsample = 32
    has_action_head = True  # step dispatch: returns (flows, logits)

    @nn.compact
    def __call__(self, pair: jnp.ndarray, train: bool = False):
        pools = VGG16Trunk(dtype=self.dtype, name="encoder")(pair)
        logits = _FCHead(self.num_classes, act="elu", dtype=self.dtype,
                         name="head")(pools[-1], train)
        flows = FlowDecoder(
            upconv_features=(256, 128, 64, 32),
            flow_channels=self.flow_channels,
            dtype=self.dtype,
            name="decoder",
        )(pools[::-1])
        return flows[::-1], logits


class STBaseline(nn.Module):
    """Two independent streams + temporal->classifier feature fusion.

    Input: (B, H, W, 6) frame pair; the spatial stream sees frame 1 only
    (`ucf101wrapFlow.py:281`).
    """

    num_classes: int = 101
    flow_channels: int = 2
    dtype: Any = jnp.float32

    flow_scales: tuple[float, ...] = FLOWNET_SCALES
    max_downsample = 64
    has_action_head = True  # step dispatch: returns (flows, logits)

    @nn.compact
    def __call__(self, pair: jnp.ndarray, train: bool = False):
        dt = self.dtype
        # temporal FlowNet-S trunk
        taps = flownet_trunk(pair, dt, prefix="Tconv")
        t5_2, t6_2 = taps[4], taps[5]

        flows = FlowDecoder(
            upconv_features=(512, 256, 128, 64, 32),
            flow_channels=self.flow_channels,
            dtype=dt,
            name="decoder",
        )(taps[::-1])

        # spatial VGG16 on frame 1
        pools = _VGGReLUTrunk(dtype=dt, name="spatial")(pair[..., :3])

        # fusion: concat(pool5, Tconv5_2) -> pool -> concat(., Tconv6_2) -> 1x1
        st = jnp.concatenate([pools[-1], t5_2], axis=-1)
        st = nn.max_pool(st, (2, 2), strides=(2, 2), padding="SAME")
        st = jnp.concatenate([st, t6_2], axis=-1)
        st = nn.relu(nn.Conv(512, (1, 1), kernel_init=_fc_init, dtype=dt,
                             name="fuse_1x1")(st))
        logits = _FCHead(self.num_classes, dtype=dt, name="head")(st, train)
        return flows[::-1], logits
