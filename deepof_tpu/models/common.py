"""Shared model building blocks.

Conventions (matching the reference's slim usage unless noted):
  - NHWC layout, SAME padding everywhere;
  - encoder/decoder convs use ELU activation in the flow models
    (`flyingChairsWrapFlow.py:28-29` arg_scope) except prediction (`pr*`) and
    flow-upsampling (`up_pr*`) layers which are linear;
  - conv kernels init with glorot-uniform (slim xavier default), zero biases;
  - transposed convs are 2*scale x 2*scale kernels with stride=scale;
    feature deconvs can be initialized to bilinear upsampling with identity
    channel mapping, the reference's `load_deconv_weights` behavior
    (`flyingChairsTrain.py:78-92`) expressed as a flax initializer.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

conv_init = nn.initializers.glorot_uniform()

Dtype = Any


def bilinear_upsample_kernel(kh: int, kw: int) -> np.ndarray:
    """(kh, kw) bilinear interpolation kernel (max 1 at the center)."""
    def axis(k):
        f = int(np.ceil(k / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        return 1 - np.abs(np.arange(k) / f - c)

    return np.outer(axis(kh), axis(kw))


def bilinear_kernel_init(key, shape, dtype=jnp.float32):
    """flax ConvTranspose kernel initializer: bilinear upsampling, identity
    across channels (zero between different in/out channels).

    shape = (kh, kw, in_features, out_features).
    """
    del key
    kh, kw, cin, cout = shape
    up = bilinear_upsample_kernel(kh, kw)
    k = np.zeros(shape, np.float32)
    for c in range(min(cin, cout)):
        k[:, :, c, c] = up
    return jnp.asarray(k, dtype)


class ConvELU(nn.Module):
    """3x3-style conv + ELU (slim conv2d with elu activation)."""

    features: int
    kernel: tuple[int, int] = (3, 3)
    stride: int = 1
    act: bool = True
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, self.kernel, strides=(self.stride, self.stride),
                    padding="SAME", kernel_init=conv_init, dtype=self.dtype)(x)
        return nn.elu(x) if self.act else x


class Deconv(nn.Module):
    """Transposed conv, kernel (2*scale, 2*scale), stride=scale.

    `bilinear_init=True` reproduces the reference's bilinear-upsampling
    initialization of the `upconv*`/`up_pr*` weights.
    """

    features: int
    scale: int = 2
    act: bool = True
    bilinear_init: bool = True
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        k = 2 * self.scale
        init = bilinear_kernel_init if self.bilinear_init else conv_init
        x = nn.ConvTranspose(self.features, (k, k),
                             strides=(self.scale, self.scale), padding="SAME",
                             kernel_init=init, dtype=self.dtype)(x)
        return nn.elu(x) if self.act else x


class FlowDecoder(nn.Module):
    """Generic multi-scale flow decoder (the pattern shared by every model:
    `flyingChairsWrapFlow.py:60-118`, `:689-739`, `:527-584`).

    Consumes encoder features coarsest-first. At each level k:
        pr_k    = 3x3 linear conv -> flow_channels
        feat    = concat(skip_{k-1}, Deconv(feat), Deconv_linear(pr_k))
    Levels may have per-level deconv scale (the Inception head uses scale=1
    between two same-resolution taps, `flyingChairsWrapFlow.py:551-556`).

    Returns flows coarsest-first; callers reverse to finest-first.
    """

    upconv_features: Sequence[int]  # feature deconv widths, one per transition
    scales: Sequence[int] | None = None  # deconv scales per transition (default 2)
    flow_channels: int = 2
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, feats_coarse_first: Sequence[jnp.ndarray]) -> list[jnp.ndarray]:
        n = len(feats_coarse_first)
        scales = self.scales or [2] * (n - 1)
        assert len(self.upconv_features) == n - 1 and len(scales) == n - 1
        flows = []
        feat = feats_coarse_first[0]
        for k in range(n - 1):
            pr = ConvELU(self.flow_channels, act=False, dtype=self.dtype,
                         name=f"pr{n - k}")(feat)
            flows.append(pr)
            up_feat = Deconv(self.upconv_features[k], scale=scales[k],
                             dtype=self.dtype, name=f"upconv{n - k - 1}")(feat)
            up_pr = Deconv(self.flow_channels, scale=scales[k], act=False,
                           dtype=self.dtype,
                           name=f"up_pr{n - k}to{n - k - 1}")(pr)
            # odd skip sizes: stride-2 deconvs overshoot by one — crop to the
            # skip resolution (standard FlowNet practice; the reference only
            # ever ran /64-divisible sizes and never hit this)
            skip = feats_coarse_first[k + 1]
            sh, sw = skip.shape[1:3]
            up_feat = up_feat[:, :sh, :sw]
            up_pr = up_pr[:, :sh, :sw]
            feat = jnp.concatenate([skip, up_feat, up_pr], axis=-1)
        flows.append(ConvELU(self.flow_channels, act=False, dtype=self.dtype,
                             name="pr1")(feat))
        return flows


def scaled_width(features: int, mult: float) -> int:
    """Channel width under a width multiplier (thin model variants); floor
    of 8 keeps every layer viable at small multipliers."""
    return max(int(features * mult), 8)


def flownet_tail(x, dtype: Dtype = jnp.float32, prefix: str = "conv",
                 width_mult: float = 1.0):
    """conv4_1..conv6_2 contracting tail (strides 2 at 4_1/5_1/6_1); returns
    (conv4_2, conv5_2, conv6_2). Called inside a parent @nn.compact so the
    layer names land in the caller's scope. Shared by FlowNet-S, FlowNet-C,
    and STBaseline's temporal trunk."""
    ch = lambda n: scaled_width(n, width_mult)  # noqa: E731
    c4_1 = ConvELU(ch(512), stride=2, dtype=dtype, name=f"{prefix}4_1")(x)
    c4_2 = ConvELU(ch(512), dtype=dtype, name=f"{prefix}4_2")(c4_1)
    c5_1 = ConvELU(ch(512), stride=2, dtype=dtype, name=f"{prefix}5_1")(c4_2)
    c5_2 = ConvELU(ch(512), dtype=dtype, name=f"{prefix}5_2")(c5_1)
    c6_1 = ConvELU(ch(1024), stride=2, dtype=dtype, name=f"{prefix}6_1")(c5_2)
    c6_2 = ConvELU(ch(1024), dtype=dtype, name=f"{prefix}6_2")(c6_1)
    return c4_2, c5_2, c6_2


def flownet_trunk(x, dtype: Dtype = jnp.float32, prefix: str = "conv",
                  width_mult: float = 1.0):
    """Full 10-conv FlowNet-S contracting trunk
    (`flyingChairsWrapFlow.py:31-40`); returns decoder taps coarsest-last:
    [conv1, conv2, conv3_2, conv4_2, conv5_2, conv6_2]. width_mult < 1
    builds the thin variant (same topology, scaled channels)."""
    ch = lambda n: scaled_width(n, width_mult)  # noqa: E731
    c1 = ConvELU(ch(64), (7, 7), 2, dtype=dtype, name=f"{prefix}1")(x)
    c2 = ConvELU(ch(128), (5, 5), 2, dtype=dtype, name=f"{prefix}2")(c1)
    c3_1 = ConvELU(ch(256), (5, 5), 2, dtype=dtype, name=f"{prefix}3_1")(c2)
    c3_2 = ConvELU(ch(256), dtype=dtype, name=f"{prefix}3_2")(c3_1)
    c4_2, c5_2, c6_2 = flownet_tail(c3_2, dtype, prefix, width_mult)
    return [c1, c2, c3_2, c4_2, c5_2, c6_2]


def count_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def load_vgg16_npz(
    params: dict,
    npz_path: str,
    trunk_path: Sequence[str] = ("encoder",),
    duplicate_input: bool = True,
) -> dict:
    """Initialize VGG16 trunk params from the public `vgg16_weights.npz`.

    Reference behavior (`flyingChairsTrain.py:60-76`): the 13 conv layers'
    weights are assigned in order; the first conv's filters are tiled x2
    along in-channels for the 6-channel (image-pair) input; fc layers are
    skipped. No download is attempted (zero-egress); callers must provide
    the file.
    """
    data = np.load(npz_path)
    new = jax.tree_util.tree_map(lambda x: x, params)  # rebuilt pytree, safe to mutate

    sub = new
    for p in trunk_path:
        sub = sub[p]

    names = [f"conv{b}_{i}" for b, n in zip(range(1, 6), (2, 2, 3, 3, 3))
             for i in range(1, n + 1)]
    for name in names:
        w, bias = data[f"{name}_W"], data[f"{name}_b"]
        # ConvELU trunks nest an nn.Conv as "Conv_0"; _VGGReLUTrunk names
        # nn.Conv layers directly (two_stream.py) — support both.
        tgt = sub[name].get("Conv_0", sub[name])
        if name == "conv1_1" and duplicate_input and tgt["kernel"].shape[2] == 2 * w.shape[2]:
            w = np.concatenate([w, w], axis=2)
        assert tgt["kernel"].shape == w.shape, (name, tgt["kernel"].shape, w.shape)
        tgt["kernel"] = jnp.asarray(w)
        tgt["bias"] = jnp.asarray(bias)
    return new
