"""VGG16-backbone flow model.

Parity with reference `VGG16` (`flyingChairsWrapFlow.py:635-749`): 13-conv
VGG16 trunk with 2x2 max-pools, 5 pyramid heads on pool5..pool1 with flow
scales 10/5/2.5/1.25/0.625 finest-first, decoder deconv widths
256/128/64/32. The reference pads its losses/flows lists to 6 entries by
repeating the coarsest — we return the true 5 scales (divergence documented;
the padding carried no information).

`VGG16Trunk` is reusable by the UCF-101 two-stream models, which tap pool5
(`ucf101wrapFlow.py:82-119`).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from .common import ConvELU, FlowDecoder

FLOW_SCALES = (10.0, 5.0, 2.5, 1.25, 0.625)  # finest (pr1) first

_VGG_CFG = ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3))


class VGG16Trunk(nn.Module):
    """conv1_1..conv5_3 + pools; returns [pool1..pool5]."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> list[jnp.ndarray]:
        pools = []
        for block, (feat, n) in enumerate(_VGG_CFG, start=1):
            for i in range(1, n + 1):
                x = ConvELU(feat, dtype=self.dtype, name=f"conv{block}_{i}")(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="SAME")
            pools.append(x)
        return pools


class VGG16Flow(nn.Module):
    flow_channels: int = 2
    dtype: Any = jnp.float32

    flow_scales: tuple[float, ...] = FLOW_SCALES
    max_downsample = 32  # five maxpools; spatial-CP gradient-safety bound

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> list[jnp.ndarray]:
        pools = VGG16Trunk(dtype=self.dtype, name="encoder")(x)
        flows = FlowDecoder(
            upconv_features=(256, 128, 64, 32),
            flow_channels=self.flow_channels,
            dtype=self.dtype,
            name="decoder",
        )(pools[::-1])
        return flows[::-1]
