"""FlowNet-Correlation flow model — new capability (BASELINE.json configs;
no reference implementation; architecture from the FlowNet paper,
arXiv:1504.06852 §3, adapted to this framework's SAME-padded ELU style).

Two siamese conv1..conv3 towers over each (preprocessed) frame, a
multiplicative correlation cost volume (max displacement 20, stride 2 ->
441 maps), a 1x1 `conv_redir` (32ch) of the first tower, then the FlowNet-S
contracting/expanding tail with 6 pyramid heads (same flow scales).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from ..ops.corr import correlation
from .common import ConvELU, FlowDecoder, flownet_tail, scaled_width
from .flownet_s import FLOW_SCALES


class FlowNetC(nn.Module):
    flow_channels: int = 2
    max_disp: int = 20
    corr_stride: int = 2
    dtype: Any = jnp.float32
    # Thin-variant channel multiplier (same role as FlowNetS.width_mult);
    # the correlation volume's (2K+1)^2 displacement channels are
    # architecture, not width, and never scale.
    width_mult: float = 1.0

    flow_scales: tuple[float, ...] = FLOW_SCALES
    max_downsample = 64  # conv1..conv6 stride-2 chain (same tail as FlowNet-S)

    @nn.compact
    def __call__(self, pair: jnp.ndarray) -> list[jnp.ndarray]:
        dt = self.dtype
        ch = lambda n: scaled_width(n, self.width_mult)  # noqa: E731
        img1, img2 = pair[..., :3], pair[..., 3:]

        conv1 = ConvELU(ch(64), (7, 7), 2, dtype=dt, name="conv1")
        conv2 = ConvELU(ch(128), (5, 5), 2, dtype=dt, name="conv2")
        conv3 = ConvELU(ch(256), (5, 5), 2, dtype=dt, name="conv3")
        c1 = conv1(img1)
        c2 = conv2(c1)
        f1 = conv3(c2)
        f2 = conv3(conv2(conv1(img2)))  # siamese: same modules, shared weights

        corr = nn.elu(correlation(f1, f2, self.max_disp, self.corr_stride))
        redir = ConvELU(ch(32), (1, 1), dtype=dt, name="conv_redir")(f1)
        net = jnp.concatenate([corr, redir], axis=-1)

        conv3_1 = ConvELU(ch(256), dtype=dt, name="conv3_1")(net)
        conv4_2, conv5_2, conv6_2 = flownet_tail(conv3_1, dt,
                                                 width_mult=self.width_mult)

        flows = FlowDecoder(
            upconv_features=tuple(ch(f) for f in (512, 256, 128, 64, 32)),
            flow_channels=self.flow_channels,
            dtype=dt,
            name="decoder",
        )([conv6_2, conv5_2, conv4_2, conv3_1, c2, c1])
        return flows[::-1]
