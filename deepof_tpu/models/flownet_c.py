"""FlowNet-Correlation flow model — new capability (BASELINE.json configs;
no reference implementation; architecture from the FlowNet paper,
arXiv:1504.06852 §3, adapted to this framework's SAME-padded ELU style).

Two siamese conv1..conv3 towers over each (preprocessed) frame, a
multiplicative correlation cost volume (max displacement 20, stride 2 ->
441 maps), a 1x1 `conv_redir` (32ch) of the first tower, then the FlowNet-S
contracting/expanding tail with 6 pyramid heads (same flow scales).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from ..ops.corr import correlation
from .common import ConvELU, FlowDecoder, flownet_tail
from .flownet_s import FLOW_SCALES


class FlowNetC(nn.Module):
    flow_channels: int = 2
    max_disp: int = 20
    corr_stride: int = 2
    dtype: Any = jnp.float32

    flow_scales: tuple[float, ...] = FLOW_SCALES
    max_downsample = 64  # conv1..conv6 stride-2 chain (same tail as FlowNet-S)

    @nn.compact
    def __call__(self, pair: jnp.ndarray) -> list[jnp.ndarray]:
        dt = self.dtype
        img1, img2 = pair[..., :3], pair[..., 3:]

        conv1 = ConvELU(64, (7, 7), 2, dtype=dt, name="conv1")
        conv2 = ConvELU(128, (5, 5), 2, dtype=dt, name="conv2")
        conv3 = ConvELU(256, (5, 5), 2, dtype=dt, name="conv3")
        c1 = conv1(img1)
        c2 = conv2(c1)
        f1 = conv3(c2)
        f2 = conv3(conv2(conv1(img2)))  # siamese: same modules, shared weights

        corr = nn.elu(correlation(f1, f2, self.max_disp, self.corr_stride))
        redir = ConvELU(32, (1, 1), dtype=dt, name="conv_redir")(f1)
        net = jnp.concatenate([corr, redir], axis=-1)

        conv3_1 = ConvELU(256, dtype=dt, name="conv3_1")(net)
        conv4_2, conv5_2, conv6_2 = flownet_tail(conv3_1, dt)

        flows = FlowDecoder(
            upconv_features=(512, 256, 128, 64, 32),
            flow_channels=self.flow_channels,
            dtype=dt,
            name="decoder",
        )([conv6_2, conv5_2, conv4_2, conv3_1, c2, c1])
        return flows[::-1]
