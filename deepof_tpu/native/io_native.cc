// Native host-side IO for the data pipeline: PPM (P6) / PNG / JPEG
// decode, Middlebury .flo parse, bilinear resize, and a persistent
// thread pool for batch assembly.
//
// The reference's loaders decode every image synchronously in Python per
// training step (`sintelLoader.py:85`, SURVEY.md §7.3.4) — at TPU step
// times the host becomes the bottleneck. This library decodes a whole
// batch in parallel outside the GIL; Python binds via ctypes
// (deepof_tpu/native/__init__.py), no pybind11 dependency.
//
// Build (full): g++ -O3 -shared -fPIC -std=c++17 -pthread
//   -DDEEPOF_HAVE_PNG -DDEEPOF_HAVE_JPEG io_native.cc -lpng -ljpeg
//   -o libdeepof_io.so
// Without the codec defines the library builds with PPM+.flo only
// (the Python side falls back to cv2 for PNG/JPEG).

#include <algorithm>
#include <atomic>
#include <cctype>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

#ifdef DEEPOF_HAVE_PNG
#include <png.h>
#endif
#ifdef DEEPOF_HAVE_JPEG
#include <csetjmp>

#include <jpeglib.h>
#endif

namespace {

// ---------------------------------------------------------------- thread pool
class ThreadPool {
 public:
  explicit ThreadPool(int n) {
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] {
        for (;;) {
          std::function<void()> job;
          {
            std::unique_lock<std::mutex> lk(mu_);
            cv_.wait(lk, [this] { return stop_ || !jobs_.empty(); });
            if (stop_ && jobs_.empty()) return;
            job = std::move(jobs_.front());
            jobs_.pop();
          }
          job();
        }
      });
    }
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

  void submit(std::function<void()> job) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      jobs_.push(std::move(job));
    }
    cv_.notify_one();
  }

 private:
  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> jobs_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_ = false;
};

ThreadPool* pool() {
  static ThreadPool p(std::max(2u, std::thread::hardware_concurrency() / 2));
  return &p;
}

// A simple countdown latch so one batch call can await all its jobs.
struct Latch {
  explicit Latch(int n) : remaining(n) {}
  void done() {
    std::lock_guard<std::mutex> lk(mu);
    if (--remaining == 0) cv.notify_all();
  }
  void wait() {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [this] { return remaining == 0; });
  }
  int remaining;
  std::mutex mu;
  std::condition_variable cv;
};

constexpr int kMaxDim = 1 << 16;
// Per-dim bounds alone still admit a 64k x 64k header (12.9 GB RGB) whose
// vector::resize would throw bad_alloc; bound total pixels too so corrupt
// headers fail the call instead of throwing (67M px ~ 201 MB RGB, far
// above any dataset frame).
constexpr size_t kMaxPixels = size_t{1} << 26;

bool dims_ok(int w, int h) {
  return w > 0 && h > 0 && w <= kMaxDim && h <= kMaxDim &&
         static_cast<size_t>(w) * h <= kMaxPixels;
}

// ------------------------------------------------------------------ PPM (P6)
bool read_ppm_dims(FILE* f, int* w, int* h) {
  char magic[3] = {0};
  if (fscanf(f, "%2s", magic) != 1 || strcmp(magic, "P6") != 0) return false;
  int vals[3], got = 0;
  while (got < 3) {
    int ch = fgetc(f);
    if (ch == EOF) return false;
    if (ch == '#') {  // comment to end of line
      while (ch != '\n' && ch != EOF) ch = fgetc(f);
      continue;
    }
    if (isspace(ch)) continue;
    ungetc(ch, f);
    if (fscanf(f, "%d", &vals[got]) != 1) return false;
    ++got;
  }
  fgetc(f);  // single whitespace before binary data
  if (vals[2] != 255) return false;
  // range-check: reject absurd/negative dims before any allocation (a
  // corrupt header must fail the call, not throw on a pool thread)
  if (!dims_ok(vals[0], vals[1])) return false;
  *w = vals[0];
  *h = vals[1];
  return true;
}

// decode one P6 stream (positioned at the magic) into uint8 RGB
bool decode_ppm_stream(FILE* f, std::vector<uint8_t>* buf, int* w, int* h) {
  if (!read_ppm_dims(f, w, h)) return false;
  size_t n = static_cast<size_t>(*w) * (*h) * 3;
  buf->resize(n);
  return fread(buf->data(), 1, n, f) == n;
}

// decode one P6 file into interleaved uint8 RGB (native size)
bool decode_ppm_file(const char* path, std::vector<uint8_t>* buf, int* w,
                     int* h) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  bool ok = decode_ppm_stream(f, buf, w, h);
  fclose(f);
  return ok;
}

#ifdef DEEPOF_HAVE_PNG
// decode one PNG stream (positioned at byte 0) via libpng's simplified API
bool decode_png_stream(FILE* f, std::vector<uint8_t>* buf, int* w, int* h) {
  png_image image;
  memset(&image, 0, sizeof image);
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_stdio(&image, f)) return false;
  image.format = PNG_FORMAT_RGB;
  *w = static_cast<int>(image.width);
  *h = static_cast<int>(image.height);
  if (!dims_ok(*w, *h)) {
    png_image_free(&image);
    return false;
  }
  buf->resize(PNG_IMAGE_SIZE(image));
  if (!png_image_finish_read(&image, nullptr, buf->data(), 0, nullptr)) {
    png_image_free(&image);
    return false;
  }
  return true;
}
#endif  // DEEPOF_HAVE_PNG

#ifdef DEEPOF_HAVE_JPEG
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  longjmp(reinterpret_cast<JpegErr*>(cinfo->err)->jb, 1);
}

// decode one JPEG stream (positioned at byte 0; libjpeg classic API;
// errors longjmp back instead of exiting the process)
bool decode_jpeg_stream(FILE* f, std::vector<uint8_t>* buf, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JpegErr err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = jpeg_err_exit;
  if (setjmp(err.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  if (!dims_ok(*w, *h) || cinfo.output_components != 3) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  buf->resize(static_cast<size_t>(*w) * (*h) * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row =
        buf->data() + static_cast<size_t>(cinfo.output_scanline) * (*w) * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}
#endif  // DEEPOF_HAVE_JPEG

enum class ImgFormat { kUnsupported, kPpm, kPng, kJpeg };

// the ONE magic-byte table (decode + the Python-side support probe)
ImgFormat sniff_format(const unsigned char sig[2]) {
  if (sig[0] == 'P' && sig[1] == '6') return ImgFormat::kPpm;
#ifdef DEEPOF_HAVE_PNG
  if (sig[0] == 0x89 && sig[1] == 'P') return ImgFormat::kPng;
#endif
#ifdef DEEPOF_HAVE_JPEG
  if (sig[0] == 0xFF && sig[1] == 0xD8) return ImgFormat::kJpeg;
#endif
  return ImgFormat::kUnsupported;
}

// dispatch PPM / PNG / JPEG by magic bytes; ONE open per file (the sniffed
// bytes are pushed back via rewind before the codec runs)
bool decode_image_file(const char* path, std::vector<uint8_t>* buf, int* w,
                       int* h) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  unsigned char sig[2] = {0, 0};
  if (fread(sig, 1, 2, f) != 2) {
    fclose(f);
    return false;
  }
  rewind(f);
  bool ok = false;
  switch (sniff_format(sig)) {
    case ImgFormat::kPpm:
      ok = decode_ppm_stream(f, buf, w, h);
      break;
#ifdef DEEPOF_HAVE_PNG
    case ImgFormat::kPng:
      ok = decode_png_stream(f, buf, w, h);
      break;
#endif
#ifdef DEEPOF_HAVE_JPEG
    case ImgFormat::kJpeg:
      ok = decode_jpeg_stream(f, buf, w, h);
      break;
#endif
    default:
      break;
  }
  fclose(f);
  return ok;
}

// -------------------------------------------------------- bilinear resize
// uint8 RGB (sh, sw) -> float32 (dh, dw), channel order swapped to BGR to
// match the reference's cv2 pipeline (`flyingChairsLoader.py:71-79`).
void resize_bilinear_bgr(const uint8_t* src, int sh, int sw, float* dst,
                         int dh, int dw) {
  if (sh == dh && sw == dw) {
    // identity: pure uint8 -> float32 + RGB->BGR swap, no interpolation
    // (the FlyingChairs default keeps the native 384x512 resolution)
    const size_t n = static_cast<size_t>(sh) * sw;
    for (size_t i = 0; i < n; ++i) {
      dst[i * 3 + 0] = src[i * 3 + 2];
      dst[i * 3 + 1] = src[i * 3 + 1];
      dst[i * 3 + 2] = src[i * 3 + 0];
    }
    return;
  }
  // per-x coefficients once per image, not per pixel (the float math and
  // clamping in the inner loop cost more than the blend itself)
  std::vector<int> x0v(dw), x1v(dw);
  std::vector<float> wxv(dw);
  const float ys = static_cast<float>(sh) / dh;
  const float xs = static_cast<float>(sw) / dw;
  for (int x = 0; x < dw; ++x) {
    // cv2-style half-pixel centers
    float fx = (x + 0.5f) * xs - 0.5f;
    int x0 = static_cast<int>(fx > 0 ? fx : 0);
    if (x0 > sw - 1) x0 = sw - 1;
    x0v[x] = x0 * 3;
    x1v[x] = (x0 + 1 < sw ? x0 + 1 : sw - 1) * 3;
    float wx = fx - x0;
    wxv[x] = wx < 0 ? 0 : wx;
  }
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * ys - 0.5f;
    int y0 = static_cast<int>(fy > 0 ? fy : 0);
    if (y0 > sh - 1) y0 = sh - 1;
    int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    float wy = fy - y0;
    if (wy < 0) wy = 0;
    const uint8_t* r0 = src + static_cast<size_t>(y0) * sw * 3;
    const uint8_t* r1 = src + static_cast<size_t>(y1) * sw * 3;
    float* out = dst + static_cast<size_t>(y) * dw * 3;
    for (int x = 0; x < dw; ++x) {
      const uint8_t* a = r0 + x0v[x];
      const uint8_t* b = r0 + x1v[x];
      const uint8_t* c = r1 + x0v[x];
      const uint8_t* d = r1 + x1v[x];
      const float wx = wxv[x];
      for (int ch = 0; ch < 3; ++ch) {
        float top = a[ch] + wx * (b[ch] - a[ch]);
        float bot = c[ch] + wx * (d[ch] - c[ch]);
        out[x * 3 + 2 - ch] = top + wy * (bot - top);  // RGB -> BGR
      }
    }
  }
}

constexpr float kFloMagic = 202021.25f;

}  // namespace

extern "C" {

// Decode one PPM to float32 BGR resized to (dh, dw). Returns 0 on success.
// try/catch: these are C-ABI entry points callable directly from ctypes —
// an exception (e.g. bad_alloc on a hostile header) must not unwind
// across the ABI and terminate the caller.
int deepof_decode_ppm(const char* path, float* out, int dh, int dw) {
  try {
    std::vector<uint8_t> buf;
    int w, h;
    if (!decode_ppm_file(path, &buf, &w, &h)) return 1;
    resize_bilinear_bgr(buf.data(), h, w, out, dh, dw);
    return 0;
  } catch (...) {
    return 2;
  }
}

// Decode one PPM/PNG/JPEG (dispatch by magic) to float32 BGR resized to
// (dh, dw). Returns 0 on success.
int deepof_decode_image(const char* path, float* out, int dh, int dw) {
  try {
    std::vector<uint8_t> buf;
    int w, h;
    if (!decode_image_file(path, &buf, &w, &h)) return 1;
    resize_bilinear_bgr(buf.data(), h, w, out, dh, dw);
    return 0;
  } catch (...) {
    return 2;
  }
}

// 1 iff this build can decode `path`'s format (by magic bytes).
int deepof_image_supported(const char* path) {
  unsigned char sig[2] = {0, 0};
  FILE* f = fopen(path, "rb");
  if (!f) return 0;
  size_t n = fread(sig, 1, 2, f);
  fclose(f);
  if (n < 2) return 0;
  return sniff_format(sig) != ImgFormat::kUnsupported ? 1 : 0;
}

// Decode a batch of images (mixed formats allowed) in parallel into
// (n, dh, dw, 3) float32 BGR. Returns number of failures.
int deepof_decode_image_batch(const char** paths, int n, float* out, int dh,
                              int dw) {
  Latch latch(n);
  std::atomic<int> failures{0};
  const size_t stride = static_cast<size_t>(dh) * dw * 3;
  for (int i = 0; i < n; ++i) {
    const char* p = paths[i];
    float* dst = out + stride * i;
    pool()->submit([p, dst, dh, dw, &latch, &failures] {
      try {
        if (deepof_decode_image(p, dst, dh, dw) != 0) failures++;
      } catch (...) {  // never let an exception escape a pool thread
        failures++;
      }
      latch.done();
    });
  }
  latch.wait();
  return failures.load();
}

// Probe a PPM's native dims.
int deepof_ppm_dims(const char* path, int* h, int* w) {
  FILE* f = fopen(path, "rb");
  if (!f) return 1;
  bool ok = read_ppm_dims(f, w, h);
  fclose(f);
  return ok ? 0 : 1;
}

// Decode a batch of PPMs (kept for ABI compat; the generic image batch
// dispatches PPM by magic bytes). Returns number of failures.
int deepof_decode_ppm_batch(const char** paths, int n, float* out, int dh,
                            int dw) {
  return deepof_decode_image_batch(paths, n, out, dh, dw);
}

// Middlebury .flo: magic float 202021.25, int32 w, int32 h, then
// h*w*2 little-endian float32 (u, v interleaved). Returns 0 on success.
int deepof_flo_dims(const char* path, int* h, int* w) {
  FILE* f = fopen(path, "rb");
  if (!f) return 1;
  float magic;
  int32_t ww, hh;
  bool ok = fread(&magic, 4, 1, f) == 1 && magic == kFloMagic &&
            fread(&ww, 4, 1, f) == 1 && fread(&hh, 4, 1, f) == 1;
  fclose(f);
  if (!ok) return 1;
  *w = ww;
  *h = hh;
  return 0;
}

int deepof_read_flo(const char* path, float* out, int h, int w) {
  FILE* f = fopen(path, "rb");
  if (!f) return 1;
  // validate the file's own header against the expected dims — the batch
  // API probes dims once from the first file; a mixed-resolution file must
  // fail loudly, not fread with the wrong row stride
  float magic;
  int32_t ww, hh;
  if (fread(&magic, 4, 1, f) != 1 || magic != kFloMagic ||
      fread(&ww, 4, 1, f) != 1 || fread(&hh, 4, 1, f) != 1 || ww != w ||
      hh != h) {
    fclose(f);
    return 1;
  }
  size_t n = static_cast<size_t>(h) * w * 2;
  bool ok = fread(out, 4, n, f) == n;
  fclose(f);
  return ok ? 0 : 1;
}

// Parallel batch .flo read into (n, h, w, 2) float32.
int deepof_read_flo_batch(const char** paths, int n, float* out, int h,
                          int w) {
  Latch latch(n);
  std::atomic<int> failures{0};
  const size_t stride = static_cast<size_t>(h) * w * 2;
  for (int i = 0; i < n; ++i) {
    const char* p = paths[i];
    float* dst = out + stride * i;
    pool()->submit([p, dst, h, w, &latch, &failures] {
      try {
        if (deepof_read_flo(p, dst, h, w) != 0) failures++;
      } catch (...) {
        failures++;
      }
      latch.done();
    });
  }
  latch.wait();
  return failures.load();
}

}  // extern "C"
