"""ctypes bindings for the native IO library (io_native.cc).

The shared library is built lazily with g++ on first use and cached next
to the source; everything degrades gracefully to the Python/cv2 path when
a toolchain is unavailable (`available()` returns False). No pybind11 —
plain C ABI + ctypes.

Thread-safety: the C++ side uses its own persistent thread pool and
touches no Python state, so batch calls release the GIL for their whole
duration (ctypes releases it around foreign calls) — decode overlaps
cleanly with the training step under the Prefetcher.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "io_native.cc")
_LIB_PATH = os.path.join(_HERE, "libdeepof_io.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_failed = False


def _build() -> bool:
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread"]
    # full build with PNG/JPEG codecs first; fall back to PPM-only when
    # the dev libraries are absent (the Python side keeps cv2 for the rest)
    variants = [
        base + ["-DDEEPOF_HAVE_PNG", "-DDEEPOF_HAVE_JPEG", _SRC,
                "-lpng", "-ljpeg", "-o", _LIB_PATH],
        base + ["-DDEEPOF_HAVE_PNG", _SRC, "-lpng", "-o", _LIB_PATH],
        base + ["-DDEEPOF_HAVE_JPEG", _SRC, "-ljpeg", "-o", _LIB_PATH],
        base + [_SRC, "-o", _LIB_PATH],
    ]
    for cmd in variants:
        try:
            res = subprocess.run(cmd, capture_output=True, timeout=180)
            if res.returncode == 0:
                return True
        except OSError:  # no g++ at all — no variant can succeed
            return False
        except subprocess.TimeoutExpired:
            continue  # loaded host: still try the cheaper PPM-only build
    return False


def _load() -> ctypes.CDLL | None:
    global _lib, _failed
    with _lock:
        if _lib is not None or _failed:
            return _lib
        if not os.path.exists(_LIB_PATH) or (
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
            if not _build():
                _failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _failed = True
            return None
        c_char_pp = ctypes.POINTER(ctypes.c_char_p)
        f32_p = ctypes.POINTER(ctypes.c_float)
        i32_p = ctypes.POINTER(ctypes.c_int)
        lib.deepof_decode_ppm.argtypes = [ctypes.c_char_p, f32_p,
                                          ctypes.c_int, ctypes.c_int]
        lib.deepof_ppm_dims.argtypes = [ctypes.c_char_p, i32_p, i32_p]
        lib.deepof_decode_ppm_batch.argtypes = [c_char_pp, ctypes.c_int,
                                                f32_p, ctypes.c_int,
                                                ctypes.c_int]
        lib.deepof_flo_dims.argtypes = [ctypes.c_char_p, i32_p, i32_p]
        lib.deepof_read_flo.argtypes = [ctypes.c_char_p, f32_p, ctypes.c_int,
                                        ctypes.c_int]
        lib.deepof_read_flo_batch.argtypes = [c_char_pp, ctypes.c_int, f32_p,
                                              ctypes.c_int, ctypes.c_int]
        lib.deepof_decode_image.argtypes = [ctypes.c_char_p, f32_p,
                                            ctypes.c_int, ctypes.c_int]
        lib.deepof_image_supported.argtypes = [ctypes.c_char_p]
        lib.deepof_decode_image_batch.argtypes = [c_char_pp, ctypes.c_int,
                                                  f32_p, ctypes.c_int,
                                                  ctypes.c_int]
        for fn in ("deepof_decode_ppm", "deepof_ppm_dims",
                   "deepof_decode_ppm_batch", "deepof_flo_dims",
                   "deepof_read_flo", "deepof_read_flo_batch",
                   "deepof_decode_image", "deepof_image_supported",
                   "deepof_decode_image_batch"):
            getattr(lib, fn).restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _paths_array(paths: list[str]):
    arr = (ctypes.c_char_p * len(paths))()
    arr[:] = [p.encode() for p in paths]
    return arr


def decode_ppm_batch(paths: list[str], size: tuple[int, int]) -> np.ndarray:
    """Parallel-decode PPMs to (N, H, W, 3) float32 BGR resized to `size`
    (the generic decoder dispatches PPM by magic bytes)."""
    return decode_image_batch(paths, size)


def decode_image_batch(paths: list[str], size: tuple[int, int]) -> np.ndarray:
    """Parallel-decode images (PPM/PNG/JPEG by magic bytes, mixed formats
    allowed) to (N, H, W, 3) float32 BGR resized to `size`."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    h, w = size
    out = np.empty((len(paths), h, w, 3), np.float32)
    failures = lib.deepof_decode_image_batch(
        _paths_array(paths), len(paths),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), h, w)
    if failures:
        raise IOError(f"native image decode failed for {failures} file(s) "
                      f"in batch of {len(paths)}")
    return out


def image_supported(path: str) -> bool:
    """True iff this build's codecs can decode `path` (by magic bytes)."""
    lib = _load()
    return bool(lib is not None and lib.deepof_image_supported(path.encode()))


def read_flo_batch(paths: list[str], size: tuple[int, int]) -> np.ndarray:
    """Parallel-read .flo files (all of shape `size`) to (N, H, W, 2)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    h, w = size
    out = np.empty((len(paths), h, w, 2), np.float32)
    failures = lib.deepof_read_flo_batch(
        _paths_array(paths), len(paths),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), h, w)
    if failures:
        raise IOError(f"native .flo read failed for {failures} file(s)")
    return out


def flo_dims(path: str) -> tuple[int, int]:
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    h, w = ctypes.c_int(), ctypes.c_int()
    if lib.deepof_flo_dims(path.encode(), ctypes.byref(h), ctypes.byref(w)):
        raise IOError(f"bad .flo file: {path}")
    return h.value, w.value
