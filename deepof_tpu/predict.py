"""Inference: run a trained flow model on image pairs, write `.flo` + visuals.

The reference has no standalone inference path — flow predictions only exist
inside the training/eval session loops (`flyingChairsTrain.py:216-296`,
`version1/testOF.py`). Decoupling the model from the loss graph
(SURVEY.md §7.1) makes this a plain forward pass: preprocess, apply, take
the finest pyramid flow, run the eval amplifier/clip/resize protocol, and
serialize with the (fixed) Middlebury writer — the reference's `writeFlow`
was dead code (`utils.py:44`, undefined TAG_CHAR).

Since the serving subsystem (DESIGN.md "Serving"), this module is a thin
offline frontend over `serve.engine.InferenceEngine`: pairs are submitted
to the dynamic micro-batcher and execute in device batches of up to
`serve.max_batch` instead of one dispatch per pair, and params restore
through the verified-checkpoint path (resilience layer) instead of a raw
orbax read.
"""

from __future__ import annotations

import os

import cv2
import numpy as np
import jax.numpy as jnp

from .core.config import ExperimentConfig
from .io.flo import write_flo
from .utils.flowviz import flow_to_color


def restore_params(cfg: ExperimentConfig):
    """Params from the newest VERIFIED checkpoint under
    cfg.train.log_dir (Trainer layout).

    Restore goes through the resilience layer's manifest verification
    (`train/checkpoint.py` + `resilience/verify.py`): a candidate whose
    manifest fails checksum/structure validation is skipped with a
    warning and the next-newest valid step restores instead — serving
    never loads a torn or bit-flipped checkpoint. Disable with
    resilience.verify_checkpoints=false.
    """
    from .serve.engine import build_serve_model
    from .train.checkpoint import CheckpointManager
    from .train.schedule import step_decay_schedule
    from .train.state import create_train_state, make_optimizer

    t = cfg.data.time_step
    model = build_serve_model(cfg)
    h, w = cfg.data.image_size  # eval-protocol resolution (val is uncropped)
    tx = make_optimizer(cfg.optim, step_decay_schedule(cfg.optim, 1))
    template = create_train_state(
        model, jnp.zeros((1, h, w, 3 * t)), tx, seed=0)
    ckpt_dir = cfg.train.log_dir + "/ckpt"
    mgr = CheckpointManager(ckpt_dir, async_save=False, create=False,
                            verify=cfg.resilience.verify_checkpoints)
    state = mgr.restore(template)
    if state is None:
        candidates = mgr.all_steps()
        if candidates:
            raise RuntimeError(
                f"checkpoints exist under {ckpt_dir} (steps {candidates}) "
                "but none restored — all candidates failed verification or "
                f"the read itself; run `python -m deepof_tpu verify-ckpt "
                f"{cfg.train.log_dir}` to see per-step corruption detail")
        raise FileNotFoundError(
            f"no checkpoint under {ckpt_dir} (run `python -m deepof_tpu "
            f"verify-ckpt {cfg.train.log_dir}` to inspect the directory)")
    return model, state.params


def write_outputs(out_dir: str, stem: str, flow: np.ndarray,
                  write_png: bool = True) -> list[str]:
    """Serialize one native-resolution flow: `.flo` (+ flow-color png).
    Shared by predict_pairs and the offline serve mode."""
    written = []
    flo_path = os.path.join(out_dir, f"{stem}_flow.flo")
    write_flo(flo_path, flow)
    written.append(flo_path)
    if write_png:
        png_path = os.path.join(out_dir, f"{stem}_flow.png")
        cv2.imwrite(png_path, flow_to_color(flow))
        written.append(png_path)
    return written


def output_stem(src_path: str, idx: int, many: bool) -> str:
    stem = os.path.splitext(os.path.basename(src_path))[0]
    # basenames may collide across dirs once there is more than one pair
    return f"{idx:04d}_{stem}" if many else stem


def predict_pairs(cfg: ExperimentConfig, pairs: list[tuple[str, str]],
                  out_dir: str, mean=None, write_png: bool = True,
                  model_params=None, precision: str | None = None
                  ) -> list[str]:
    """Predict flow for (prev, next) image-path pairs; returns written paths.

    The net runs at the request's shape bucket (default ladder: one
    bucket at cfg.data.image_size — the eval resolution); the output is
    amplified/clipped per the eval protocol (`flyingChairsTrain.py:
    264-296`), resized to the source image resolution, and — unlike the
    reference's AEE protocol, which resizes the flow *map* only — the
    u/v vectors are rescaled by (W_native/W_net, H_native/H_net) so the
    standalone `.flo` is in native pixel units.

    Execution goes through the serving engine: all pairs are enqueued up
    front and the micro-batcher coalesces them into device batches of up
    to `serve.max_batch` (one dispatch per flush instead of one per
    pair). Responses are bit-identical to the serial per-pair path at
    the same bucket (padded fixed-occupancy dispatch; pinned in tests).

    model_params: optional (model, params) overriding the checkpoint
    restore (tests; callers that already restored).
    precision: serving tier for every pair ("f32" | "bf16" | "int8";
    must be in cfg.serve.precisions — the engine owns one quantized
    params tree and one AOT executable per (bucket, tier)). None = the
    config's first tier.
    """
    from collections import deque

    from .serve.engine import InferenceEngine

    os.makedirs(out_dir, exist_ok=True)
    written: list[str] = []
    many = len(pairs) > 1
    with InferenceEngine(cfg, model_params=model_params, mean=mean) as eng:
        # bounded outstanding-futures window: a resolved future holds a
        # full native-resolution flow, so consuming-as-we-submit (not
        # after submitting everything) keeps host memory O(window) on
        # arbitrarily long pair lists — and overlaps writes with
        # in-flight inference
        window = max(4 * eng.max_batch, 16)
        buf: deque = deque()

        def drain_one() -> None:
            idx, src_path, fut = buf.popleft()
            flow = fut.result()["flow"]
            written.extend(write_outputs(
                out_dir, output_stem(src_path, idx, many), flow,
                write_png=write_png))

        for idx, (src, tgt) in enumerate(pairs):
            buf.append((idx, src, eng.submit(src, tgt,
                                             precision=precision)))
            if len(buf) >= window:
                drain_one()
        while buf:
            drain_one()
    return written
