"""Inference: run a trained flow model on image pairs, write `.flo` + visuals.

The reference has no standalone inference path — flow predictions only exist
inside the training/eval session loops (`flyingChairsTrain.py:216-296`,
`version1/testOF.py`). Decoupling the model from the loss graph
(SURVEY.md §7.1) makes this a plain forward pass: preprocess, apply, take
the finest pyramid flow, run the eval amplifier/clip/resize protocol, and
serialize with the (fixed) Middlebury writer — the reference's `writeFlow`
was dead code (`utils.py:44`, undefined TAG_CHAR).
"""

from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp

from .core.config import ExperimentConfig
from .data.datasets import _imread_bgr, _resize
from .io.flo import write_flo
from .losses.pyramid import preprocess
from .models.registry import build_model
from .train.evaluate import postprocess_flow
from .utils.flowviz import flow_to_color


def restore_params(cfg: ExperimentConfig):
    """Latest-checkpoint params from cfg.train.log_dir (Trainer layout)."""
    from .train.checkpoint import CheckpointManager
    from .train.schedule import step_decay_schedule
    from .train.state import create_train_state, make_optimizer

    t = cfg.data.time_step
    model = build_model(cfg.model, flow_channels=2 * (t - 1),
                        width_mult=cfg.width_mult,
                        corr_max_disp=cfg.corr_max_disp,
                        corr_stride=cfg.corr_stride)
    h, w = cfg.data.image_size  # eval-protocol resolution (val is uncropped)
    tx = make_optimizer(cfg.optim, step_decay_schedule(cfg.optim, 1))
    template = create_train_state(
        model, jnp.zeros((1, h, w, 3 * t)), tx, seed=0)
    state = CheckpointManager(cfg.train.log_dir + "/ckpt",
                          async_save=False).restore(template)
    if state is None:
        raise FileNotFoundError(
            f"no checkpoint under {cfg.train.log_dir}/ckpt")
    return model, state.params


def predict_pairs(cfg: ExperimentConfig, pairs: list[tuple[str, str]],
                  out_dir: str, mean=None,
                  write_png: bool = True) -> list[str]:
    """Predict flow for (prev, next) image-path pairs; returns written paths.

    The net runs at cfg.data.image_size (the eval resolution — val samples
    are never cropped); the output is amplified/clipped per the eval
    protocol (`flyingChairsTrain.py:264-296`), resized to the source image
    resolution, and — unlike the reference's AEE protocol, which resizes
    the flow *map* only — the u/v vectors are rescaled by (W_native/W_net,
    H_native/H_net) so the standalone `.flo` is in native pixel units.
    """
    from .data.datasets import DATASET_MEANS

    model, params = restore_params(cfg)
    mean = mean if mean is not None else DATASET_MEANS.get(
        cfg.data.dataset, DATASET_MEANS["flyingchairs"])
    h, w = cfg.data.image_size

    @jax.jit
    def fwd(params, pair):
        flows = model.apply({"params": params}, pair)
        return flows[0] * model.flow_scales[0]

    os.makedirs(out_dir, exist_ok=True)
    written = []
    for idx, (src_path, tgt_path) in enumerate(pairs):
        src_raw = _imread_bgr(src_path)
        native_hw = src_raw.shape[:2]
        src = _resize(src_raw, (h, w)).astype(np.float32)
        tgt = _resize(_imread_bgr(tgt_path), (h, w)).astype(np.float32)
        pair = jnp.concatenate(
            [preprocess(jnp.asarray(src[None]), mean),
             preprocess(jnp.asarray(tgt[None]), mean)], axis=-1)
        flow = np.asarray(fwd(params, pair))
        flow = postprocess_flow(flow, cfg, native_hw)[0, :, :, :2]
        flow[..., 0] *= native_hw[1] / w  # u: native horizontal px
        flow[..., 1] *= native_hw[0] / h  # v: native vertical px

        stem = os.path.splitext(os.path.basename(src_path))[0]
        if len(pairs) > 1:
            stem = f"{idx:04d}_{stem}"  # basenames may collide across dirs
        flo_path = os.path.join(out_dir, f"{stem}_flow.flo")
        write_flo(flo_path, flow)
        written.append(flo_path)
        if write_png:
            import cv2

            png_path = os.path.join(out_dir, f"{stem}_flow.png")
            cv2.imwrite(png_path, flow_to_color(flow))
            written.append(png_path)
    return written
