"""Inference: run a trained flow model on image pairs, write `.flo` + visuals.

The reference has no standalone inference path — flow predictions only exist
inside the training/eval session loops (`flyingChairsTrain.py:216-296`,
`version1/testOF.py`). Decoupling the model from the loss graph
(SURVEY.md §7.1) makes this a plain forward pass: preprocess, apply, take
the finest pyramid flow, run the eval amplifier/clip/resize protocol, and
serialize with the (fixed) Middlebury writer — the reference's `writeFlow`
was dead code (`utils.py:44`, undefined TAG_CHAR).

Since the serving subsystem (DESIGN.md "Serving"), this module is a thin
offline frontend over `serve.engine.InferenceEngine`: pairs are submitted
to the dynamic micro-batcher and execute in device batches of up to
`serve.max_batch` instead of one dispatch per pair, and params restore
through the verified-checkpoint path (resilience layer) instead of a raw
orbax read.
"""

from __future__ import annotations

import os

import cv2
import numpy as np
import jax.numpy as jnp

from .core.config import ExperimentConfig
from .io.flo import write_flo
from .utils.flowviz import flow_to_color


def _restore_verified(cfg: ExperimentConfig, model, channels: int,
                      ckpt_dir: str | None = None):
    """Params of `model` from the newest VERIFIED checkpoint under
    `ckpt_dir` (default: cfg.train.log_dir's Trainer layout).

    Restore goes through the resilience layer's manifest verification
    (`train/checkpoint.py` + `resilience/verify.py`): a candidate whose
    manifest fails checksum/structure validation is skipped with a
    warning and the next-newest valid step restores instead — serving
    never loads a torn or bit-flipped checkpoint. Disable with
    resilience.verify_checkpoints=false.
    """
    from .train.checkpoint import CheckpointManager
    from .train.schedule import step_decay_schedule
    from .train.state import create_train_state, make_optimizer

    h, w = cfg.data.image_size  # eval-protocol resolution (val is uncropped)
    tx = make_optimizer(cfg.optim, step_decay_schedule(cfg.optim, 1))
    template = create_train_state(
        model, jnp.zeros((1, h, w, channels)), tx, seed=0)
    ckpt_dir = ckpt_dir or cfg.train.log_dir + "/ckpt"
    mgr = CheckpointManager(ckpt_dir, async_save=False, create=False,
                            verify=cfg.resilience.verify_checkpoints)
    state = mgr.restore(template)
    if state is None:
        candidates = mgr.all_steps()
        if candidates:
            raise RuntimeError(
                f"checkpoints exist under {ckpt_dir} (steps {candidates}) "
                "but none restored — all candidates failed verification or "
                f"the read itself; run `python -m deepof_tpu verify-ckpt "
                f"{cfg.train.log_dir}` to see per-step corruption detail")
        raise FileNotFoundError(
            f"no checkpoint under {ckpt_dir} (run `python -m deepof_tpu "
            f"verify-ckpt {cfg.train.log_dir}` to inspect the directory)")
    return model, state.params


def restore_params(cfg: ExperimentConfig):
    """(model, params) for the flow predict/serve path — see
    `_restore_verified` for the verification contract."""
    from .serve.engine import build_serve_model

    return _restore_verified(cfg, build_serve_model(cfg),
                             3 * cfg.data.time_step)


def restore_action_params(cfg: ExperimentConfig, ckpt_dir: str | None = None):
    """(model, params) for the action predict path: the full training
    model (the checkpoint's exact param tree — the serve path's
    `build_serve_model` strips the action head, which is precisely the
    part this path needs).

    ckpt_dir: explicit checkpoint directory override — a recipe run's
    final stage lives under <log_dir>/ckpt-stage<i> (train/recipe.py),
    not the plain Trainer's <log_dir>/ckpt.
    """
    from .models.registry import build_model

    t = cfg.data.time_step
    dtype = (jnp.bfloat16 if cfg.train.compute_dtype == "bfloat16"
             else jnp.float32)
    model = build_model(cfg.model, flow_channels=2 * (t - 1), dtype=dtype,
                        width_mult=cfg.width_mult,
                        corr_max_disp=cfg.corr_max_disp,
                        corr_stride=cfg.corr_stride)
    if not (getattr(model, "has_action_head", False)
            or getattr(model, "classifier_only", False)):
        raise ValueError(
            f"model {cfg.model!r} has no action head — the action predict "
            "path needs st_single, st_baseline, or ucf101_spatial")
    channels = 3 if getattr(model, "classifier_only", False) else 3 * t
    return _restore_verified(cfg, model, channels, ckpt_dir=ckpt_dir)


def write_outputs(out_dir: str, stem: str, flow: np.ndarray,
                  write_png: bool = True) -> list[str]:
    """Serialize one native-resolution flow: `.flo` (+ flow-color png).
    Shared by predict_pairs and the offline serve mode."""
    written = []
    flo_path = os.path.join(out_dir, f"{stem}_flow.flo")
    write_flo(flo_path, flow)
    written.append(flo_path)
    if write_png:
        png_path = os.path.join(out_dir, f"{stem}_flow.png")
        cv2.imwrite(png_path, flow_to_color(flow))
        written.append(png_path)
    return written


def output_stem(src_path: str, idx: int, many: bool) -> str:
    stem = os.path.splitext(os.path.basename(src_path))[0]
    # basenames may collide across dirs once there is more than one pair
    return f"{idx:04d}_{stem}" if many else stem


def predict_pairs(cfg: ExperimentConfig, pairs: list[tuple[str, str]],
                  out_dir: str, mean=None, write_png: bool = True,
                  model_params=None, precision: str | None = None
                  ) -> list[str]:
    """Predict flow for (prev, next) image-path pairs; returns written paths.

    The net runs at the request's shape bucket (default ladder: one
    bucket at cfg.data.image_size — the eval resolution); the output is
    amplified/clipped per the eval protocol (`flyingChairsTrain.py:
    264-296`), resized to the source image resolution, and — unlike the
    reference's AEE protocol, which resizes the flow *map* only — the
    u/v vectors are rescaled by (W_native/W_net, H_native/H_net) so the
    standalone `.flo` is in native pixel units.

    Execution goes through the serving engine: all pairs are enqueued up
    front and the micro-batcher coalesces them into device batches of up
    to `serve.max_batch` (one dispatch per flush instead of one per
    pair). Responses are bit-identical to the serial per-pair path at
    the same bucket (padded fixed-occupancy dispatch; pinned in tests).

    model_params: optional (model, params) overriding the checkpoint
    restore (tests; callers that already restored).
    precision: serving tier for every pair ("f32" | "bf16" | "int8";
    must be in cfg.serve.precisions — the engine owns one quantized
    params tree and one AOT executable per (bucket, tier)). None = the
    config's first tier.
    """
    from collections import deque

    from .serve.engine import InferenceEngine

    os.makedirs(out_dir, exist_ok=True)
    written: list[str] = []
    many = len(pairs) > 1
    with InferenceEngine(cfg, model_params=model_params, mean=mean) as eng:
        # bounded outstanding-futures window: a resolved future holds a
        # full native-resolution flow, so consuming-as-we-submit (not
        # after submitting everything) keeps host memory O(window) on
        # arbitrarily long pair lists — and overlaps writes with
        # in-flight inference
        window = max(4 * eng.max_batch, 16)
        buf: deque = deque()

        def drain_one() -> None:
            idx, src_path, fut = buf.popleft()
            flow = fut.result()["flow"]
            written.extend(write_outputs(
                out_dir, output_stem(src_path, idx, many), flow,
                write_png=write_png))

        for idx, (src, tgt) in enumerate(pairs):
            buf.append((idx, src, eng.submit(src, tgt,
                                             precision=precision)))
            if len(buf) >= window:
                drain_one()
        while buf:
            drain_one()
    return written


def predict_action(cfg: ExperimentConfig, pairs: list[tuple[str, str]],
                   out_dir: str, model_params=None,
                   labels: list[str] | None = None, top_k: int = 5,
                   ckpt_dir: str | None = None) -> list[dict]:
    """Classify (prev, next) frame pairs with a trained action model
    (the UCF-101 workload: st_single / st_baseline two-stream heads, or
    the ucf101_spatial single-frame classifier — which ignores the
    `next` frame by construction).

    Each pair becomes one network input at cfg.data.image_size through
    the SAME preprocess the trainer applies (resize, BGR mean subtract,
    /255 — serve/buckets.py); the head's softmax yields the top_k
    classes. Returns the per-pair prediction rows and writes them to
    <out_dir>/actions.json.

    labels: optional class-name list (index order) to attach names.
    model_params: optional (model, params) override (tests; callers
    that already restored). ckpt_dir: see `restore_action_params`.
    """
    import json

    import jax

    from .data.datasets import DATASET_MEANS
    from .serve.buckets import prepare_frame, prepare_pair

    if model_params is not None:
        model, params = model_params
    else:
        model, params = restore_action_params(cfg, ckpt_dir=ckpt_dir)
    mean = DATASET_MEANS.get(cfg.data.dataset, DATASET_MEANS["flyingchairs"])
    h, w = cfg.data.image_size
    spatial_only = getattr(model, "classifier_only", False)

    @jax.jit
    def fwd(p, x):
        out = model.apply({"params": p}, x, train=False)
        logits = out if spatial_only else out[1]
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    rows: list[dict] = []
    for src_path, tgt_path in pairs:
        src, tgt = cv2.imread(src_path), cv2.imread(tgt_path)
        if src is None or tgt is None:
            missing = src_path if src is None else tgt_path
            raise FileNotFoundError(f"cannot read image {missing!r}")
        x = (prepare_frame(src, (h, w), mean) if spatial_only
             else prepare_pair(src, tgt, (h, w), mean))[None]
        probs = np.asarray(fwd(params, x))[0]
        order = np.argsort(probs)[::-1][: max(top_k, 1)]
        top = [{"class": int(i),
                **({"label": labels[i]} if labels and i < len(labels)
                   else {}),
                "prob": round(float(probs[i]), 6)} for i in order]
        rows.append({"source": src_path, "target": tgt_path,
                     **{k: top[0][k] for k in ("class", "label", "prob")
                        if k in top[0]},
                     "top": top})
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "actions.json"), "w") as f:
        json.dump(rows, f, indent=2)
    return rows
