"""Learning-rate schedules.

The reference multiplies LR by `decay_factor` every `num_epochs_per_decay`
epochs, feeding it through a placeholder (`flyingChairsTrain.py:27-33,124,
208-209`). Here it is a pure step->lr function handed to optax, so the
schedule state lives in the step counter and survives checkpoint/resume
(fixing the reference deficiency of restarting the LR schedule on resume,
SURVEY.md §5.4).
"""

from __future__ import annotations

from ..core.config import OptimConfig


def step_decay_schedule(cfg: OptimConfig, steps_per_epoch: int):
    """lr(step) = learning_rate * decay_factor ** (epoch // epochs_per_decay)."""
    spe = max(steps_per_epoch, 1)

    def schedule(step):
        epoch = step // spe
        return cfg.learning_rate * (cfg.decay_factor ** (epoch // cfg.epochs_per_decay))

    return schedule
