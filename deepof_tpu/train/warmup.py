"""Persistent compile cache + AOT warmup: start every process hot.

The TPU behind this repo is reached through a scarce, flaky tunnel
window (DESIGN.md "Benchmark honesty"); r03 lost a live window
*mid-compile* and r04 converted zero measurement attempts. The fix is
to make XLA compilation a once-per-config cost instead of a
once-per-process cost:

- `enable_compile_cache()` points jax's on-disk compilation cache at
  `artifacts/xla_cache/` (hostmesh.COMPILE_CACHE_DIR) and installs
  hit/miss counters, so "this process compiled nothing" is a checkable
  fact, not a hope.
- `warmup_compile(cfg)` AOT-lowers and compiles the train + eval
  executables for a config from shape specs alone — no training data
  movement, no step execution — populating the cache ahead of a tunnel
  window (`python -m deepof_tpu warmup ...`).

What the cache does and doesn't persist: entries are keyed by the
lowered HLO, compile options (shardings, donation), backend, and the
jax/XLA version — a config/jax upgrade misses cleanly (recompiles,
never loads stale executables), and CPU entries never serve TPU
processes. Cross-host reuse within the same ISA family works (observed
r03->r04 host change, benign feature-hint warning).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any

import jax
import numpy as np

from ..core.config import ExperimentConfig
from ..core.hostmesh import COMPILE_CACHE_DIR

# jax.monitoring event names emitted by jax/_src/compiler.py for every
# compile request that consults the persistent cache, and for each hit.
# misses = requests - hits (jax emits no dedicated miss event).
_EVENT_REQUESTS = "/jax/compilation_cache/compile_requests_use_cache"
_EVENT_HITS = "/jax/compilation_cache/cache_hits"

_counts = {"requests": 0, "hits": 0}
_listener_installed = False


def _on_event(event: str, **kw) -> None:
    if event == _EVENT_REQUESTS:
        _counts["requests"] += 1
    elif event == _EVENT_HITS:
        _counts["hits"] += 1


def install_cache_counters() -> None:
    """Idempotently register the hit/miss counting listener."""
    global _listener_installed
    if not _listener_installed:
        jax.monitoring.register_event_listener(_on_event)
        _listener_installed = True


def cache_stats() -> dict[str, int]:
    """Cumulative process-wide counters since install_cache_counters()."""
    return {"requests": _counts["requests"], "hits": _counts["hits"],
            "misses": _counts["requests"] - _counts["hits"]}


class cache_delta:
    """Measures cache activity of a code region: requests/hits/misses
    attributable to the region (counters are process-cumulative).
    Usable as a context manager (`with cache_delta() as d: ...;
    d.stats()`) or bare (`d = cache_delta(); ...; d.stats()`) — the
    snapshot is taken at construction and refreshed by __enter__."""

    def __init__(self) -> None:
        install_cache_counters()
        self._start = cache_stats()

    def __enter__(self) -> "cache_delta":
        self._start = cache_stats()
        return self

    def __exit__(self, *exc) -> None:
        self._end = cache_stats()

    def stats(self) -> dict[str, int]:
        end = getattr(self, "_end", None) or cache_stats()
        return {k: end[k] - self._start[k] for k in end}


def enable_compile_cache(cache_dir: str | None = None,
                         min_compile_time_secs: float = 1.0) -> str:
    """Enable jax's on-disk compilation cache and the hit/miss counters.

    min_compile_time_secs stays at jax's 1 s default: sub-second
    persistence was tried and reverted (hostmesh.py — serializing
    thousands of tiny CPU executables intermittently crashes jaxlib
    0.4.37), and the model/step compiles that dominate cold starts clear
    1 s on every backend. Safe to call repeatedly; changing the directory
    resets jax's cache singleton so the new location takes effect.
    """
    d = cache_dir or COMPILE_CACHE_DIR
    os.makedirs(d, exist_ok=True)
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_time_secs)
    # jax initializes its cache singleton AT MOST ONCE per process, bound
    # to whatever dir was configured at the first compile. Any jit that
    # ran before this call (CLI/import-time helpers) trips that latch
    # with no dir and silently disables caching for the rest of the
    # process — every "writing cache entry" after that is a no-op. Drop
    # the singleton whenever it isn't already live against `d` so the
    # next compile re-initializes with the configured dir.
    from jax._src import compilation_cache as _cc

    if prev != d or getattr(_cc, "_cache", None) is None:
        _cc.reset_cache()
    install_cache_counters()
    return d


def disable_compile_cache() -> None:
    """Turn the persistent cache off, including when a previous caller in
    this process enabled it (bench's _import_compute and the CPU test
    mesh enable unconditionally): unset the dir and drop jax's cache
    singleton so no further entries are read or written — the documented
    escape hatch for the jaxlib 0.4.37 cache-writer crash."""
    from jax._src import compilation_cache as _cc

    if jax.config.jax_compilation_cache_dir is not None:
        jax.config.update("jax_compilation_cache_dir", None)
        _cc.reset_cache()


def enable_for_config(cfg: ExperimentConfig) -> str | None:
    """Apply cfg.train.compile_cache / compile_cache_dir (Trainer entry).

    None = auto: on for accelerator backends, off on cpu — cross-process
    cache *reads* on this host's cpu jaxlib intermittently corrupt the
    heap (config.py compile_cache comment has the bisect evidence);
    writes are safe but pointless if nothing will read them.
    """
    if cfg.train.compile_cache is False:  # explicit off: tear down
        disable_compile_cache()
        return None
    if cfg.train.compile_cache is None and jax.default_backend() == "cpu":
        # auto-off: don't ENABLE, but leave ambient state alone — the
        # test suite's process-wide cache (hostmesh.force_cpu_devices)
        # must survive a default-config Trainer construction
        return None
    return enable_compile_cache(cfg.train.compile_cache_dir or None)


def _sds(tree: Any) -> Any:
    """Pytree of host arrays -> matching ShapeDtypeStructs."""
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype), tree)


def example_train_batch(cfg: ExperimentConfig, dataset) -> dict:
    """One host batch assembled exactly as Trainer.fit()'s producer does
    (sample -> optional augment -> K-stack), so the lowered avals — and
    therefore the compile-cache key — match the real first step.
    `test_warmup_then_trainer_compiles_nothing` pins this equivalence.
    """
    rng = np.random.RandomState(0)
    k = max(cfg.train.steps_per_call, 1)

    def one() -> dict:
        b = dataset.sample_train(cfg.data.batch_size, rng=rng)
        if cfg.data.augment_geo or cfg.data.augment_photo:
            from ..data.augmentation import make_augment_fn

            b = make_augment_fn(cfg.data)(b, np.int64(0))
        return {key: np.asarray(v) for key, v in b.items()}

    b = one()
    if k == 1:
        return b
    return {key: np.stack([v] * k) for key, v in b.items()}


def warmup_compile(cfg: ExperimentConfig, mesh=None, dataset=None,
                   include_eval: bool = True) -> dict:
    """AOT-compile the train (and optionally eval) executables for `cfg`.

    Pure ahead-of-time: state and batch enter as ShapeDtypeStructs
    (`jit(...).lower(specs).compile()`), so nothing executes on the
    device and no batch bytes move — only XLA runs, and its output lands
    in the persistent cache for every later process to load. Returns
    compile timings plus the cache hit/miss delta of this call: a warm
    cache shows misses == 0.
    """
    import jax.numpy as jnp

    from ..data import build_dataset
    from ..models.registry import build_model
    from ..parallel.mesh import build_mesh
    from .state import create_train_state, make_optimizer
    from .step import make_eval_fn, make_train_step

    enable_for_config(cfg)
    mesh = mesh if mesh is not None else build_mesh(cfg.mesh)
    dataset = dataset if dataset is not None else build_dataset(cfg.data)

    t = cfg.data.time_step
    dtype = (jnp.bfloat16 if cfg.train.compute_dtype == "bfloat16"
             else jnp.float32)
    model = build_model(cfg.model, flow_channels=2 * (t - 1), dtype=dtype,
                        width_mult=cfg.width_mult,
                        corr_max_disp=cfg.corr_max_disp,
                        corr_stride=cfg.corr_stride)
    from .schedule import step_decay_schedule

    steps_per_epoch = max(dataset.num_train // cfg.data.batch_size, 1)
    tx = make_optimizer(cfg.optim, step_decay_schedule(cfg.optim,
                                                       steps_per_epoch))
    h, w = cfg.data.crop_size or cfg.data.image_size
    channels = 3 if cfg.model == "ucf101_spatial" else 3 * t
    example = jax.ShapeDtypeStruct((cfg.data.batch_size, h, w, channels),
                                   jnp.float32)
    # abstract state: eval_shape traces create_train_state without
    # allocating params or touching the backend
    state_sds = jax.eval_shape(
        lambda x: create_train_state(model, x, tx, seed=cfg.train.seed),
        example)

    smooth_border = cfg.model in ("st_single", "st_baseline")
    step = make_train_step(model, cfg, dataset.mean, mesh, smooth_border)
    batch_sds = _sds(example_train_batch(cfg, dataset))

    out: dict[str, Any] = {"model": cfg.model,
                           "steps_per_call": max(cfg.train.steps_per_call, 1),
                           "backend": jax.default_backend(),
                           "cache_dir": jax.config.jax_compilation_cache_dir}
    # executable ledger (obs/ledger.py): every AOT compile below appends
    # a provenance row (StableHLO fingerprint, compile seconds, cache
    # hit/miss, cost analysis, memory footprint, donation map) to
    # <log_dir>/ledger.jsonl — the baseline a later run's `tail`/
    # ledger_diff drift verdict compares against
    from ..obs.ledger import ExecutableLedger

    ledger = ExecutableLedger(cfg.train.log_dir, enabled=cfg.obs.ledger,
                              backend=jax.default_backend())
    out["executables"] = []
    with cache_delta() as d:
        _, row = ledger.record_aot(
            "train_step", lambda: step.lower(state_sds, batch_sds))
        out["train_compile_s"] = row["compile_s"]
        out["executables"].append(_ledger_report_entry(row))

        if include_eval:
            # mirror Trainer.__init__'s eval_batch_size shard rounding so
            # the eval executable's avals match the real eval sweep
            shards = mesh.shape["data"]
            eval_bs = max(cfg.train.eval_batch_size // shards, 1) * shards
            eval_fn = make_eval_fn(model, cfg, dataset.mean, mesh=mesh,
                                   smooth_border_mask=smooth_border)
            eval_sds = _sds({key: np.asarray(v)
                             for key, v in dataset.sample_val(eval_bs, 0).items()})
            _, row = ledger.record_aot(
                "eval_step",
                lambda: eval_fn.lower(state_sds.params, eval_sds))
            out["eval_compile_s"] = row["compile_s"]
            out["executables"].append(_ledger_report_entry(row))
    out["cache"] = d.stats()
    return out


def _ledger_report_entry(row: dict) -> dict:
    """The per-executable line the warmup CLI report carries: name,
    compile seconds, fingerprint, and the compile's own cache verdict —
    a warm rerun that silently re-lowered one entry shows `misses: 1`
    (and, next run, a drifted fingerprint) right at the CLI."""
    return {"name": row["name"], "compile_s": row["compile_s"],
            "fingerprint": row["fingerprint"],
            "cache_hits": row["cache_hits"],
            "cache_misses": row["cache_misses"]}


def warmup_recipe(cfg: ExperimentConfig) -> dict:
    """AOT-compile every recipe stage's (train, eval) executable pair
    (`warmup` with recipe stages configured). One lower+compile pass
    per stage through `recipe.precompile_stages` populates the
    persistent cache and writes one `train_step_stage<i>` /
    `eval_step_stage<i>` ledger row per executable — the baseline
    ledger_diff later holds a recipe run against to prove its stage
    switches compiled nothing."""
    from .recipe import precompile_stages

    enable_for_config(cfg)
    _, report = precompile_stages(cfg)
    return report


def warmup_serve(cfg: ExperimentConfig) -> dict:
    """AOT-compile the serve ladder into the persistent cache
    (`warmup --serve`): one inference executable per configured
    (shape bucket, precision tier, dispatch mode) entry, lowered
    exactly as `serve/engine.py:_executable` lowers at runtime (shared
    `make_raw_forward`/`make_refine_forward` + `serve_avals`/
    `refine_serve_avals`, tier params avals derived through the same
    `quantize_params` transform — abstractly, via eval_shape), so a
    later engine's first request per (bucket, tier, mode) LOADS instead
    of compiling — zero first-request XLA across the whole lattice
    (pinned in tests/test_serve.py, tests/test_quant.py and
    tests/test_warm.py). The mode axis ({cold} or {cold, warm}) follows
    `serve.session.warm_start`: a warm-enabled config's FIRST warm step
    — the temporal warm-start refinement executable — is pre-lowered
    next to its cold siblings. When `obs.quality_sample_rate` > 0 the
    per-bucket label-free quality-scorer executables (obs/quality.py)
    are pre-lowered too, so sampled scoring on a cold endpoint loads
    instead of compiling.

    No checkpoint needed: params enter as ShapeDtypeStructs from an
    eval_shape of model.init — warmup compiles executables for a
    *config*, ahead of any trained weights existing.

    Each bucket entry reports ``persisted``: whether this bucket's
    executable is actually IN the on-disk cache after the call — either
    its compile wrote a new cache file, or the compile was already a
    cache hit. A compile that persists nothing (``persisted: false``,
    status ``skipped``) is the jax 1 s persistence floor at work:
    sub-second forwards (e.g. flownet_s fwd-only on this host) sit AT
    the floor and intermittently don't persist, and the floor must stay
    at jax's default (hostmesh segfault note). The zero-recompile test
    asserts against this report, not raw cache deltas — a skipped bucket
    legitimately recompiles in the next process.
    """
    import jax.numpy as jnp

    from ..obs.quality import make_score_fn, quality_avals
    from ..serve.buckets import resolve_buckets
    from ..serve.engine import (PAIR_CHANNELS, _lowered_out_hw,
                                build_refine_model, build_serve_model,
                                cold_output_hw, make_raw_forward,
                                make_refine_forward, refine_serve_avals,
                                serve_avals)
    from ..serve.quant import quantize_params, resolve_precisions

    enable_for_config(cfg)
    model = build_serve_model(cfg)
    buckets = resolve_buckets(cfg)
    tiers = resolve_precisions(cfg)
    modes = (("cold", "warm") if cfg.serve.session.warm_start
             else ("cold",))
    max_batch = max(cfg.serve.max_batch, 1)
    fwd = jax.jit(make_raw_forward(model))
    refine_model = refine_fwd = None
    if "warm" in modes:
        refine_model = build_refine_model(cfg)
        refine_fwd = jax.jit(make_refine_forward(refine_model))
    # quality-scorer executables (obs/quality.py) ride the same warmup:
    # one per bucket (tiers/modes share it — f32 in, f32 flow in), same
    # make_score_fn + quality_avals lowering the engine uses at runtime,
    # so a sampled request on a cold endpoint LOADS its scorer
    score_jit = (jax.jit(make_score_fn())
                 if float(cfg.obs.quality_sample_rate) > 0 else None)

    # executable ledger (obs/ledger.py): one provenance row per lattice
    # entry, same naming scheme the engine uses at runtime — the
    # committed-baseline side of the ledger_diff drift gate
    from ..obs.ledger import (ExecutableLedger, exec_name,
                              quality_exec_name)
    from ..serve.artifacts import (params_aval_sig, resolution_key,
                                   serve_config_digest, store_for_config,
                                   write_index)

    ledger = ExecutableLedger(cfg.train.log_dir, enabled=cfg.obs.ledger,
                              backend=jax.default_backend())
    # artifact plane (serve/artifacts.py): `warmup --serve` is the
    # SINGLE WRITER — every freshly compiled lattice entry is
    # serialized + atomically published under its StableHLO
    # fingerprint, and a re-run against a warm store fetches instead of
    # compiling (compile_kind "artifact"), which is also the publish
    # idempotence proof. Next to the per-fingerprint entries it
    # publishes the executable INDEX (atomic-rename index.json): each
    # entry's jax-free resolution key -> the fingerprint this run
    # lowered, so a later engine/replica boots the whole lattice with
    # zero trace/lower calls (serve/engine.py `_resolve_index`).
    store = store_for_config(cfg)
    cfg_digest = serve_config_digest(cfg) if store is not None else None
    index_entries: dict[str, dict] = {}

    def _index(name, row, art, params_sds, bucket, extra_meta=None):
        """Stage one index entry: only executables that are actually IN
        the store (fresh publish, prior entry, or fingerprint hit) get
        indexed — an index entry whose target is absent would be a
        stale-target reject at every boot."""
        if store is None or not row["fingerprint"]:
            return
        if art not in ("hit", "published", "exists"):
            return
        x_aval = ("__x__",
                  (max_batch, bucket[0], bucket[1], PAIR_CHANNELS),
                  "float32")
        sig = params_aval_sig(params_sds, extra=(x_aval,))
        key = resolution_key(name, cfg_digest, sig,
                             store.backend or jax.default_backend(),
                             jax.__version__)
        ent = {"name": name, "fingerprint": row["fingerprint"],
               "config_digest": cfg_digest, "aval_sig": sig,
               "backend": store.backend or jax.default_backend(),
               "jax": jax.__version__, "created": time.time()}
        if extra_meta:
            ent.update(extra_meta)
        index_entries[key] = ent

    def _aot(name, lower_fn):
        compiled, row = ledger.record_aot(name, lower_fn, artifacts=store)
        art = None
        if store is not None:
            if row["compile_kind"] == "artifact":
                art = "hit"
            elif row["fingerprint"]:
                art = store.publish(
                    row["fingerprint"], compiled, name=name,
                    compile_s=row["compile_s"],
                    meta={"donated_args": row["donated_args"],
                          "num_args": row["num_args"]})
            else:
                art = "error:no_fingerprint"
        return row, art
    out: dict[str, Any] = {"model": cfg.model, "max_batch": max_batch,
                           "backend": jax.default_backend(),
                           "cache_dir": jax.config.jax_compilation_cache_dir,
                           "tiers": list(tiers),
                           "modes": list(modes),
                           "buckets": []}
    # everything inside the delta must be the bucket executables and
    # nothing else: abstract init (eval_shape over ShapeDtypeStructs
    # executes nothing) keeps helper compiles (zeros fills, PRNG setup)
    # from polluting the hit/miss pin
    key_sds = jax.ShapeDtypeStruct((2,), np.uint32)
    cache_dir = jax.config.jax_compilation_cache_dir

    def _entries() -> set[str]:
        try:
            return set(os.listdir(cache_dir)) if cache_dir else set()
        except OSError:
            return set()

    with cache_delta() as d:
        for bucket in buckets:
            h, w = bucket
            variables_sds = jax.eval_shape(
                model.init, key_sds,
                jax.ShapeDtypeStruct((1, h, w, PAIR_CHANNELS), jnp.float32))
            refine_vars_sds = None
            if refine_model is not None:
                # the refinement stage's params AVALS, abstractly: for
                # flownet_cs this equals the checkpoint's `refine`
                # subtree by construction (same module, same scope);
                # for other models it matches the engine's seeded init
                refine_vars_sds = jax.eval_shape(
                    refine_model.init, key_sds,
                    jax.ShapeDtypeStruct((1, h, w, PAIR_CHANNELS),
                                         jnp.float32),
                    jax.ShapeDtypeStruct((1, h, w, 2), jnp.float32))
            # the cold head grid is dtype-independent: derive it ONCE
            # per bucket (one eval_shape) and share it across every
            # tier's warm entry and the bucket's quality scorer — each
            # formerly paid its own trace of the full cold network
            bucket_hw: tuple[int, int] | None = None
            for tier in tiers:
                # the tier's params AVALS through the same transform the
                # engine applies to real weights — abstract, so no
                # weight bytes materialize and no helper compiles leak
                # into the delta
                cold_tier_sds = jax.eval_shape(
                    lambda p, _t=tier: quantize_params(p, _t),
                    variables_sds["params"])
                for mode in modes:
                    before_files = _entries()
                    name = exec_name(bucket, tier, mode)
                    idx_meta = None
                    if mode == "cold":
                        params_sds, x_sds = serve_avals(
                            cold_tier_sds, bucket, max_batch)
                        row, art = _aot(
                            name,
                            lambda: fwd.lower(params_sds, x_sds))
                        index_params = cold_tier_sds
                    else:
                        refine_tier_sds = jax.eval_shape(
                            lambda p, _t=tier: quantize_params(p, _t),
                            refine_vars_sds["params"])
                        if bucket_hw is None:
                            bucket_hw = tuple(cold_output_hw(
                                fwd, cold_tier_sds, bucket, max_batch))
                        prior_hw = bucket_hw
                        params_sds, x_sds, prior_sds = refine_serve_avals(
                            refine_tier_sds, bucket, max_batch, prior_hw)

                        def lower_checked(_p=params_sds, _x=x_sds,
                                          _pr=prior_sds, _hw=prior_hw):
                            lowered = refine_fwd.lower(_p, _x, _pr)
                            # mirror the engine's prior-chain shape
                            # check off the lowering's OWN out_info —
                            # one shared `lowered` per entry across the
                            # grid check, fingerprint, ledger row, and
                            # compile (no second trace); a config the
                            # engine would reject must fail warmup
                            # identically, not silently pre-compile
                            out_hw = _lowered_out_hw(lowered)
                            if out_hw != tuple(_hw):
                                raise ValueError(
                                    f"warm_start unsupported for model "
                                    f"{cfg.model!r} at bucket {bucket}: "
                                    f"refinement head grid {out_hw} != "
                                    f"cold head grid {tuple(_hw)}")
                            return lowered

                        row, art = _aot(name, lower_checked)
                        index_params = refine_tier_sds
                        idx_meta = {"prior_hw": list(prior_hw)}
                    _index(name, row, art, index_params, bucket,
                           extra_meta=idx_meta)
                    hits = row["cache_hits"] or 0
                    # persisted = a new on-disk entry appeared
                    # (filesystem truth, not the counter's hope) OR the
                    # compile was already a hit (the entry predates this
                    # call). Neither => the 1 s floor swallowed it:
                    # compiled fine, persisted nothing.
                    wrote = bool(_entries() - before_files)
                    persisted = wrote or hits >= 1
                    entry = {"bucket": [h, w], "tier": tier, "mode": mode,
                             "compile_s": row["compile_s"],
                             "fingerprint": row["fingerprint"],
                             "persisted": persisted,
                             "status": ("hit" if hits >= 1
                                        else "persisted" if wrote
                                        else "skipped")}
                    if art is not None:
                        entry["artifact"] = art
                    out["buckets"].append(entry)
            if score_jit is not None:
                # the bucket's quality scorer: flow grid derived from
                # the DEFAULT tier's cold executable, exactly as
                # engine._score_executable derives it at runtime
                tier0_sds = jax.eval_shape(
                    lambda p: quantize_params(p, tiers[0]),
                    variables_sds["params"])
                before_files = _entries()
                if bucket_hw is None:
                    bucket_hw = tuple(cold_output_hw(
                        fwd, tier0_sds, bucket, max_batch))
                flow_hw = bucket_hw
                x_sds, flow_sds = quality_avals(bucket, flow_hw)
                row, art = _aot(
                    quality_exec_name(bucket),
                    lambda: score_jit.lower(x_sds, flow_sds))
                _index(quality_exec_name(bucket), row, art, tier0_sds,
                       bucket, extra_meta={"flow_hw": list(flow_hw)})
                hits = row["cache_hits"] or 0
                wrote = bool(_entries() - before_files)
                persisted = wrote or hits >= 1
                entry = {"bucket": [h, w], "tier": "-", "mode": "quality",
                         "compile_s": row["compile_s"],
                         "fingerprint": row["fingerprint"],
                         "persisted": persisted,
                         "status": ("hit" if hits >= 1
                                    else "persisted" if wrote
                                    else "skipped")}
                if art is not None:
                    entry["artifact"] = art
                out["buckets"].append(entry)
    out["cache"] = d.stats()
    out["persisted_buckets"] = sum(b["persisted"] for b in out["buckets"])
    out["skipped_buckets"] = sum(not b["persisted"] for b in out["buckets"])
    if store is not None:
        arts = [b.get("artifact") for b in out["buckets"]]
        out["artifacts"] = {
            "dir": store.root,
            "published": sum(1 for a in arts if a == "published"),
            "exists": sum(1 for a in arts if a == "exists"),
            "hits": sum(1 for a in arts if a == "hit"),
            "errors": sum(1 for a in arts
                          if isinstance(a, str) and a.startswith("error")),
        }
        # the executable index: ONE atomic rename after the whole
        # lattice published (readers see the old complete index until
        # the new complete one lands — never a partial lattice)
        try:
            write_index(store.root, index_entries)
            out["artifacts"]["index_entries"] = len(index_entries)
            out["artifacts"]["config_digest"] = cfg_digest
        except OSError as e:
            print(f"warmup: index publish failed: {e}", file=sys.stderr)
            out["artifacts"]["index_entries"] = 0
    return out


def deep_verify_serve(cfg: ExperimentConfig) -> dict:
    """Offline deep audit of the executable index (`deepof_tpu
    artifacts verify --deep`): re-lower every lattice entry THIS config
    would serve — the full bucket x tier x mode ladder plus quality
    scorers, exactly the `warmup_serve` lowerings — and compare each
    local StableHLO fingerprint against what the index maps that
    entry's resolution key to. This is the same check the engine's
    background deep-verify plane performs behind live serving, run
    ahead of deployment instead: ``drift`` entries are executables an
    index boot would serve stale (until demoted), ``unindexed`` ones
    would miss to the compile path. Nothing is published or repaired —
    re-run `warmup --serve` for that."""
    import jax.numpy as jnp

    from ..obs.ledger import (exec_name, fingerprint_text,
                              quality_exec_name)
    from ..obs.quality import make_score_fn, quality_avals
    from ..serve.artifacts import (params_aval_sig, resolution_key,
                                   serve_config_digest, store_for_config)
    from ..serve.buckets import resolve_buckets
    from ..serve.engine import (PAIR_CHANNELS, build_refine_model,
                                build_serve_model, cold_output_hw,
                                make_raw_forward, make_refine_forward,
                                refine_serve_avals, serve_avals)
    from ..serve.quant import quantize_params, resolve_precisions

    store = store_for_config(cfg)
    if store is None:
        raise ValueError("artifacts verify --deep needs "
                         "serve.artifacts_dir (or --dir) set")
    cfg_digest = serve_config_digest(cfg)
    model = build_serve_model(cfg)
    buckets = resolve_buckets(cfg)
    tiers = resolve_precisions(cfg)
    modes = (("cold", "warm") if cfg.serve.session.warm_start
             else ("cold",))
    max_batch = max(cfg.serve.max_batch, 1)
    fwd = jax.jit(make_raw_forward(model))
    refine_model = refine_fwd = None
    if "warm" in modes:
        refine_model = build_refine_model(cfg)
        refine_fwd = jax.jit(make_refine_forward(refine_model))
    score_jit = (jax.jit(make_score_fn())
                 if float(cfg.obs.quality_sample_rate) > 0 else None)
    backend = store.backend or jax.default_backend()
    key_sds = jax.ShapeDtypeStruct((2,), np.uint32)

    entries: list[dict] = []

    def _check(name, params_sds, bucket, lowered):
        x_aval = ("__x__",
                  (max_batch, bucket[0], bucket[1], PAIR_CHANNELS),
                  "float32")
        sig = params_aval_sig(params_sds, extra=(x_aval,))
        key = resolution_key(name, cfg_digest, sig, backend,
                             jax.__version__)
        local_fp = fingerprint_text(lowered.as_text())
        ent = store.index_entry(key) or {}
        indexed_fp = ent.get("fingerprint")
        status = ("unindexed" if indexed_fp is None
                  else "ok" if indexed_fp == local_fp else "drift")
        entries.append({"name": name, "key": key,
                        "indexed": indexed_fp, "local": local_fp,
                        "status": status})

    for bucket in buckets:
        h, w = bucket
        variables_sds = jax.eval_shape(
            model.init, key_sds,
            jax.ShapeDtypeStruct((1, h, w, PAIR_CHANNELS), jnp.float32))
        refine_vars_sds = None
        if refine_fwd is not None:
            refine_vars_sds = jax.eval_shape(
                refine_model.init, key_sds,
                jax.ShapeDtypeStruct((1, h, w, PAIR_CHANNELS),
                                     jnp.float32),
                jax.ShapeDtypeStruct((1, h, w, 2), jnp.float32))
        bucket_hw = None
        for tier in tiers:
            cold_tier_sds = jax.eval_shape(
                lambda p, _t=tier: quantize_params(p, _t),
                variables_sds["params"])
            for mode in modes:
                name = exec_name(bucket, tier, mode)
                if mode == "cold":
                    params_sds, x_sds = serve_avals(
                        cold_tier_sds, bucket, max_batch)
                    lowered = fwd.lower(params_sds, x_sds)
                    _check(name, cold_tier_sds, bucket, lowered)
                else:
                    refine_tier_sds = jax.eval_shape(
                        lambda p, _t=tier: quantize_params(p, _t),
                        refine_vars_sds["params"])
                    if bucket_hw is None:
                        bucket_hw = tuple(cold_output_hw(
                            fwd, cold_tier_sds, bucket, max_batch))
                    params_sds, x_sds, prior_sds = refine_serve_avals(
                        refine_tier_sds, bucket, max_batch, bucket_hw)
                    lowered = refine_fwd.lower(params_sds, x_sds,
                                               prior_sds)
                    _check(name, refine_tier_sds, bucket, lowered)
        if score_jit is not None:
            tier0_sds = jax.eval_shape(
                lambda p: quantize_params(p, tiers[0]),
                variables_sds["params"])
            if bucket_hw is None:
                bucket_hw = tuple(cold_output_hw(
                    fwd, tier0_sds, bucket, max_batch))
            x_sds, flow_sds = quality_avals(bucket, bucket_hw)
            lowered = score_jit.lower(x_sds, flow_sds)
            _check(quality_exec_name(bucket), tier0_sds, bucket, lowered)

    return {
        "dir": store.root,
        "backend": backend,
        "config_digest": cfg_digest,
        "entries": entries,
        "total": len(entries),
        "ok": sum(1 for e in entries if e["status"] == "ok"),
        "drift": [e["name"] for e in entries if e["status"] == "drift"],
        "unindexed": [e["name"] for e in entries
                      if e["status"] == "unindexed"],
    }
