"""Evaluation protocols.

Reproduces the reference's AEE measurement conventions exactly
(SURVEY.md §6): the finest prediction (already multiplied by its
flow_scale) is multiplied by the dataset `eval_amplifier`, clipped to
`eval_clip`, bilinearly resized to the native ground-truth resolution, and
compared against GT flow with mean endpoint error:

  - FlyingChairs: x2, clip [-300, 250], resize to 384x512
    (`flyingChairsTrain.py:264-296`);
  - Sintel: x3, clip [-420.621, 426.311], resize to 436x1024, averaged over
    all T-1 flow pairs (`sintelTrain.py:264-328`);
  - UCF-101: action accuracy over per-class batches (`ucf101train.py:210-287`).

Visual artifacts (flow color images, warped frames) mirror the reference's
cv2.imwrite dumps (`flyingChairsTrain.py:272-291`).
"""

from __future__ import annotations

import os

import numpy as np

try:
    import cv2
except Exception:  # noqa: BLE001
    cv2 = None

from ..core.config import ExperimentConfig
from ..utils.flowviz import flow_to_color
from ..utils.metrics import flow_aae, flow_epe


def postprocess_flow(flow: np.ndarray, cfg: ExperimentConfig,
                     gt_hw: tuple[int, int]) -> np.ndarray:
    """(B, h, w, 2k) net output -> amplified/clipped/native-res flow."""
    lo, hi = cfg.train.eval_clip
    flow = np.clip(flow * cfg.train.eval_amplifier, lo, hi)
    b, h, w, c = flow.shape
    gh, gw = gt_hw
    if (h, w) == (gh, gw):
        return flow
    out = np.empty((b, gh, gw, c), np.float32)
    for i in range(b):
        for p in range(0, c, 2):  # cv2.resize handles <=4 channels; per pair
            out[i, :, :, p : p + 2] = cv2.resize(
                flow[i, :, :, p : p + 2], (gw, gh), interpolation=cv2.INTER_LINEAR)
    return out


def dump_visuals(out_dir: str, tag: str, flow: np.ndarray,
                 recon: np.ndarray | None = None,
                 gt: np.ndarray | None = None,
                 max_samples: int = 8) -> None:
    """Write flow-color / reconstruction / GT images per sample (the
    reference dumps one set per val clip, `sintelTrain.py:283-307`)."""
    os.makedirs(out_dir, exist_ok=True)
    for i in range(min(flow.shape[0], max_samples)):
        cv2.imwrite(os.path.join(out_dir, f"{tag}_s{i}_flow.png"),
                    flow_to_color(flow[i, :, :, :2]))
        if gt is not None:
            cv2.imwrite(os.path.join(out_dir, f"{tag}_s{i}_gt.png"),
                        flow_to_color(gt[i, :, :, :2]))
        if recon is not None:
            img = np.clip(recon[i, :, :, :3] * 255.0, 0, 255).astype(np.uint8)
            cv2.imwrite(os.path.join(out_dir, f"{tag}_s{i}_recon.png"), img)


def _wmean(pairs: list[tuple[float, int]]) -> float:
    """Row-weighted mean of per-batch (value, valid_rows) pairs — the
    full-split eval convention shared by evaluate_aee/evaluate_ucf101."""
    vals, ws = zip(*pairs)
    return float(np.average(vals, weights=ws))


def evaluate_aee(eval_fn, params, dataset, cfg: ExperimentConfig,
                 dump_dir: str | None = None) -> dict[str, float]:
    """Run the AEE protocol over the full validation split.

    Every val sample is counted exactly once for any eval_batch_size
    (matching the reference's full-split iteration,
    `flyingChairsTrain.py:227-236`): batches are ceil-divided and the
    final, short one is evaluated by tiling its `v` unseen rows
    cyclically across L = v/gcd(v, bs) full-shape calls — every row
    appears exactly bs/gcd times, so the mean of the L jitted
    batch-mean totals IS the uniform mean over the v rows, making
    `val_loss` exact for any eval_batch_size (VERDICT r04 item 7)
    whenever the loss is row-separable (all variants except
    `loss.occlusion`, whose visibility normalizer couples rows — there
    a split-wide val_loss from batch means is composition-dependent by
    definition). The eval_fn only ever sees the full batch shape: no
    extra jit compile, and the sharded path never receives a batch dim
    the mesh can't divide."""
    import math as _math

    bs = cfg.train.eval_batch_size
    n_val = max(dataset.num_val, 1)
    n_batches = -(-n_val // bs)  # ceil: cover the remainder batch too
    epes, aaes, totals = [], [], []
    # running aggregates (O(1) memory — the val split at native res is GBs)
    p_sum = g_sum = 0.0
    p_n = g_n = 0
    p_max = g_max = 0.0
    for bid in range(n_batches):
        batch = dataset.sample_val(bs, bid)
        valid = min(bs, n_val - bid * bs)
        if valid < bs:
            # remainder: replace sample_val's wrap-to-head padding (rows
            # from OTHER batches, which polluted val_loss) with the
            # cyclic self-tiling described in the docstring
            vrows = {k: np.asarray(v)[:valid] for k, v in batch.items()}
            n_tiles = valid // _math.gcd(valid, bs)
            tile_totals = []
            out = None
            for j in range(n_tiles):
                idx = np.arange(j * bs, (j + 1) * bs) % valid
                o = eval_fn(params, {k: v[idx] for k, v in vrows.items()})
                o = {k: np.asarray(x) for k, x in o.items()}
                if j == 0:
                    out = o  # rows 0..valid-1 are the unseen rows in order
                tile_totals.append(float(o["total"]))
            batch_total = float(np.mean(tile_totals))
        else:
            out = {k: np.asarray(v) for k, v in eval_fn(params, batch).items()}
            batch_total = float(out["total"])
        gt = batch["flow"][:valid]
        pred = postprocess_flow(out["flow"][:valid], cfg, gt.shape[1:3])
        # AEE per flow pair, averaged (multi-frame: all T-1 pairs, like
        # `sintelTrain.py:309-328`), row-weighted so a short final batch
        # contributes per-sample, not per-batch
        for p in range(0, gt.shape[-1], 2):
            epes.append((float(flow_epe(pred[..., p : p + 2], gt[..., p : p + 2])), valid))
            aaes.append((float(flow_aae(pred[..., p : p + 2], gt[..., p : p + 2])), valid))
        totals.append((batch_total, valid))
        pa, ga = np.abs(pred), np.abs(gt)
        p_sum += float(pa.sum()); p_n += pa.size; p_max = max(p_max, float(pa.max()))
        g_sum += float(ga.sum()); g_n += ga.size; g_max = max(g_max, float(ga.max()))
        if dump_dir and bid == 0:
            dump_visuals(dump_dir, f"val{bid}", pred,
                         out.get("recon"), gt)

    # flow-statistics report (reference `flyingChairsTrain.py:298-312`)
    return {
        "aee": _wmean(epes),
        "aae": _wmean(aaes),
        "val_loss": _wmean(totals),
        "pred_abs_mean": p_sum / max(p_n, 1),
        "pred_abs_max": p_max,
        "gt_abs_mean": g_sum / max(g_n, 1),
        "gt_abs_max": g_max,
    }


def evaluate_ucf101(eval_fn, params, dataset, cfg: ExperimentConfig,
                    n_classes: int = 101) -> dict[str, float]:
    """Action accuracy over one batch per class (`ucf101train.py:210-223`)."""
    bs = cfg.train.eval_batch_size
    correct, seen, totals = 0, 0, []
    per_class = hasattr(dataset, "val_clips")
    if per_class:
        n = min(n_classes, max(len(dataset.val_clips), 1))
    else:  # non-class datasets (synthetic): cover the val split exactly once
        n = -(-max(dataset.num_val, 1) // bs)
    for bid in range(n):
        batch = dataset.sample_val(bs, bid)
        valid = bs if per_class else min(bs, dataset.num_val - bid * bs)
        out = eval_fn(params, batch)
        logits = np.asarray(out["logits"])[:valid]
        correct += int(np.sum(np.argmax(logits, -1) == batch["label"][:valid]))
        seen += logits.shape[0]
        # weight the (jitted, whole-batch-mean) total by unseen rows so a
        # padded remainder batch doesn't over-weight its wrapped head
        # duplicates (same convention as evaluate_aee's wmean)
        totals.append((float(out["total"]), valid))
    return {
        "accuracy": correct / max(seen, 1),
        "val_loss": _wmean(totals),
    }
