"""Training drivers (L4): pjit step functions, optimization, checkpointing,
evaluation, and the epoch loop — replacing the reference's per-dataset
`*Train.py` session loops (SURVEY.md §2.2) with one dataset-agnostic engine.
"""

from .checkpoint import CheckpointManager
from .evaluate import evaluate_aee, evaluate_ucf101
from .loop import Trainer
from .metrics_log import MetricsLogger, StepTimer
from .schedule import step_decay_schedule
from .state import TrainState, create_train_state
from .step import make_eval_fn, make_train_step

__all__ = [
    "CheckpointManager",
    "MetricsLogger",
    "StepTimer",
    "TrainState",
    "Trainer",
    "create_train_state",
    "evaluate_aee",
    "evaluate_ucf101",
    "make_eval_fn",
    "make_train_step",
    "step_decay_schedule",
]
