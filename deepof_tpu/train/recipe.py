"""Staged training-recipe engine (DESIGN.md "Recipe engine").

The reference ships three disjoint trainers — FlyingChairs pairs,
Sintel 10-frame volumes, UCF-101 two-stream (`flyingChairsTrain.py`,
`sintelTrain.py`, `ucf101train.py`) — and its published results come
from running them in sequence by hand. `run_recipe` replaces that with
one declarative `RecipeConfig`: an ordered list of stages, each naming
a weighted dataset mixture (data/mixture.py), per-stage overrides of
the base config (image size, time_step, model, loss weights, lr), and
an advance condition — a fixed step count or the `eval_trend`
sustained-AEE-plateau signal (analyze.py).

Mechanics, in terms of existing planes rather than new ones:

- Each stage runs a fresh `Trainer` against a stage-resolved config and
  an injected mixture dataset. The mixed stream inherits the
  `derive_batch_rng` determinism contract wholesale — bit-identical for
  any `data.num_workers` and across elastic generation bumps — because
  the member CHOICE is folded from the same per-batch rng.
- Each stage owns its checkpoint lineage (`<log_dir>/ckpt-stage<i>`),
  and every manifest the stage writes carries
  ``extra = {recipe_stage, recipe_stage_name, stage_start_step}`` —
  resume (plain or post-reform) scans the stage directories newest
  first and lands in exactly the stage the newest valid manifest names.
- Stage i+1 starts from stage i's params via `transfer_params` (the
  same shape-matched graft the Chairs->Sintel fine-tune path uses), and
  the global step carries across stages so LR schedules and records
  stay monotonic.
- `precompile_stages` AOT-compiles every stage's (train, eval)
  executable pair through `ExecutableLedger.record_aot` before step 1,
  and injects the Compiled objects into each stage's Trainer — a stage
  switch mid-run executes, it never compiles, and the ledger proves it
  (tools/ledger_diff.py: zero non-warmup compile rows at the boundary).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import numpy as np

from ..analyze import eval_trend
from ..core.config import ExperimentConfig, StageConfig
from ..data.mixture import MixtureDataset, build_mixture
from ..resilience import verify as ckpt_verify


def stage_ckpt_dir(cfg: ExperimentConfig, index: int) -> str:
    """Per-stage checkpoint lineage: stages may disagree on pytree
    structure (model / time_step overrides), so sharing one directory
    would make every cross-stage candidate fail structure verification
    noise-first; one directory per stage keeps each lineage clean."""
    return f"{cfg.train.log_dir}/ckpt-stage{index}"


def stage_config(cfg: ExperimentConfig, stage: StageConfig) -> ExperimentConfig:
    """The base config with this stage's non-sentinel overrides applied
    (None / 0 / "" / () inherit — a stage names only what it changes)."""
    data = cfg.data
    if stage.image_size is not None:
        data = dataclasses.replace(data, image_size=tuple(stage.image_size))
    if stage.gt_size is not None:
        data = dataclasses.replace(data, gt_size=tuple(stage.gt_size))
    if stage.crop_size is not None:
        data = dataclasses.replace(data, crop_size=tuple(stage.crop_size))
    if stage.time_step:
        data = dataclasses.replace(data, time_step=stage.time_step)
    if stage.batch_size:
        data = dataclasses.replace(data, batch_size=stage.batch_size)
    if stage.mixture:
        # the first member is the stage's face for anything that reads
        # cfg.data.dataset (telemetry, eval protocol selection)
        data = dataclasses.replace(data, dataset=stage.mixture[0].dataset)
    out = cfg.replace(data=data)
    if stage.model:
        out = out.replace(model=stage.model)
    if stage.loss_weights:
        out = out.replace(loss=dataclasses.replace(
            out.loss, weights=tuple(float(w) for w in stage.loss_weights)))
    if stage.learning_rate:
        out = out.replace(optim=dataclasses.replace(
            out.optim, learning_rate=stage.learning_rate))
    return out


def stage_dataset(scfg: ExperimentConfig, stage: StageConfig):
    """The stage's dataset: its weighted mixture, or the stage-resolved
    base dataset when the stage declares no mixture."""
    if stage.mixture:
        return build_mixture(scfg.data, stage)
    from ..data import build_dataset

    return build_dataset(scfg.data)


def plateau_reached(stage: StageConfig, evals: list[dict]) -> bool:
    """The EPE-plateau advance condition, pure in its inputs: True when
    `eval_trend` over this stage's eval records reports an AEE slope
    that has flattened to >= -plateau_slope AEE per 1000 steps (i.e. no
    longer improving faster than the declared threshold), with at least
    max(min_evals, 3) finite stage evals seen."""
    if len(evals) < max(stage.min_evals, 3):
        return False
    trend = eval_trend(evals, window=max(stage.plateau_window, 3))
    if trend is None or not math.isfinite(trend["slope_aee_per_kstep"]):
        return False
    return trend["slope_aee_per_kstep"] >= -abs(stage.plateau_slope)


def find_resume_stage(cfg: ExperimentConfig) -> tuple[int, dict]:
    """(stage index, newest manifest extra) a resume lands in: the
    HIGHEST stage whose checkpoint directory holds a committed step —
    the manifest's ``extra.recipe_stage`` is authoritative when present
    (it survives directory renames), the directory index otherwise.
    (0, {}) for a fresh run. jax-free: callable from tools/tests."""
    for i in reversed(range(len(cfg.recipe.stages))):
        steps = ckpt_verify._step_dirs(stage_ckpt_dir(cfg, i))
        if not steps:
            continue
        manifest = ckpt_verify.load_manifest(
            ckpt_verify.manifest_path(steps[-1][1]))
        extra = (manifest or {}).get("extra")
        extra = dict(extra) if isinstance(extra, dict) else {}
        return int(extra.get("recipe_stage", i)), extra
    return 0, {}


def precompile_stages(cfg: ExperimentConfig, mesh=None,
                      stages: "list[int] | None" = None
                      ) -> tuple[dict, dict]:
    """AOT-compile every stage's (train, eval) executable pair before
    the recipe's first step (`warmup_compile`'s lower-then-compile
    pattern, once per stage), recording each through
    `ExecutableLedger.record_aot` — the rows that later prove a stage
    switch compiled nothing.

    Returns (built, report): ``built[i]`` holds the stage's dataset,
    mesh, and Compiled ``train_step``/``eval_fn`` for injection into
    that stage's Trainer — the SAME dataset object must feed both the
    lowering (its mean is baked into the step) and the Trainer, or the
    executables would not match. ``report`` is the jsonable warmup
    summary (per-stage compile seconds, fingerprints, cache verdict).
    """
    import jax
    import jax.numpy as jnp

    from ..models.registry import build_model
    from ..obs.ledger import ExecutableLedger
    from ..parallel.mesh import build_mesh
    from .schedule import step_decay_schedule
    from .state import create_train_state, make_optimizer
    from .step import make_eval_fn, make_train_step
    from .warmup import _sds, cache_delta, example_train_batch

    mesh = mesh if mesh is not None else build_mesh(cfg.mesh)
    ledger = ExecutableLedger(cfg.train.log_dir, enabled=cfg.obs.ledger,
                              backend=jax.default_backend())
    built: dict[int, dict] = {}
    report: dict[str, Any] = {"backend": jax.default_backend(),
                              "stages": []}
    with cache_delta() as delta:
        for i, stage in enumerate(cfg.recipe.stages):
            if stages is not None and i not in stages:
                continue
            scfg = stage_config(cfg, stage)
            dataset = stage_dataset(scfg, stage)
            t = scfg.data.time_step
            dtype = (jnp.bfloat16
                     if scfg.train.compute_dtype == "bfloat16"
                     else jnp.float32)
            model = build_model(scfg.model, flow_channels=2 * (t - 1),
                                dtype=dtype, width_mult=scfg.width_mult,
                                corr_max_disp=scfg.corr_max_disp,
                                corr_stride=scfg.corr_stride)
            steps_per_epoch = max(
                dataset.num_train // scfg.data.batch_size, 1)
            tx = make_optimizer(scfg.optim,
                                step_decay_schedule(scfg.optim,
                                                    steps_per_epoch))
            h, w = scfg.data.crop_size or scfg.data.image_size
            channels = 3 if scfg.model == "ucf101_spatial" else 3 * t
            example = jax.ShapeDtypeStruct(
                (scfg.data.batch_size, h, w, channels), jnp.float32)
            state_sds = jax.eval_shape(
                lambda x, m=model, o=tx, s=scfg: create_train_state(
                    m, x, o, seed=s.train.seed),
                example)
            smooth_border = scfg.model in ("st_single", "st_baseline")
            step = make_train_step(model, scfg, dataset.mean, mesh,
                                   smooth_border)
            batch_sds = _sds(example_train_batch(scfg, dataset))
            train_compiled, row = ledger.record_aot(
                f"train_step_stage{i}",
                lambda s=step, a=state_sds, b=batch_sds: s.lower(a, b))
            shards = mesh.shape["data"]
            eval_bs = max(scfg.train.eval_batch_size // shards, 1) * shards
            eval_fn = make_eval_fn(model, scfg, dataset.mean, mesh=mesh,
                                   smooth_border_mask=smooth_border)
            eval_sds = _sds({k: np.asarray(v) for k, v in
                             dataset.sample_val(eval_bs, 0).items()})
            eval_compiled, erow = ledger.record_aot(
                f"eval_step_stage{i}",
                lambda f=eval_fn, p=state_sds.params, b=eval_sds:
                f.lower(p, b))
            # tx rides along: the Compiled train step's input pytree
            # pins the TrainState's static optimizer metadata by object
            # identity — the stage Trainer must build its state around
            # THIS tx, not a freshly made twin
            built[i] = {"dataset": dataset, "mesh": mesh, "tx": tx,
                        "train_step": train_compiled,
                        "eval_fn": eval_compiled}
            report["stages"].append(
                {"stage": i, "name": stage.name, "model": scfg.model,
                 "time_step": t,
                 "train_compile_s": row["compile_s"],
                 "eval_compile_s": erow["compile_s"],
                 "train_fingerprint": row["fingerprint"],
                 "eval_fingerprint": erow["fingerprint"]})
    report["cache"] = delta.stats()
    return built, report


def run_recipe(cfg: ExperimentConfig, max_steps: int | None = None,
               num_epochs: int | None = None) -> dict:
    """Drive the staged recipe end to end (``train --recipe``).

    Resumes stage-correct from the newest stage checkpoint (manifest
    ``extra``), pre-compiles every remaining stage's executables when
    ``recipe.warmup`` (zero-recompile stage switches), runs each stage's
    Trainer to its advance condition, and grafts params forward across
    stage boundaries. ``max_steps`` bounds TOTAL optimizer steps across
    all stages this call (the CLI's --max-steps contract). Returns a
    jsonable summary: final stage/step, per-stage advance causes, the
    last stage's fit summary scalars."""
    import jax.numpy as jnp

    from .checkpoint import transfer_params
    from .loop import Trainer

    stages = cfg.recipe.stages
    if not stages:
        raise ValueError("recipe.enabled with no recipe.stages declared")
    start_stage, resume_extra = find_resume_stage(cfg)
    built, warm_report = ({}, None)
    if cfg.recipe.warmup:
        built, warm_report = precompile_stages(
            cfg, stages=list(range(start_stage, len(stages))))

    per_stage: list[dict] = []
    advances = 0
    last_trigger = ""
    gstep = 0
    budget_left = max_steps  # total across every stage's fit
    prev_params = None
    summary: dict[str, float] = {}
    for i in range(start_stage, len(stages)):
        stage = stages[i]
        scfg = stage_config(cfg, stage)
        entry = built.get(i, {})
        dataset = entry.get("dataset")
        if dataset is None:
            dataset = stage_dataset(scfg, stage)
        # stage_start_step: where this stage's step budget counts from —
        # for a resumed stage the value its manifests recorded, else the
        # global step the previous stage handed over
        if i == start_stage and resume_extra.get("recipe_stage") == i:
            stage_start = int(resume_extra.get("stage_start_step", gstep))
        else:
            stage_start = gstep

        evals: list[dict] = []
        trigger = {"cause": ""}

        def on_eval(step, metrics, _stage=stage, _evals=evals,
                    _trigger=trigger):
            if _stage.advance != "plateau":
                return False
            aee = metrics.get("aee")
            if aee is None or not math.isfinite(float(aee)):
                return False
            _evals.append({"step": int(step), "aee": float(aee)})
            del _evals[:-max(cfg.recipe.max_trigger_evals, 8)]
            if plateau_reached(_stage, _evals):
                _trigger["cause"] = "plateau"
                return True
            return False

        def recipe_stats(_i=i, _dataset=dataset):
            out = {"recipe_stage": _i, "recipe_stages": len(stages),
                   "recipe_advances": advances,
                   "recipe_last_trigger": last_trigger or None}
            if isinstance(_dataset, MixtureDataset):
                out.update(_dataset.mixture_stats())
            return out

        trainer = Trainer(
            scfg, dataset=dataset, mesh=entry.get("mesh"),
            ckpt_dir=stage_ckpt_dir(cfg, i),
            train_step=entry.get("train_step"),
            eval_fn=entry.get("eval_fn"), tx=entry.get("tx"),
            manifest_extra={"recipe_stage": i,
                            "recipe_stage_name": stage.name,
                            "stage_start_step": stage_start},
            extra_stats=recipe_stats, on_eval=on_eval)
        if int(trainer.state.step) == 0 and prev_params is not None:
            # fresh stage: graft the previous stage's params (trunk
            # transfers, shape-mismatched heads re-init — the
            # Chairs->Sintel handoff) and carry the global step so the
            # LR schedule and every record stay monotonic across stages
            params, n_copied, n_skipped = transfer_params(
                trainer.state.params, prev_params)
            trainer.state = trainer.state.replace(
                params=params,
                step=jnp.asarray(
                    gstep, jnp.asarray(trainer.state.step).dtype))
            trainer.logger.log(
                "info", gstep,
                message=f"recipe stage {i} ({stage.name}): started at "
                        f"step {gstep}; {n_copied} tensors grafted from "
                        f"stage {i - 1}, {n_skipped} re-initialized")
        gstep = int(trainer.state.step)

        # step budget of this fit: the stage's own target (absolute:
        # stage_start + steps) intersected with the recipe-wide cap
        remaining = None
        if stage.steps > 0:
            remaining = stage_start + stage.steps - gstep
        if budget_left is not None:
            remaining = (budget_left if remaining is None
                         else min(remaining, budget_left))
        stage_out: dict[str, float] = {}
        if remaining is None or remaining > 0:
            # epochs sized so the epoch budget never truncates a
            # steps/plateau-bounded stage
            if remaining is not None:
                epochs = max(
                    -(-(gstep + remaining) // trainer.steps_per_epoch) + 1,
                    1)
            else:
                epochs = num_epochs or scfg.train.num_epochs
            stage_out = trainer.fit(num_epochs=epochs, max_steps=remaining)
        new_gstep = int(trainer.state.step)
        if budget_left is not None:
            budget_left -= max(new_gstep - gstep, 0)
        gstep = new_gstep
        prev_params = trainer.state.params
        summary = stage_out

        cause = trigger["cause"]
        if not cause and stage.steps > 0 and \
                gstep >= stage_start + stage.steps:
            cause = "steps"
        elif not cause:
            cause = "budget"  # epoch/--max-steps budget ended the fit
        per_stage.append({"stage": i, "name": stage.name,
                          "start_step": stage_start, "end_step": gstep,
                          "advance": cause})
        out_of_budget = budget_left is not None and budget_left <= 0
        if i + 1 < len(stages) and not out_of_budget \
                and cause in ("steps", "plateau"):
            advances += 1
            last_trigger = cause
            trainer.logger.log(
                "info", gstep,
                message=f"recipe advance: stage {i} ({stage.name}) -> "
                        f"stage {i + 1} ({stages[i + 1].name}) on "
                        f"'{cause}' at step {gstep}")
            continue
        break  # terminal stage, exhausted budget, or untriggered fit

    result = {"final_stage": per_stage[-1]["stage"] if per_stage else
              start_stage,
              "global_step": gstep, "advances": advances,
              "last_trigger": last_trigger or None,
              "per_stage": per_stage,
              **{k: float(v) for k, v in summary.items()
                 if isinstance(v, (int, float))}}
    if warm_report is not None:
        result["warmup_cache"] = warm_report.get("cache")
    return result
