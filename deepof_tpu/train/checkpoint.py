"""Full train-state checkpointing with auto-resume.

Replaces `tf.train.Saver` model-variables-only checkpoints
(`flyingChairsTrain.py:156-161,211-213`) with orbax checkpoints of the whole
TrainState pytree — params + optimizer state + step + PRNG key — so resume
continues the LR schedule and optimizer moments exactly (fixes the
reference deficiency in SURVEY.md §5.4). Restore-if-present at startup
mirrors the reference's `get_checkpoint_state` behavior.
"""

from __future__ import annotations

import os
import re
import shutil

import jax
import orbax.checkpoint as ocp

from .state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        os.makedirs(self.directory, exist_ok=True)
        self._ckpt = ocp.PyTreeCheckpointer()

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    def all_steps(self) -> list[int]:
        steps = []
        for name in os.listdir(self.directory):
            m = re.match(r"step_(\d+)$", name)
            # only completed orbax dirs (atomic rename drops the tmp suffix)
            if m and os.path.isdir(os.path.join(self.directory, name)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, state: TrainState) -> str:
        step = int(jax.device_get(state.step))
        path = self._path(step)
        # Multi-host: orbax coordinates the distributed write itself, but
        # directory surgery (clobber + prune) must be single-writer or one
        # host can rmtree a directory another host's writer is mid-write to.
        primary = jax.process_index() == 0
        if primary and os.path.exists(path):
            shutil.rmtree(path)
        self._ckpt.save(path, state)
        if primary:
            for old in self.all_steps()[: -self.keep]:
                shutil.rmtree(self._path(old), ignore_errors=True)
        return path

    def restore(self, template: TrainState, step: int | None = None) -> TrainState | None:
        """Restore into the structure of `template` (shapes/dtypes/shardings
        come from the abstract template, the non-pytree `tx` is carried
        over). Returns None if no checkpoint exists."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        restored = self._ckpt.restore(self._path(step), item=template)
        return restored.replace(tx=template.tx)
