"""Full train-state checkpointing with auto-resume and verification.

Replaces `tf.train.Saver` model-variables-only checkpoints
(`flyingChairsTrain.py:156-161,211-213`) with orbax checkpoints of the whole
TrainState pytree — params + optimizer state + step + PRNG key — so resume
continues the LR schedule and optimizer moments exactly (fixes the
reference deficiency in SURVEY.md §5.4). Restore-if-present at startup
mirrors the reference's `get_checkpoint_state` behavior.

Resilience layer (DESIGN.md "Resilience"): every committed checkpoint
gets a sibling manifest (pytree-structure digest + per-file size/crc32
inventory + config digest, resilience/verify.py); `restore` verifies the
manifest and falls back to the newest checkpoint that validates instead
of restoring garbage, and save failures (disk full, injected) degrade to
a logged warning with the previous checkpoint retained — a torn or
bit-flipped rollback target is a counted event, not a crash.
"""

from __future__ import annotations

import os
import re
import shutil
import warnings

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from ..resilience import verify as ckpt_verify
from .state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3, create: bool = True,
                 async_save: bool = True, verify: bool = True,
                 log=None, injector=None, config_digest: str | None = None,
                 writer: bool = True, info_log=None,
                 manifest_extra: dict | None = None):
        """create=False opens read-only (no mkdir side effect — e.g. the
        transfer-init source, where a typo'd path must not leave a phantom
        empty run directory behind).

        async_save: serialize to disk on a background thread — save()
        returns after the device->host snapshot, so step-cadence
        checkpointing (`ckpt_every_steps`) doesn't stall training on IO.
        Every read path (and the next save) waits for the in-flight write,
        so observable behavior is unchanged; call finalize() before
        process exit.

        verify: validate each candidate's manifest on restore and fall
        back to the newest checkpoint that verifies (missing manifests —
        legacy checkpoints — restore unverified).
        log: optional (step, message) sink for recovery events
        (MetricsLogger-shaped); warnings.warn when absent — a degraded
        save or a skipped corrupt checkpoint must never be silent.
        injector: optional resilience.faults.FaultInjector — consulted at
        the ckpt_save / ckpt_restore sites and for post-commit tampering
        (the chaos-test substrate).
        config_digest: recorded in each manifest; restore warns (but
        proceeds) on mismatch — fine-tune handoffs legitimately cross
        configs.
        info_log: optional (step, message) sink for INFORMATIONAL
        records (restore provenance) — wired to kind="info" by the
        Trainer so a healthy restore never lands on the operator's
        warnings surface; falls back to `log` when absent, so
        single-sink users still get the provenance audit trail.
        writer: False opens the directory restore-only — save() is a
        silent no-op returning None. Elastic non-primary trainer hosts
        share the primary's checkpoint directory (train/elastic.py):
        they must resume from it on every re-form, but concurrent
        writers at different steps would race the prune/clobber
        directory surgery, so exactly one host (the generation's
        primary) writes.
        manifest_extra: optional jsonable block ridden verbatim on every
        manifest this manager writes (e.g. the recipe engine's active
        stage index, train/recipe.py) — readable back via
        `read_manifest_extra()` without touching the orbax payload."""
        self.directory = os.path.abspath(directory)
        self.keep = keep
        self._verify = verify
        self._log = log
        self._inj = injector
        self._config_digest = config_digest
        self._writer = writer
        self._info_log = info_log
        self._manifest_extra = manifest_extra
        self._pending_manifest: tuple[int, dict] | None = None
        # recovery-event counters (GIL-atomic int bumps; heartbeat reads)
        self._saves = 0
        self._save_failures = 0
        self._restore_failures = 0
        self._restore_fallbacks = 0
        self._verify_failures = 0
        if create:
            os.makedirs(self.directory, exist_ok=True)
        if async_save:
            self._ckpt = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        else:
            self._ckpt = ocp.PyTreeCheckpointer()

    # ------------------------------------------------------------- events
    def _warn(self, step: int, message: str) -> None:
        if self._log is not None:
            self._log(step, message)
        else:
            warnings.warn(message, RuntimeWarning, stacklevel=3)

    def stats(self) -> dict[str, int]:
        """Recovery-event counters for train records / heartbeat / the
        fit summary."""
        return {"saves": self._saves,
                "save_failures": self._save_failures,
                "restore_failures": self._restore_failures,
                "restore_fallbacks": self._restore_fallbacks,
                "verify_failures": self._verify_failures}

    # ------------------------------------------------------------ commits
    def _wait(self) -> None:
        wait = getattr(self._ckpt, "wait_until_finished", None)
        if wait is not None:
            try:
                wait()
            except Exception as e:  # noqa: BLE001 - degrade, don't crash
                # the async WRITE failed (disk full, injected, ...): the
                # previous checkpoint is still on disk and still the
                # resume/rollback target — a failed save must not take
                # the run down with it
                self._save_failures += 1
                step = (self._pending_manifest[0]
                        if self._pending_manifest is not None else -1)
                self._pending_manifest = None
                # drop the partial dir (never restorable) — primary only:
                # directory surgery stays single-writer (see save())
                if step >= 0 and jax.process_index() == 0:
                    shutil.rmtree(self._path(step), ignore_errors=True)
                self._warn(max(step, 0),
                           f"checkpoint write failed at step {step}: "
                           f"{type(e).__name__}: {e}; previous checkpoint "
                           "retained")
        self._flush_manifest()

    def _flush_manifest(self) -> None:
        """Write the manifest for the newest COMMITTED save (deferred
        for async saves: the file inventory is only meaningful once the
        write has fully committed), then let the injector tamper — after
        the manifest, so damage is detectable, like real corruption."""
        if self._pending_manifest is None:
            return
        step, structure = self._pending_manifest
        self._pending_manifest = None
        path = self._path(step)
        if not os.path.isdir(path):
            return  # write never committed (failure handled in _wait)
        # count COMMITTED checkpoints only: an async write that fails at
        # _wait never reaches here, so saves/save_failures stay disjoint
        self._saves += 1
        if jax.process_index() != 0:
            return
        try:
            manifest = ckpt_verify.build_manifest(
                path, step, structure=structure,
                cfg_digest=self._config_digest,
                extra=self._manifest_extra)
            ckpt_verify.write_manifest(path, manifest)
        except OSError as e:
            self._warn(step, f"checkpoint manifest write failed at step "
                             f"{step}: {e}; checkpoint restores unverified")
        if self._inj is not None:
            for act in self._inj.tamper_checkpoint(step, path):
                self._warn(step, f"fault injection: {act}")

    def finalize(self) -> None:
        """Block until any in-flight async save has fully committed (and
        its manifest is flushed)."""
        self._wait()

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    def all_steps(self) -> list[int]:
        self._wait()  # an in-flight async save must be visible (or absent)
        if not os.path.isdir(self.directory):
            return []
        steps = []
        for name in os.listdir(self.directory):
            m = re.match(r"step_(\d+)$", name)
            # only completed orbax dirs (atomic rename drops the tmp suffix)
            if m and os.path.isdir(os.path.join(self.directory, name)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    @staticmethod
    def _structure_digest(state) -> dict:
        """Pytree-structure digest: leaf paths + shapes + dtypes (no
        value reads — the content checksum is the manifest's per-file
        crc inventory over the committed bytes)."""
        import zlib

        leaves = jax.tree_util.tree_flatten_with_path(state)[0]
        crc = 0
        for keypath, leaf in leaves:
            spec = (f"{jax.tree_util.keystr(keypath)}:"
                    f"{getattr(leaf, 'shape', ())}:"
                    f"{getattr(leaf, 'dtype', type(leaf).__name__)};")
            crc = zlib.crc32(spec.encode(), crc)
        return {"num_leaves": len(leaves), "crc32": crc}

    def save(self, state: TrainState) -> str | None:
        """Write a checkpoint; on failure (disk full, injected fault),
        degrade to a logged warning and return None — the previous
        checkpoint stays the resume/rollback target."""
        if not self._writer:
            return None  # restore-only handle (elastic non-primary host)
        step = int(jax.device_get(state.step))
        self._wait()  # serialize with any still-writing previous save
        path = self._path(step)
        # Multi-host: orbax coordinates the distributed write itself, but
        # directory surgery (clobber + prune) must be single-writer or one
        # host can rmtree a directory another host's writer is mid-write to.
        primary = jax.process_index() == 0
        started = False  # the write itself began (vs a pre-write failure)
        try:
            if self._inj is not None:
                self._inj.check("ckpt_save", step)
            if primary:
                if os.path.exists(path):
                    shutil.rmtree(path)
                    self._rm_manifest(step)
                # Prune BEFORE the (possibly async) write, but always retain
                # the newest completed checkpoint: if the in-flight write never
                # commits (crash, disk full), a restorable state must survive.
                # keep=1 therefore transiently holds 2 checkpoints on disk.
                done = self.all_steps()  # _wait() already ran above
                for old in done[: -max(self.keep - 1, 1)]:
                    if old != step:
                        shutil.rmtree(self._path(old), ignore_errors=True)
                        self._rm_manifest(old)
            started = True
            self._ckpt.save(path, state)
        except Exception as e:  # noqa: BLE001 - degrade, don't crash
            self._save_failures += 1
            # remove the partial dir ONLY if the write began: a failure
            # before that (e.g. an injected pre-write fault on a re-save)
            # must not delete a previously COMMITTED checkpoint at this
            # step. Single-writer directory surgery (see above).
            if primary and started:
                shutil.rmtree(path, ignore_errors=True)
                self._rm_manifest(step)
            self._warn(step,
                       f"checkpoint save failed at step {step}: "
                       f"{type(e).__name__}: {e}; previous checkpoint "
                       "retained")
            return None
        # manifest deferred until the write has COMMITTED: flushed by the
        # next _wait() (any read path / next save / finalize); sync
        # checkpointers have committed already, flush now
        self._pending_manifest = (step, self._structure_digest(state))
        if not hasattr(self._ckpt, "wait_until_finished"):
            self._flush_manifest()
        return path

    def read_manifest_extra(self, step: int | None = None) -> dict | None:
        """The ``extra`` block of a committed checkpoint's manifest
        (newest step when None) — how the recipe engine learns which
        stage a resume belongs to, jax-free. None when the checkpoint or
        its manifest is absent (legacy / torn manifests report as
        no-extra, not as errors)."""
        self._wait()
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        manifest = ckpt_verify.load_manifest(
            ckpt_verify.manifest_path(self._path(step)))
        if manifest is None:
            return None
        extra = manifest.get("extra")
        return dict(extra) if isinstance(extra, dict) else None

    def _rm_manifest(self, step: int) -> None:
        try:
            os.remove(ckpt_verify.manifest_path(self._path(step)))
        except OSError:
            pass

    def _verify_candidate(self, step: int,
                          expect_structure: dict | None = None) -> list[str]:
        """Problems blocking a restore of `step` ([] = restorable).
        A missing manifest (legacy checkpoint, or a crash between commit
        and manifest flush) restores unverified — absence is not
        corruption. `expect_structure` (the restore template's pytree
        digest) catches the files-intact-but-wrong-tree case before
        orbax does anything with it."""
        if not self._verify:
            return []
        path = self._path(step)
        manifest = ckpt_verify.load_manifest(ckpt_verify.manifest_path(path))
        if manifest is None:
            return []
        problems = ckpt_verify.verify_files(path, manifest)
        saved = manifest.get("structure")
        if not problems and saved and expect_structure is not None:
            if (saved.get("num_leaves") != expect_structure["num_leaves"]
                    or saved.get("crc32") != expect_structure["crc32"]):
                problems = [
                    f"pytree structure mismatch (checkpoint {saved} != "
                    f"restore template {expect_structure})"]
        if not problems:
            digest = manifest.get("config_digest")
            if (digest and self._config_digest
                    and digest != self._config_digest):
                self._warn(step,
                           f"checkpoint step {step} was written by a "
                           f"different config (digest {digest} != "
                           f"{self._config_digest}); restoring anyway")
        return problems

    def restore(self, template: TrainState, step: int | None = None) -> TrainState | None:
        """Restore into the structure of `template` (shapes/dtypes/shardings
        come from the abstract template, the non-pytree `tx` is carried
        over). Returns None if no checkpoint exists.

        With verification on (ResilienceConfig.verify_checkpoints), a
        candidate whose manifest fails — or whose orbax read raises — is
        skipped with a logged warning and the next-newest checkpoint is
        tried: auto-resume and NaN rollback land on the newest VALID
        state instead of crashing into (or silently loading) a torn one.
        An explicit `step` restores only that step (None on failure)."""
        self._wait()
        candidates = ([step] if step is not None
                      else list(reversed(self.all_steps())))
        expect = self._structure_digest(template) if self._verify else None
        for i, s in enumerate(candidates):
            problems = self._verify_candidate(s, expect)
            if problems:
                self._verify_failures += 1
                self._warn(s,
                           f"checkpoint step {s} failed verification "
                           f"({'; '.join(problems[:3])}); "
                           + ("trying an older checkpoint"
                              if i + 1 < len(candidates)
                              else "no older checkpoint to fall back to"))
                continue
            try:
                if self._inj is not None:
                    self._inj.check("ckpt_restore", s)
                restored = self._ckpt.restore(self._path(s), item=template)
            except Exception as e:  # noqa: BLE001 - fall back, don't crash
                self._restore_failures += 1
                self._warn(s,
                           f"checkpoint restore failed at step {s}: "
                           f"{type(e).__name__}: {e}; "
                           + ("trying an older checkpoint"
                              if i + 1 < len(candidates)
                              else "no older checkpoint to fall back to"))
                continue
            if i > 0:
                self._restore_fallbacks += 1
            # restore provenance, auditable from metrics.jsonl alone: a
            # post-reform / post-rollback run states WHICH step it came
            # back from and WHY (requested vs newest vs fallback after
            # corruption), so "where did these params come from" never
            # needs the checkpoint directory's history reconstructed
            why = ("explicitly requested"
                   if step is not None else
                   "newest checkpoint" if i == 0 else
                   f"fallback after corruption: {i} newer candidate(s) "
                   "failed verification/restore")
            msg = f"checkpoint restore: step {s} ({why})"
            if self._info_log is not None:
                self._info_log(s, msg)
            elif self._log is not None:
                self._log(s, msg)
            elif i > 0:  # a healthy sink-less restore stays quiet
                warnings.warn(msg, RuntimeWarning, stacklevel=2)
            return restored.replace(tx=template.tx)
        return None

    def restore_raw(self, step: int | None = None,
                    subtree: str | None = None) -> dict | None:
        """Restore the checkpoint as a raw pytree (no template) — for
        cross-config transfer where structures differ (`transfer_params`).

        subtree: restore only that top-level entry (e.g. "params"),
        skipping the rest — Adam moments are ~2x the param bytes, so a
        params-only transfer read is ~3x cheaper. Falls back to a full
        read if selective restore isn't supported by the orbax version.
        """
        self._wait()
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        path = self._path(step)
        if subtree is not None:
            try:
                meta = self._ckpt.metadata(path)
                skip = jax.tree_util.tree_map(
                    lambda m: ocp.RestoreArgs(restore_type=None), meta)
                if isinstance(skip, dict) and subtree in skip:
                    skip[subtree] = jax.tree_util.tree_map(
                        lambda m: ocp.RestoreArgs(), meta[subtree])
                    return self._ckpt.restore(path, restore_args=skip)[subtree]
            except Exception:  # noqa: BLE001 - orbax API drift: full read
                pass
            return self._ckpt.restore(path)[subtree]
        return self._ckpt.restore(path)


def transfer_params(target: dict, source: dict) -> tuple[dict, int, int]:
    """Graft `source` leaves onto `target` where path AND shape match.

    The cross-config fine-tune path (e.g. FlyingChairs 2-frame pretrain ->
    Sintel T=10 volume model, the reference paper's training recipe): trunk
    weights transfer; the first conv (3T input channels) and the pyramid
    heads / flow upsamplers (2(T-1) channels) re-initialize. Returns
    (new_target, n_copied, n_skipped) where skipped counts target leaves
    with no same-shape source counterpart.
    """
    copied = skipped = 0

    def graft(tgt, src):
        nonlocal copied, skipped
        if isinstance(tgt, dict):
            return {
                k: (graft(v, src[k]) if isinstance(src, dict) and k in src
                    else _skip(v))
                for k, v in tgt.items()
            }
        if (src is not None and hasattr(src, "shape")
                and getattr(tgt, "shape", None) == src.shape):
            copied += 1
            return jnp.asarray(src, dtype=tgt.dtype)
        return _skip(tgt)

    def _skip(sub):
        nonlocal skipped
        skipped += len(jax.tree_util.tree_leaves(sub))
        return sub

    return graft(target, source), copied, skipped
