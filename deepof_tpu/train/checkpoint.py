"""Full train-state checkpointing with auto-resume.

Replaces `tf.train.Saver` model-variables-only checkpoints
(`flyingChairsTrain.py:156-161,211-213`) with orbax checkpoints of the whole
TrainState pytree — params + optimizer state + step + PRNG key — so resume
continues the LR schedule and optimizer moments exactly (fixes the
reference deficiency in SURVEY.md §5.4). Restore-if-present at startup
mirrors the reference's `get_checkpoint_state` behavior.
"""

from __future__ import annotations

import os
import re
import shutil

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from .state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3, create: bool = True,
                 async_save: bool = True):
        """create=False opens read-only (no mkdir side effect — e.g. the
        transfer-init source, where a typo'd path must not leave a phantom
        empty run directory behind).

        async_save: serialize to disk on a background thread — save()
        returns after the device->host snapshot, so step-cadence
        checkpointing (`ckpt_every_steps`) doesn't stall training on IO.
        Every read path (and the next save) waits for the in-flight write,
        so observable behavior is unchanged; call finalize() before
        process exit."""
        self.directory = os.path.abspath(directory)
        self.keep = keep
        if create:
            os.makedirs(self.directory, exist_ok=True)
        if async_save:
            self._ckpt = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        else:
            self._ckpt = ocp.PyTreeCheckpointer()

    def _wait(self) -> None:
        wait = getattr(self._ckpt, "wait_until_finished", None)
        if wait is not None:
            wait()

    def finalize(self) -> None:
        """Block until any in-flight async save has fully committed."""
        self._wait()

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    def all_steps(self) -> list[int]:
        self._wait()  # an in-flight async save must be visible (or absent)
        if not os.path.isdir(self.directory):
            return []
        steps = []
        for name in os.listdir(self.directory):
            m = re.match(r"step_(\d+)$", name)
            # only completed orbax dirs (atomic rename drops the tmp suffix)
            if m and os.path.isdir(os.path.join(self.directory, name)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, state: TrainState) -> str:
        step = int(jax.device_get(state.step))
        self._wait()  # serialize with any still-writing previous save
        path = self._path(step)
        # Multi-host: orbax coordinates the distributed write itself, but
        # directory surgery (clobber + prune) must be single-writer or one
        # host can rmtree a directory another host's writer is mid-write to.
        primary = jax.process_index() == 0
        if primary:
            if os.path.exists(path):
                shutil.rmtree(path)
            # Prune BEFORE the (possibly async) write, but always retain
            # the newest completed checkpoint: if the in-flight write never
            # commits (crash, disk full), a restorable state must survive.
            # keep=1 therefore transiently holds 2 checkpoints on disk.
            done = self.all_steps()  # _wait() already ran above
            for old in done[: -max(self.keep - 1, 1)]:
                if old != step:
                    shutil.rmtree(self._path(old), ignore_errors=True)
        self._ckpt.save(path, state)
        return path

    def restore(self, template: TrainState, step: int | None = None) -> TrainState | None:
        """Restore into the structure of `template` (shapes/dtypes/shardings
        come from the abstract template, the non-pytree `tx` is carried
        over). Returns None if no checkpoint exists."""
        self._wait()
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        restored = self._ckpt.restore(self._path(step), item=template)
        return restored.replace(tx=template.tx)

    def restore_raw(self, step: int | None = None,
                    subtree: str | None = None) -> dict | None:
        """Restore the checkpoint as a raw pytree (no template) — for
        cross-config transfer where structures differ (`transfer_params`).

        subtree: restore only that top-level entry (e.g. "params"),
        skipping the rest — Adam moments are ~2x the param bytes, so a
        params-only transfer read is ~3x cheaper. Falls back to a full
        read if selective restore isn't supported by the orbax version.
        """
        self._wait()
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        path = self._path(step)
        if subtree is not None:
            try:
                meta = self._ckpt.metadata(path)
                skip = jax.tree_util.tree_map(
                    lambda m: ocp.RestoreArgs(restore_type=None), meta)
                if isinstance(skip, dict) and subtree in skip:
                    skip[subtree] = jax.tree_util.tree_map(
                        lambda m: ocp.RestoreArgs(), meta[subtree])
                    return self._ckpt.restore(path, restore_args=skip)[subtree]
            except Exception:  # noqa: BLE001 - orbax API drift: full read
                pass
            return self._ckpt.restore(path)[subtree]
        return self._ckpt.restore(path)


def transfer_params(target: dict, source: dict) -> tuple[dict, int, int]:
    """Graft `source` leaves onto `target` where path AND shape match.

    The cross-config fine-tune path (e.g. FlyingChairs 2-frame pretrain ->
    Sintel T=10 volume model, the reference paper's training recipe): trunk
    weights transfer; the first conv (3T input channels) and the pyramid
    heads / flow upsamplers (2(T-1) channels) re-initialize. Returns
    (new_target, n_copied, n_skipped) where skipped counts target leaves
    with no same-shape source counterpart.
    """
    copied = skipped = 0

    def graft(tgt, src):
        nonlocal copied, skipped
        if isinstance(tgt, dict):
            return {
                k: (graft(v, src[k]) if isinstance(src, dict) and k in src
                    else _skip(v))
                for k, v in tgt.items()
            }
        if (src is not None and hasattr(src, "shape")
                and getattr(tgt, "shape", None) == src.shape):
            copied += 1
            return jnp.asarray(src, dtype=tgt.dtype)
        return _skip(tgt)

    def _skip(sub):
        nonlocal skipped
        skipped += len(jax.tree_util.tree_leaves(sub))
        return sub

    return graft(target, source), copied, skipped
