"""Elastic multi-host training: a coordinator-supervised trainer pool
that survives host loss and preemption without operator action.

The static multi-host story (`--multihost`, parallel/mesh.py) dies with
its weakest host: on a preemptible pod one SIGKILL ends a multi-day run.
Every recovery ingredient already exists in this repo — verified
checkpoints with fallback restore (train/checkpoint.py), per-host
decorrelated data streams (`data_stream_seed`), liveness heartbeats with
a wedge verdict (obs/heartbeat.py), and a proven supervisor state
machine (serve/fleet.py). This module adds the missing detect/re-form/
resume step.

One coordinator (`ElasticCoordinator`, stdlib-only code — it performs no
jax computation; the CLI defuses the axon backend before the
train-package import chain initializes anything) spawns N single-host
trainer subprocesses:

    deepof_tpu train --config-json <log_dir>/host-<i>/config.json \
        --host-index <i>

Each child gets the parent's exact config tree with its elastic identity
filled in (host_index, current world size, generation, the shared
verified-checkpoint directory, and which host is the checkpoint
PRIMARY); with ``elastic.virtual_devices > 0`` the child forces that
many virtual CPU devices (core/hostmesh.py), so a whole pool is testable
on one machine — the same defuse the test suite uses.

Health gating reads each host's ``heartbeat.json`` (rewritten every
``obs.heartbeat_period_s`` by the trainer's own heartbeat thread):
`host_verdict` is the pure decision function — the file must belong to
the CURRENT process (pid gate: a dead incarnation's file can neither
vouch for nor condemn a respawn), ``wedged: true`` is the trainer's own
watchdog verdict, a stale ``time`` means the whole process is frozen,
and a fresh file whose ``last_step_age_s`` keeps growing past
``elastic.wedge_after_s`` with >= 1 completed step is a content stall
(a dispatch hung before the in-process watchdog — which needs 3 beats
and ``obs.watchdog_min_s`` — would say so). Process death is caught by
``poll()`` between heartbeats.

On a lost host the coordinator bumps the **generation**:

  1. **Barrier** — SIGTERM every survivor. The trainer's graceful
     handler (train/loop.py) finishes the current step, saves a verified
     checkpoint (the primary writes the shared directory; non-primaries
     are restore-only handles), flushes metrics/trace, and exits 0.
     Stragglers are SIGKILLed after ``elastic.barrier_timeout_s`` —
     bounded lost work either way (<= steps since the last commit).
  2. **Re-form** — the world is the surviving original host indices
     (a lost host is never respawned: its capacity is gone, exactly like
     a preempted pod host). New world size, new primary (the lowest
     survivor), generation + 1. Each survivor's data stream re-shards
     via `parallel/mesh.py::elastic_stream_seed` — host index, world
     size, generation, and resume step are all folded into the base
     seed, so the post-reform streams are deterministic AND decorrelated
     from every stream any previous generation drew.
  3. **Resume** — survivors respawn and restore the newest VALID
     checkpoint from the shared directory via CheckpointManager's
     verify-and-fallback restore (a checkpoint torn by the dying host
     falls back to the previous valid one, counted and logged).

The chaos sites ``host_loss`` / ``host_wedge`` / ``preempt_notice``
(resilience/faults.py, keyed by host index, armed at
``faults.host_fault_step``) inject exactly these failures
deterministically — `maybe_host_fault` runs inside each trainer's step
loop, so a drill reproduces from config alone.

**Scope, stated plainly:** the trainers do NOT exchange gradients — each
host trains an independent replica on its decorrelated shard, and the
persisted run is the PRIMARY's checkpoint lineage (non-primary hosts are
hot spares of that lineage: they validate the data path at scale, keep
the pool warm, and take over as primary when hosts ahead of them die).
This is what is honestly testable on one machine; wiring true
data-parallel gradient exchange across the pool (jax.distributed
re-initialized per generation over the surviving hosts — the
coordinator's spawn/verdict/barrier/generation machinery is exactly the
harness that needs) is the follow-on step and changes none of the
supervision protocol built here. Likewise the coordinator spawns
children on THIS machine; a real pod runs one coordinator per pool with
a remote process runner in `_spawn`'s place.

`run_elastic` is the ``train --elastic N`` entry: coordinator + a
jax-free heartbeat whose ``elastic_*`` counter block (generation,
reforms, lost_hosts, resumed_step, steps_lost, per-host states) lands in
``heartbeat.json`` and in ``kind="elastic"`` metrics records —
`deepof_tpu tail` surfaces the block and exits 5 (distinct from wedged
rc 3 and fleet rc 4) when a run had to re-form.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time

from ..core import supervise
from ..core.config import ExperimentConfig
from ..obs import incident
from ..resilience import verify as ckpt_verify

#: Trainer-host lifecycle states (ElasticCoordinator._check_host is the
#: transition table). Terminal: "lost" (never respawned), "done"
#: (reached the target step), "stopped" (coordinator shutdown).
HOST_STATES = ("spawning", "starting", "running", "barrier", "lost",
               "done", "stopped")


# --------------------------------------------------------------- verdicts


def _trainer_stepped(hb: dict) -> bool:
    """The coordinator's stall gate for the shared heartbeat verdict
    (core/supervise.py): the stall clock is meaningful only once >= 1
    beat completed — a first-dispatch XLA compile is never judged."""
    return int(hb.get("beats") or 0) >= 1


def host_verdict(hb: dict | None, pid: int | None, now_wall: float,
                 stale_after_s: float, wedge_after_s: float) -> str:
    """Pure health verdict for one trainer from its heartbeat CONTENT —
    the shared supervisor verdict (`supervise.heartbeat_verdict`, the
    same decision function the serving fleet judges replicas with) under
    the coordinator's stall gate.

    Returns one of:
      "no_heartbeat"  — no (readable) file yet: pre-fit grace, judged
                        only by the spawn timeout;
      "foreign_pid"   — the file belongs to another incarnation: same
                        treatment as no_heartbeat (it can neither vouch
                        for nor condemn this process);
      "wedged"        — the trainer's own watchdog declared the wedge;
      "stale"         — the heartbeat thread itself stopped writing
                        (frozen/SIGSTOPped process, dead host);
      "stalled"       — the file is fresh but >= 1 step completed and
                        nothing has progressed for wedge_after_s: the
                        main loop is hung before the in-process watchdog
                        (3 beats + obs.watchdog_min_s) would say so.
                        Gated on beats >= 1 so the first-dispatch XLA
                        compile is never judged;
      "ok"            — healthy.
    """
    return supervise.heartbeat_verdict(hb, pid, now_wall, stale_after_s,
                                       wedge_after_s,
                                       stall_gate=_trainer_stepped)


# ------------------------------------------------------- in-trainer chaos


def maybe_host_fault(inj, host_index: int, gstep: int, arm_step: int,
                     log=None, _kill=os.kill,
                     _block=lambda: threading.Event().wait()) -> None:
    """Host-level chaos hook, called from the trainer's step loop after
    each completed dispatch (train/loop.py). Sites are keyed by the
    host index and arm once the global step reaches ``arm_step``
    (``faults.host_fault_step``); `FaultInjector.hit` is consume-once,
    so each site fires at most once per trainer incarnation.

      preempt_notice — SIGTERM self-delivery: the graceful handler saves
        a verified checkpoint and exits 0 (the cloud's preemption
        warning, end to end).
      host_wedge — the main loop blocks forever: the heartbeat thread
        keeps the file fresh while ``last_step_age_s`` grows — exactly
        the content stall the coordinator's `host_verdict` exists for.
      host_loss — SIGKILL: the host vanishes mid-step (preemption
        without notice, OOM kill), nothing gets to clean up.

    ``_kill`` / ``_block`` are test seams (the real actions end or hang
    the calling process)."""
    if inj is None or host_index < 0 or gstep < max(int(arm_step), 0):
        return
    if inj.hit("preempt_notice", host_index):
        if log is not None:
            log(f"fault injection: preemption notice (SIGTERM) to host "
                f"{host_index} at step {gstep}")
        _kill(os.getpid(), signal.SIGTERM)
        return
    if inj.hit("host_wedge", host_index):
        if log is not None:
            log(f"fault injection: host {host_index} wedging at step "
                f"{gstep} (main loop blocks forever)")
        _block()
    if inj.hit("host_loss", host_index):
        if log is not None:
            log(f"fault injection: host loss (SIGKILL) of host "
                f"{host_index} at step {gstep}")
        _kill(os.getpid(), signal.SIGKILL)


def pace_to_world(world_file: str, generation: int, gstep: int,
                  sync_ahead: int, should_stop, touch=None,
                  poll_s: float = 0.05, stale_s: float = 30.0,
                  _sleep=time.sleep, _now=time.time) -> int | None:
    """Step-skew limiter, called from the trainer's step loop
    (train/loop.py) before each dispatch: block while this host is more
    than ``sync_ahead`` steps ahead of the slowest live host (the world
    FLOOR the coordinator publishes to ``world_file`` every poll).

    Real synchronous data-parallel training is lockstepped by its
    collectives; virtual elastic hosts are independent processes, and on
    a contended machine their step counts diverge by whole compile
    times — which would void the guarantee that a re-form discards at
    most checkpoint-cadence + sync_ahead steps (the furthest host's
    uncommitted tail IS the lost work). While paced, the wait
    ``touch``es the heartbeat so a deliberately-waiting leader never
    reads as a stall. The gate yields immediately when the file is
    missing/unreadable (pacing is an optimization, never a hard
    dependency), names a different generation (stale across a re-form —
    the SIGTERM barrier is what actually stops this host), or the stop
    flag is raised.

    Returns the last floor observed (None when pacing is inapplicable:
    missing/unreadable file, a stale generation, or a floor older than
    ``stale_s`` — a coordinator killed uncleanly leaves the file frozen
    forever, and a paced ORPHAN must finish training to target, not
    block on a dead supervisor; pacing is an optimization, never a hard
    dependency). The floor only ever advances within one generation, so
    callers may cache it and skip the file read entirely while ``gstep -
    cached_floor <= sync_ahead`` — the hot loop then touches the
    filesystem only when it could actually need to block."""
    floor_seen: int | None = None
    while not should_stop():
        try:
            with open(world_file) as f:
                w = json.load(f)
        except (OSError, ValueError):
            return floor_seen
        if w.get("generation") != generation:
            return floor_seen
        t = w.get("time")
        if (isinstance(t, (int, float))
                and _now() - t > max(float(stale_s), 0.1)):
            return floor_seen  # frozen file: the coordinator is gone
        floor = w.get("floor")
        if not isinstance(floor, (int, float)):
            return floor_seen
        floor_seen = int(floor)
        if gstep - floor_seen <= max(int(sync_ahead), 0):
            return floor_seen
        if touch is not None:
            touch()
        _sleep(poll_s)
    return floor_seen


# ------------------------------------------------------------ coordinator


class _TrainerHost(supervise.Child):
    """Coordinator-side record of one trainer host (keyed by its
    ORIGINAL index — survivors keep their identity across re-forms, so
    a host-indexed fault schedule can never re-fire on a renumbered
    neighbor). Built on the shared supervisor child record
    (core/supervise.py); only the coordinator's monitor loop mutates
    it."""

    def __init__(self, idx: int):
        super().__init__(idx, "spawning")
        self.last_step = 0


class ElasticCoordinator:
    """See module docstring.

    cfg: the run-level experiment config; each trainer child gets a copy
        with its own log_dir and elastic identity serialized to
        <log_dir>/host-<i>/config.json.
    hosts: initial world size (overrides cfg.elastic.hosts).
    target_step: absolute global step the run trains to (overrides
        cfg.elastic.target_step; elastic runs REQUIRE one — a respawned
        trainer must stop where the run ends, not "max_steps further").
    """

    def __init__(self, cfg: ExperimentConfig, hosts: int | None = None,
                 target_step: int | None = None):
        self.cfg = cfg
        self.ec = cfg.elastic
        n = int(hosts) if hosts is not None else int(self.ec.hosts)
        if n < 1:
            raise ValueError(f"elastic world needs >= 1 host, got {n}")
        self.target = int(target_step if target_step is not None
                          else self.ec.target_step)
        if self.target <= 0:
            raise ValueError(
                "elastic training needs an absolute target step "
                "(`train --elastic N --max-steps T`, or "
                "--set elastic.target_step=T)")
        # absolute paths throughout: children run with cwd=_REPO_ROOT,
        # so a relative --log-dir serialized verbatim into their configs
        # would split the run across two directory trees (coordinator
        # reading under the caller's cwd, children writing under the
        # repo) and every host would "spawn_timeout"
        self.dir = os.path.abspath(cfg.train.log_dir)
        self.ckpt_dir = os.path.abspath(
            self.ec.ckpt_dir or os.path.join(self.dir, "ckpt"))
        self.size = n
        self.generation = 0
        self.hosts: dict[int, _TrainerHost] = {
            i: _TrainerHost(i) for i in range(n)}
        self._counters = {k: 0 for k in (
            "spawns", "respawns", "reforms", "lost_hosts", "preemptions",
            "kill_escalations", "steps_lost")}
        self.max_step_seen = 0
        self.resumed_step = 0
        self.last_reform_s: float | None = None
        self._reform_started: float | None = None
        self._stopping = False
        self.beat_hook = None  # set by run_elastic: (step) -> None
        self.incidents = None  # set by run_elastic (obs/incident.py)
        self.world_path = os.path.join(self.dir, "elastic_world.json")
        self._last_poll_m = time.monotonic()

    # ------------------------------------------------------------- spawn
    def _host_dir(self, h: _TrainerHost) -> str:
        return os.path.join(self.dir, f"host-{h.idx}")

    def _live(self) -> list[_TrainerHost]:
        """Hosts still part of the training world (not lost/done)."""
        return [h for h in self.hosts.values()
                if h.state in ("spawning", "starting", "running", "barrier")]

    def start(self) -> None:
        if self._stopping:  # SIGTERM already landed: spawn nothing
            return
        os.makedirs(self.dir, exist_ok=True)
        os.makedirs(self.ckpt_dir, exist_ok=True)
        # A rerun over an existing run directory auto-resumes from the
        # newest valid checkpoint: every host's presumed step — and the
        # published world floor — must start THERE, not at 0, or the
        # pace gate would judge resumed trainers "ahead" of a floor
        # nobody is actually at (and an at-target trainer's instant
        # clean exit would be misread as a preemption).
        self.resumed_step = self._newest_ckpt_step()
        for h in self.hosts.values():
            h.last_step = self.resumed_step
        self._write_world()
        for h in self._live():
            if self._stopping:
                break
            self._spawn(h)

    def _spawn(self, h: _TrainerHost) -> None:
        """Spawn one trainer child for the CURRENT generation. The world
        the child sees — size, generation, primary — is computed from
        the live set at spawn time, so every member of one generation
        agrees on it (all spawns of a generation happen before the next
        poll can change the live set)."""
        hdir = self._host_dir(h)  # absolute (self.dir is)
        live_idx = sorted(x.idx for x in self._live())
        hcfg = self.cfg.replace(
            train=dataclasses.replace(self.cfg.train, log_dir=hdir),
            elastic=dataclasses.replace(
                self.ec, hosts=0, host_index=h.idx,
                num_hosts=len(live_idx), generation=self.generation,
                primary_host=min(live_idx), target_step=self.target,
                ckpt_dir=self.ckpt_dir, world_file=self.world_path))
        # shared child-dir prep (core/supervise.py): mkdir, delete the
        # dead incarnation's heartbeat (it must not speak for the next),
        # serialize the child's EXACT config tree
        cfg_path = supervise.prepare_child_dir(hdir, hcfg)
        # virtual-host mode must never probe the accelerator tunnel;
        # the child also calls force_cpu_devices before backend init
        env = supervise.child_env(force_cpu=self.ec.virtual_devices > 0)
        with open(os.path.join(hdir, "stdout.log"), "ab") as out, \
                open(os.path.join(hdir, "stderr.log"), "ab") as err:
            h.proc = supervise.spawn_child(
                [sys.executable, "-m", "deepof_tpu", "train",
                 "--config-json", cfg_path, "--host-index", str(h.idx)],
                env, out, err)
        h.incarnation += 1
        h.state = "starting"
        h.started_m = time.monotonic()
        h.last_exit = None
        self._counters["spawns"] += 1
        if h.incarnation > 1:
            self._counters["respawns"] += 1
        self._log_event(h, f"spawned (generation {self.generation}, "
                           f"world {len(live_idx)}, pid {h.proc.pid})")

    # ----------------------------------------------------------- monitor
    def run(self) -> int:
        """Supervise until the run completes (0), aborts (1), or the
        coordinator is stopped externally (`stop()`; 0 — a preempted
        coordinator is a clean save-and-exit, like its trainers)."""
        while True:
            if self._stopping:
                self._stop_world("coordinator stop requested")
                return 0
            lost = self._poll()
            self._last_poll_m = time.monotonic()
            self._sweep_incidents()
            if lost:
                if self._counters["reforms"] >= int(self.ec.max_reforms):
                    self._log(f"giving up: {self.ec.max_reforms} re-forms "
                              "exhausted and another host was lost")
                    self._record_incident(
                        "elastic_abort", "critical",
                        {"reason": "max_reforms exhausted",
                         "reforms": self._counters["reforms"],
                         "lost": sorted(h.idx for h in lost)})
                    self._stop_world("max_reforms exhausted")
                    return 1
                self._reform(lost)
            if not self._live():
                # every host is terminal: the run completed iff a host
                # trained to the target AND the persisted lineage (the
                # shared checkpoint directory — the only state that
                # outlives the pool) reached it too. A non-primary
                # finishing while every primary died below target is
                # NOT success: its replica's progress was never saved.
                if any(h.state == "done" for h in self.hosts.values()):
                    if self._newest_ckpt_step(valid_only=True) \
                            >= self.target:
                        return 0
                    self._log("a host reached the target but the shared "
                              "checkpoint lineage's newest VERIFIED "
                              "step is "
                              f"{self._newest_ckpt_step(valid_only=True)}"
                              f" < {self.target} (primary lost or torn "
                              "final save); failing the run")
                    self._record_incident(
                        "elastic_abort", "critical",
                        {"reason": "lineage below target",
                         "target": self.target})
                    return 1
                self._log("all hosts terminal below the target step "
                          f"{self.target}; aborting")
                self._record_incident(
                    "elastic_abort", "critical",
                    {"reason": "all hosts terminal below target",
                     "target": self.target,
                     "max_step_seen": self.max_step_seen})
                return 1
            time.sleep(max(float(self.ec.poll_s), 0.05))

    def _poll(self) -> list[_TrainerHost] | None:
        """One health pass. Returns the hosts newly judged lost this
        pass (None = nothing lost)."""
        now_m = time.monotonic()
        now_w = time.time()
        lost: list[_TrainerHost] = []
        progressed = False
        for h in list(self._live()):
            hb = self._read_heartbeat(h)
            if hb is not None and isinstance(hb.get("step"), int):
                pid = h.proc.pid if h.proc is not None else None
                if (hb.get("pid") in (None, pid)
                        and int(hb.get("beats") or 0) >= 1):
                    # the current incarnation's heartbeat is
                    # authoritative once it has completed a step — a
                    # respawn legitimately reports a LOWER step than
                    # the incarnation it replaced. Before the first
                    # beat the file's step field is a meaningless 0
                    # (obs/heartbeat.py initializes it): adopting it
                    # would drag the world floor to 0, deadlocking the
                    # pace gate pool-wide, and make an at-target
                    # respawn's clean exit read as a preemption — keep
                    # the spawn-time resume point instead.
                    h.last_step = hb["step"]
                    if h.last_step > self.max_step_seen:
                        self.max_step_seen = h.last_step
                        progressed = True
            rc = h.proc.poll() if h.proc is not None else None
            if rc is not None:
                h.last_exit = rc
                if rc == 0 and h.last_step < self.target:
                    # TOCTOU: the heartbeat above may predate the
                    # trainer's FINAL write (Heartbeat.close() flushes
                    # one last state before process exit) while poll()
                    # already sees the exit — re-read before judging a
                    # clean exit "preempted", or a host finishing at
                    # target between the two reads triggers a spurious
                    # re-form that barrier-kills healthy survivors
                    hb2 = self._read_heartbeat(h)
                    pid2 = h.proc.pid if h.proc is not None else None
                    if (hb2 is not None
                            and hb2.get("pid") in (None, pid2)
                            and int(hb2.get("beats") or 0) >= 1
                            and isinstance(hb2.get("step"), int)):
                        h.last_step = max(h.last_step, hb2["step"])
                        self.max_step_seen = max(self.max_step_seen,
                                                 h.last_step)
                if rc == 0 and h.last_step >= self.target:
                    h.state = "done"
                    self._log_event(h, f"completed at step {h.last_step} "
                                       f"(target {self.target})")
                elif rc == 0:
                    # a clean exit below the target is a preemption
                    # notice honored: checkpoint saved, capacity gone
                    self._counters["preemptions"] += 1
                    self._mark_lost(h, f"preempted (clean exit at step "
                                       f"{h.last_step})")
                    lost.append(h)
                else:
                    self._mark_lost(h, f"crashed (rc={rc})")
                    lost.append(h)
                continue
            pid = h.proc.pid if h.proc is not None else None
            verdict = host_verdict(hb, pid, now_w,
                                   self.ec.stale_after_s,
                                   self.ec.wedge_after_s)
            if h.state == "starting":
                if verdict == "ok":
                    h.state = "running"
                    if (self._reform_started is not None
                            and all(x.state == "running"
                                    for x in self._live())):
                        self.last_reform_s = round(
                            now_m - self._reform_started, 3)
                        self._reform_started = None
                        self._log("re-form complete: all survivors "
                                  f"running again after "
                                  f"{self.last_reform_s}s")
                elif now_m - h.started_m > float(self.ec.spawn_timeout_s):
                    self._kill(h)
                    self._mark_lost(h, "spawn_timeout")
                    lost.append(h)
            elif h.state == "running":
                if verdict in ("wedged", "stale", "stalled"):
                    self._kill(h)  # sick: no graceful drain owed
                    self._mark_lost(h, verdict)
                    lost.append(h)
        if progressed and self.beat_hook is not None:
            try:
                self.beat_hook(self.max_step_seen)
            except Exception:  # noqa: BLE001 - observability must not kill
                pass
        self._write_world()  # publish the (possibly advanced) floor
        return lost or None

    def _write_world(self) -> None:
        """Atomically publish the world floor (the slowest live host's
        last observed step) for `pace_to_world`'s step-skew limiter.
        Done hosts are excluded — they sit at the target and must not
        hold the floor down; a missing/stale file only disables pacing,
        never training."""
        live = self._live()
        if not live:
            return
        rec = {"generation": self.generation,
               "floor": min(h.last_step for h in live),
               "target": self.target, "time": time.time()}
        try:
            tmp = f"{self.world_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, self.world_path)
        except OSError:
            pass

    def _read_heartbeat(self, h: _TrainerHost) -> dict | None:
        return supervise.read_heartbeat(self._host_dir(h))

    # ------------------------------------------------------------ reform
    def _reform(self, lost: list[_TrainerHost]) -> None:
        """Generation bump: barrier-stop the survivors, account the lost
        work against the newest valid checkpoint, respawn the shrunken
        world."""
        t0 = time.monotonic()
        self._reform_started = t0
        survivors = self._live()
        self._counters["reforms"] += 1
        self._log(f"re-forming: lost host(s) "
                  f"{sorted(h.idx for h in lost)} "
                  f"({'; '.join(h.last_reason or '?' for h in lost)}); "
                  f"{len(survivors)} survivor(s); barrier SIGTERM")
        self._record_incident(
            "elastic_reform", "warn",
            {"generation": self.generation,
             "lost": sorted(h.idx for h in lost),
             "reasons": sorted({h.last_reason or "?" for h in lost}),
             "survivors": len(survivors)})
        self._barrier(survivors)
        self.resumed_step = self._newest_ckpt_step()
        stride = max(int(self.cfg.train.steps_per_call), 1)
        lost_now = max(0, self.max_step_seen - self.resumed_step)
        self._counters["steps_lost"] += lost_now
        # the world genuinely rewound to the resume point: max_step_seen
        # restarts there, or a SECOND re-form before the respawned world
        # re-passes the old high-water mark would re-charge this same
        # discarded tail a second time (steps_lost double-count)
        self.max_step_seen = self.resumed_step
        self.generation += 1
        if len(survivors) < max(int(self.ec.min_hosts), 1):
            self._log(f"only {len(survivors)} survivor(s) left, below "
                      f"elastic.min_hosts={self.ec.min_hosts}; not "
                      "re-forming (run() aborts)")
            for h in survivors:  # cleanly barrier-stopped, not lost
                h.state = "stopped"
                h.last_reason = "below min_hosts"
            self._write_record()
            return
        self._log(f"generation {self.generation}: re-forming on "
                  f"{len(survivors)} survivor(s) "
                  f"{sorted(h.idx for h in survivors)} from checkpoint "
                  f"step {self.resumed_step} ({lost_now} step(s) of the "
                  f"furthest host discarded; dispatch stride {stride})")
        for h in survivors:
            h.state = "spawning"
            h.last_step = self.resumed_step  # where the respawn resumes
        self._write_world()  # new generation's floor, before any child
        #                      of it could read a stale one
        for h in survivors:
            self._spawn(h)
        self._write_record()

    def _barrier(self, survivors: list[_TrainerHost]) -> None:
        """Clean stop of every survivor: SIGTERM (the trainer saves a
        verified checkpoint and exits 0), SIGKILL stragglers after
        barrier_timeout_s. A survivor that dies un-cleanly here is still
        respawned — it was healthy, and the resume point covers it."""
        for h in survivors:
            h.state = "barrier"
            if h.proc is not None and h.proc.poll() is None:
                supervise.terminate_quietly(h.proc)
        deadline = time.monotonic() + max(float(self.ec.barrier_timeout_s),
                                          1.0)
        for h in survivors:
            if h.proc is None:
                continue
            if not self._wait_supervising(h.proc, deadline):
                self._counters["kill_escalations"] += 1
                self._log_event(h, "barrier SIGTERM grace expired; SIGKILL")
                supervise.kill_quietly(h.proc)
                h.proc.wait()
            h.last_exit = h.proc.returncode
            self._log_event(h, f"barrier stop complete (rc={h.last_exit})")

    def _wait_supervising(self, proc: subprocess.Popen,
                          deadline: float) -> bool:
        """Wait (poll at 0.2 s) for a process to exit, refreshing the
        supervise-liveness clock each tick: a barrier legitimately lasts
        up to barrier_timeout_s (a survivor writing its checkpoint), and
        the coordinator heartbeat's touch gate must not read that
        as "run() hung" — the supervisor is alive, doing exactly its
        job. Returns True when the process exited before the
        deadline."""
        while True:
            if proc.poll() is not None:
                return True
            now = time.monotonic()
            self._last_poll_m = now
            if now >= deadline:
                return False
            time.sleep(min(0.2, max(deadline - now, 0.01)))

    def _newest_ckpt_step(self, valid_only: bool = False) -> int:
        """Newest restorable checkpoint step in the shared directory —
        the generation's resume point, judged by the same jax-free
        manifest verification `verify-ckpt` and the trainer's
        verify-and-fallback restore use. By default unverified
        (manifest-less) checkpoints count: restore tries them too.
        valid_only=True counts only manifest-verified steps — the RUN
        SUCCESS gate must not accept a primary's torn final save
        (SIGKILL mid-write leaves a partial, manifest-less step dir that
        classifies "unverified" but will not restore)."""
        report = ckpt_verify.verify_run(self.ckpt_dir)
        steps = report["valid_steps"]
        if not valid_only:
            steps = steps + report["unverified_steps"]
        return max(steps) if steps else 0

    # ----------------------------------------------------- state changes
    def poll_age_s(self) -> float:
        """Seconds since the supervise loop last completed a health
        pass — the coordinator's OWN liveness signal (run_elastic's
        heartbeat touch()es only while this is fresh, so a coordinator
        hung in a re-form or a filesystem walk eventually trips its
        wedge watchdog instead of reporting healthy forever)."""
        return time.monotonic() - self._last_poll_m

    def _record_incident(self, kind: str, severity: str = "warn",
                         trigger: dict | None = None) -> None:
        """Flight-recorder trigger (obs/incident.py); no-op unless
        run_elastic installed a recorder. The coordinator is
        single-threaded, so captures run inline (no lock to shed,
        unlike the fleet's pending-queue)."""
        if self.incidents is not None:
            self.incidents.record(kind, severity, trigger=trigger)

    def _sweep_incidents(self) -> None:
        """Move committed bundles out of host-<i>/incidents/ into the
        run root (the fleet supervisor runs the same sweep): one triage
        surface per run, each bundle counted exactly once."""
        rec = self.incidents
        if rec is not None:
            rec.note_collected(incident.collect_from_children(self.dir))

    def _mark_lost(self, h: _TrainerHost, reason: str) -> None:
        self._counters["lost_hosts"] += 1
        h.state = "lost"
        h.last_reason = reason
        self._log_event(h, f"LOST ({reason}) at observed step "
                           f"{h.last_step}")

    def _kill(self, h: _TrainerHost) -> None:
        if h.proc is not None and h.proc.poll() is None:
            supervise.kill_quietly(h.proc)
            h.proc.wait()
            h.last_exit = h.proc.returncode

    def stop(self) -> None:
        """External graceful stop (coordinator SIGTERM/^C): barrier-stop
        the world — every trainer saves — and exit cleanly."""
        self._stopping = True

    def _stop_world(self, why: str) -> None:
        live = self._live()
        if live:
            self._log(f"stopping world ({why}): barrier over "
                      f"{len(live)} live host(s)")
            self._barrier(live)
            for h in live:
                h.state = "stopped"
        self._write_record()

    def close(self) -> None:
        """Last-resort teardown for EVERY exit path: no trainer process
        may outlive the coordinator (they are detached sessions).
        Idempotent; graceful stops have already emptied the live set."""
        self._stopping = True
        for h in self.hosts.values():
            if h.proc is not None and h.proc.poll() is None:
                supervise.terminate_quietly(h.proc)
        deadline = time.monotonic() + max(float(self.ec.term_grace_s), 1.0)
        for h in self.hosts.values():
            if h.proc is not None:
                # bounded reap, SIGKILL escalation on expiry (shared
                # SIGTERM-then-SIGKILL ladder, core/supervise.py)
                supervise.reap_within(h.proc, deadline)

    def __enter__(self) -> "ElasticCoordinator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        """The elastic_* counter block (heartbeat sample, kind="elastic"
        records, the run summary — one source, three surfaces)."""
        states = {f"host-{h.idx}": h.state for h in self.hosts.values()}
        return {
            "elastic_hosts": self.size,
            "elastic_live": len(self._live()),
            "elastic_done": sum(h.state == "done"
                                for h in self.hosts.values()),
            "elastic_generation": self.generation,
            "elastic_reforms": self._counters["reforms"],
            "elastic_lost_hosts": self._counters["lost_hosts"],
            "elastic_preemptions": self._counters["preemptions"],
            "elastic_resumed_step": self.resumed_step,
            "elastic_steps_lost": self._counters["steps_lost"],
            "elastic_max_step": self.max_step_seen,
            "elastic_target_step": self.target,
            "elastic_spawns": self._counters["spawns"],
            "elastic_respawns": self._counters["respawns"],
            "elastic_kill_escalations": self._counters["kill_escalations"],
            "elastic_last_reform_s": self.last_reform_s,
            "elastic_states": states,
        }

    # ----------------------------------------------------------- logging
    def _log(self, message: str) -> None:
        self._append({"kind": "warn", "step": self.max_step_seen,
                      "time": time.time(),
                      "message": f"elastic: {message}"})

    def _log_event(self, h: _TrainerHost, message: str) -> None:
        self._append({"kind": "warn", "step": self.max_step_seen,
                      "time": time.time(),
                      "message": f"elastic host-{h.idx} (incarnation "
                                 f"{h.incarnation}): {message}"})

    def _write_record(self) -> None:
        """One kind="elastic" record with the cumulative elastic_* block
        (after each re-form and at shutdown) — the run's reform timeline
        is auditable from metrics.jsonl alone."""
        self._append({"kind": "elastic", "step": self.max_step_seen,
                      "time": time.time(), **self.stats()})

    def _append(self, rec: dict) -> None:
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(os.path.join(self.dir, "metrics.jsonl"), "a") as f:
                f.write(json.dumps(rec, allow_nan=False) + "\n")
        except OSError:
            pass


# ------------------------------------------------------------- CLI entry


def run_elastic(cfg: ExperimentConfig, hosts: int | None = None,
                max_steps: int | None = None) -> int:
    """`deepof_tpu train --elastic N`: coordinator + jax-free heartbeat,
    supervising until the run completes or aborts. Blocks; returns the
    exit code. SIGTERM is a graceful full-stop: barrier-save the world,
    write the summary, exit 0 (a second SIGTERM falls through to the
    default action — a wedged barrier stays killable)."""
    from ..obs.heartbeat import Heartbeat

    coord = ElasticCoordinator(cfg, hosts=hosts, target_step=max_steps)
    coord.incidents = incident.install(cfg, coord.dir, "elastic")
    hb = None
    metrics_srv = None
    rc = 1
    # graceful-stop handler BEFORE any child exists: a preemption
    # SIGTERM landing mid-start() would otherwise take the default
    # action, skip every finally, and orphan the already-spawned
    # detached trainer sessions
    if threading.current_thread() is threading.main_thread():
        def _on_term(signum, frame):
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            coord.stop()

        signal.signal(signal.SIGTERM, _on_term)
    try:
        coord.start()
        hb_ref: dict = {}

        def sample() -> dict:
            s = coord.stats()
            # an idle coordinator (world training away between polls) is
            # healthy, not wedged — but ONLY while the supervise loop is
            # actually completing health passes: an unconditional touch
            # would keep heartbeat.json fresh forever while run() hangs
            # in a re-form or a filesystem walk, hiding the exact wedge
            # the watchdog exists to flag
            if ("hb" in hb_ref and coord.poll_age_s()
                    < 3 * max(float(cfg.elastic.poll_s), 0.05) + 5.0):
                hb_ref["hb"].touch()
            return s

        sample_fn = sample
        if coord.incidents is not None:
            # observe each sample (alert rules + last-K ring) and merge
            # the incident_*/alert_* counters into the heartbeat block
            sample_fn = coord.incidents.wrap_sample(sample)
        hb = Heartbeat(os.path.join(coord.dir, "heartbeat.json"),
                       period_s=cfg.obs.heartbeat_period_s,
                       watchdog_factor=cfg.obs.watchdog_factor,
                       watchdog_min_s=cfg.obs.watchdog_min_s,
                       sample=sample_fn,
                       on_wedge=(None if coord.incidents is None else
                                 lambda dump: coord.incidents.record(
                                     "watchdog_wedge", "critical",
                                     text_files={"stacks.txt": dump})),
                       devmem=False)  # supervisor: jax-free
        hb_ref["hb"] = hb
        coord.beat_hook = hb.beat

        if cfg.obs.metrics_port is not None:
            # scrapeable elastic_* block (obs/export.py): GET /metrics
            # (Prometheus text) + /healthz (JSON) on the coordinator —
            # the pool's generation/reform/lost-host counters become
            # dashboard series instead of a heartbeat file read
            from ..obs.export import start_metrics_server

            metrics_srv = start_metrics_server(
                coord.stats, port=int(cfg.obs.metrics_port))
            print(json.dumps(
                {"metrics": f"http://127.0.0.1:"
                            f"{metrics_srv.server_address[1]}/metrics"}),
                flush=True)

        try:
            rc = coord.run()
        except KeyboardInterrupt:
            coord.stop()
            coord._stop_world("keyboard interrupt")
            rc = 0
        return rc
    finally:
        coord.close()  # every exit path: no orphaned trainer sessions
        coord._sweep_incidents()  # children are dead: final collection
        coord._write_record()
        if metrics_srv is not None:
            metrics_srv.shutdown()
            metrics_srv.server_close()
        if hb is not None:
            hb.close()
        print(json.dumps(
            {**coord.stats(),
             "completed": rc == 0
             and coord._newest_ckpt_step(valid_only=True) >= coord.target,
             "rc": rc}, allow_nan=False), flush=True)
