"""Train state: one pytree carrying everything a step needs.

The reference checkpoints only model variables — optimizer state and the
epoch counter are lost on resume and the LR schedule restarts
(`flyingChairsTrain.py:156-161`, SURVEY.md §5.4). Here params, optimizer
state, step counter, and the PRNG key are one pytree, checkpointed whole.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct

from ..core.config import OptimConfig
from ..models.common import count_params


@struct.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any
    rng: jax.Array
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt,
        )


def make_optimizer(cfg: OptimConfig, schedule: Callable) -> optax.GradientTransformation:
    """Adam with the reference's hyper-parameters (`flyingChairsTrain.py:124`)
    plus optional global-norm gradient clipping and gradient accumulation
    (new capabilities)."""
    accum = max(cfg.grad_accum, 1)
    if accum > 1:
        # MultiSteps' inner count advances once per optimizer update (every
        # `accum` micro-steps); stretch the schedule so LR-decay boundaries
        # stay at the same number of *data* batches as without accumulation.
        inner_schedule = lambda count: schedule(count * accum)  # noqa: E731
    else:
        inner_schedule = schedule
    tx = optax.adam(inner_schedule, b1=cfg.beta1, b2=cfg.beta2,
                    eps=cfg.adam_eps)
    if cfg.grad_clip_norm:
        tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm), tx)
    if accum > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=accum)
    return tx


def create_train_state(
    model,
    example_input: jnp.ndarray,
    tx: optax.GradientTransformation,
    seed: int = 0,
    log: Callable[[str], None] | None = None,
) -> TrainState:
    """Initialize params (bilinear deconv init is built into the modules via
    `bilinear_kernel_init`) and the optimizer.

    Prints the parameter count — the reference's architecture checksum
    (`flyingChairsTrain.py:106-118`).
    """
    rng, init_rng = jax.random.split(jax.random.PRNGKey(seed))
    params = model.init({"params": init_rng}, example_input)["params"]
    if log:
        log(f"model parameters: {count_params(params):,}")
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        rng=rng,
        tx=tx,
    )
