"""Structured metrics logging + step timing + the async metrics drain.

Replaces the reference's print-only observability (SURVEY.md §5.5): every
record is one JSON line (machine-parseable, the `analyze_test_loss.py`
replacement reads it back), mirrored to stdout. StepTimer reports
steps/sec and image-pairs/sec/chip — the BASELINE.json north-star metric —
plus per-phase host time (assemble / put / dispatch / fetch) so dispatch/
fetch overlap is verifiable in CI and readable in bench logs.

AsyncFetcher is the loop's latency-hiding half (DESIGN.md "Execution
layer"): on a 67-90 ms-RTT tunnel a synchronous `device_get` between
dispatches serializes dispatch->fetch->dispatch; draining metric values on
a bounded background consumer lets the next super-batch dispatch while the
previous call's fetch is still in flight.
"""

from __future__ import annotations

import json
import math
import os
import queue
import threading
import time

import jax
import numpy as np

from ..obs import trace as obs_trace
from ..resilience.healing import retry_bounded


def _scalarize(v):
    if v is None or isinstance(v, (str, bool, int)):
        return v
    if isinstance(v, dict):
        # map/state-kind counters (obs/registry.py) ride train records
        # as nested objects — e.g. the recipe engine's
        # recipe_draws_by_dataset — scalarized value-wise
        return {k: _scalarize(x) for k, x in v.items()}
    a = np.asarray(v)
    return a.tolist() if a.ndim else float(a)


def _json_safe(v):
    """Non-finite floats -> None: `json.dumps` would otherwise write bare
    `NaN`/`Infinity` tokens — not JSON — into metrics.jsonl, breaking
    strict parsers (analyze.py round-trips, jq, browsers). null keeps the
    key visible (a NaN loss is information) while the file stays JSON."""
    if isinstance(v, float) and not math.isfinite(v):
        return None
    if isinstance(v, list):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {k: _json_safe(x) for k, x in v.items()}
    return v


class MetricsLogger:
    def __init__(self, log_dir: str, filename: str = "metrics.jsonl",
                 echo: bool = True):
        # Multi-host: one writer — every process computes identical metrics
        # (state is replicated), so non-primary hosts would only interleave
        # duplicate lines into a shared log_dir.
        self._primary = jax.process_index() == 0
        self.path = os.path.join(log_dir, filename)
        if self._primary:
            os.makedirs(log_dir, exist_ok=True)
            self._f = open(self.path, "a", buffering=1)
        self.echo = echo and self._primary
        # train records arrive from the AsyncFetcher consumer thread while
        # info/eval/warn records come from the main loop — serialize writes
        # so jsonl lines never interleave mid-record
        self._lock = threading.Lock()

    def log(self, kind: str, step: int, **metrics) -> None:
        if not self._primary:
            return
        rec = {"kind": kind, "step": int(step), "time": time.time()}
        rec.update({k: _json_safe(_scalarize(v)) for k, v in metrics.items()})
        with self._lock:
            # allow_nan=False backstops _json_safe: an unsanitized
            # non-finite must fail loudly here, not corrupt the log
            self._f.write(json.dumps(rec, allow_nan=False) + "\n")
            if self.echo:
                brief = {k: (round(v, 6) if isinstance(v, float) else v)
                         for k, v in rec.items() if k != "time"}
                print(brief, flush=True)

    def close(self) -> None:
        if self._primary:
            self._f.close()


class StepTimer:
    """Cumulative steps/sec + items/sec/chip over *training* time only.

    The first tick after construction or `pause()` only arms the timer, so
    the compile step and any paused-over work (eval sweeps, checkpoint
    saves) are excluded from the rates.

    `phase(name, dt)` additionally accumulates per-phase host time — the
    dispatch-timeline instrument: `assemble` (waiting on the prefetcher),
    `put` (host->device staging, recorded by the prefetch thread),
    `dispatch` (the async step call), `fetch` (device->host value reads,
    recorded by the AsyncFetcher consumer). Under full overlap,
    fetch time stops appearing on the main thread's critical path while
    still being accounted here.

    `count(name)` accumulates named event counters — the loop's
    starvation instrument: `starved` counts steps where the main thread
    measurably waited on the input side (the device had nothing to eat).
    Counters travel with `counters()` into train logs and bench output.
    """

    def __init__(self, items_per_step: int, n_chips: int = 1):
        self.items_per_step = items_per_step
        self.n_chips = max(n_chips, 1)
        self._last: float | None = None
        self._elapsed = 0.0
        self._steps = 0
        self._phases: dict[str, float] = {}
        self._phase_counts: dict[str, int] = {}
        self._counters: dict[str, int] = {}

    def phase(self, name: str, seconds: float) -> None:
        """Accumulate host seconds spent in a named loop phase. Called
        from the main loop AND the prefetch/fetch threads — distinct
        names per thread, so the GIL-atomic dict ops suffice."""
        self._phases[name] = self._phases.get(name, 0.0) + seconds
        self._phase_counts[name] = self._phase_counts.get(name, 0) + 1

    def count(self, name: str, n: int = 1) -> None:
        """Accumulate a named event counter (e.g. `starved`)."""
        self._counters[name] = self._counters.get(name, 0) + n

    def counters(self) -> dict[str, int]:
        """Event-counter totals (snapshot-first, same rationale as
        `phases()`)."""
        return dict(self._counters)

    def phases(self) -> dict[str, float]:
        """Per-phase totals, `phase_<name>_s` keyed (log/bench-ready).
        Snapshot first: called from the fetcher thread while the main
        loop may be inserting a new phase key (C-level dict copy is
        atomic under the GIL; iterating the live dict is not)."""
        return {f"phase_{k}_s": round(v, 4)
                for k, v in sorted(dict(self._phases).items())}

    def tick(self, n: int = 1) -> None:
        """Record n completed steps (n>1 for steps_per_call batched calls)."""
        now = time.perf_counter()
        if self._last is not None:
            self._elapsed += now - self._last
            self._steps += n
        self._last = now

    def pause(self) -> None:
        """Exclude wall time until the next tick (eval / checkpoint)."""
        self._last = None

    def rates(self) -> dict[str, float]:
        if not self._steps or self._elapsed <= 0.0:
            return {"steps_per_sec": 0.0, "items_per_sec_per_chip": 0.0}
        sps = self._steps / self._elapsed
        return {
            "steps_per_sec": sps,
            "items_per_sec_per_chip": sps * self.items_per_step / self.n_chips,
        }

    def reset(self) -> None:
        self._last, self._elapsed, self._steps = None, 0.0, 0
        self._phases, self._phase_counts = {}, {}
        self._counters = {}

    def mark(self) -> tuple[float, int]:
        """Snapshot for `rewind` — taken when a checkpoint is saved."""
        return (self._elapsed, self._steps)

    def rewind(self, mark: tuple[float, int]) -> None:
        """Drop the time AND step count accumulated since `mark` (a NaN
        rollback discards those steps; keeping them would skew rates)."""
        self._elapsed, self._steps = mark
        self._last = None


def _fetch_with_retry(fetch, tree, seq: int, retries: int, backoff_s: float,
                      injector, count_retry) -> dict:
    """Device->host value fetch on the shared bounded retry ladder
    (resilience/healing.py): a transient transport error — the
    tunneled-RTT failure mode this repo's fetchers exist for, or an
    injected `fetch` fault — is retried with exponential backoff instead
    of dooming the run at the next submit/drain. `seq` keys injection
    deterministically (fetch consumption order == submit order)."""

    def once():
        if injector is not None:
            injector.check("fetch", seq)
        return fetch(tree)

    return retry_bounded(once, retries=retries, backoff_s=backoff_s,
                         on_retry=count_retry)


class AsyncFetcher:
    """Bounded-depth background drain of device metric values.

    The main loop `submit()`s a (tag, device pytree, callback) and keeps
    dispatching; a consumer thread fetches the values (`jax.device_get`
    blocks until the step that produced them completes) and runs the
    callback with the host pytree. The in-flight bound is the honesty
    mechanism (DESIGN.md "Benchmark honesty"): `submit()` blocks while
    `depth` submitted-but-unfetched calls are outstanding (counted under
    a condition variable, so admission and the `max_in_flight` witness
    are race-free), and every recorded fetch duration is a *completed*
    value fetch — the only clock this repo trusts. The queue itself is
    unbounded so `close()` can always enqueue its stop sentinel — even
    when the consumer is wedged in a hung `device_get` (dead tunnel),
    teardown proceeds to checkpoint finalization instead of hanging.

    Callback/fetch exceptions are re-raised on the next submit()/drain()
    (the Prefetcher's surface-on-get idiom). `stats()` reports completed
    fetch count, total fetch seconds, and the max observed in-flight
    depth — the overlap witness the CPU pipelining test pins.
    """

    _STOP = object()

    def __init__(self, depth: int = 2, fetch_fn=None,
                 timer: StepTimer | None = None, retries: int = 0,
                 backoff_s: float = 0.05, injector=None):
        self._fetch = fetch_fn if fetch_fn is not None else jax.device_get
        self._timer = timer
        self._retries = max(int(retries), 0)
        self._backoff = max(float(backoff_s), 0.0)
        self._inj = injector
        self._retry_count = 0
        self._seq = 0  # fetches consumed, = submit order (FIFO queue)
        self._depth = max(depth, 1)
        self._q: queue.Queue = queue.Queue()  # unbounded; _cv is the bound
        self._exc: BaseException | None = None
        self._cv = threading.Condition()
        self._in_flight = 0
        self._max_in_flight = 0
        self._fetches = 0
        self._fetch_s = 0.0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="metrics-fetcher")
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is self._STOP:
                self._q.task_done()
                return
            tag, tree, callback = item
            try:
                seq, self._seq = self._seq, self._seq + 1
                t0 = time.perf_counter()
                with obs_trace.span("fetch"):
                    host = _fetch_with_retry(self._fetch, tree, seq,
                                             self._retries, self._backoff,
                                             self._inj, self._count_retry)
                dt = time.perf_counter() - t0
                with self._cv:
                    self._fetches += 1
                    self._fetch_s += dt
                if self._timer is not None:
                    self._timer.phase("fetch", dt)
                callback(tag, host)
            except BaseException as e:  # noqa: BLE001 - surfaced on submit/drain
                self._exc = e
            finally:
                with self._cv:
                    self._in_flight -= 1
                    self._cv.notify()
                self._q.task_done()

    def _raise_pending(self) -> None:
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc

    def submit(self, tag, tree, callback) -> None:
        """Enqueue a fetch; blocks while `depth` fetches are in flight."""
        self._raise_pending()
        # admission and accounting are one atomic section: the counter
        # can never go negative or miss a peak, and a submit blocked in
        # wait() is by definition NOT in flight (that block is the bound)
        with self._cv:
            while self._in_flight >= self._depth:
                self._cv.wait()
            self._in_flight += 1
            self._max_in_flight = max(self._max_in_flight, self._in_flight)
        self._q.put((tag, tree, callback))

    def drain(self, timeout: float | None = None) -> bool:
        """Block until every submitted fetch has completed and its
        callback has run (called before eval / checkpoint / rollback so
        those decisions see all host-visible metrics). With a timeout
        (the finalize path, where a consumer wedged in a dead-tunnel
        device_get must not hang teardown away from ckpt.finalize()),
        gives up after `timeout` seconds and returns False; mid-loop
        barriers pass None — there a hung fetch means a hung device and
        the loop could not proceed anyway."""
        if timeout is None:
            self._q.join()
        else:
            deadline = time.monotonic() + timeout
            with self._q.all_tasks_done:
                while self._q.unfinished_tasks:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    self._q.all_tasks_done.wait(remaining)
        self._raise_pending()
        return True

    def _count_retry(self) -> None:
        self._retry_count += 1  # GIL-atomic; read by stats()

    def stats(self) -> dict[str, float]:
        with self._cv:
            return {"fetches": self._fetches,
                    "fetch_s": round(self._fetch_s, 4),
                    "fetch_retries": self._retry_count,
                    "max_in_flight": self._max_in_flight}

    def close(self) -> None:
        # never blocks: the queue is unbounded, so a wedged consumer
        # (hung device_get on a dead tunnel) can't stall teardown — the
        # daemon thread is abandoned after the join timeout and fit()'s
        # finally still reaches prefetch.close() / ckpt.finalize()
        self._q.put(self._STOP)
        self._thread.join(timeout=5.0)


class SyncFetcher:
    """Depth-0 stand-in: fetch + callback inline on the caller's thread
    (the pre-r06 serial dispatch->fetch->dispatch loop, selectable via
    `TrainConfig.pipeline_depth = 0`). Same interface as AsyncFetcher so
    the train loop has one code path."""

    def __init__(self, fetch_fn=None, timer: StepTimer | None = None,
                 retries: int = 0, backoff_s: float = 0.05, injector=None):
        self._fetch = fetch_fn if fetch_fn is not None else jax.device_get
        self._timer = timer
        self._retries = max(int(retries), 0)
        self._backoff = max(float(backoff_s), 0.0)
        self._inj = injector
        self._retry_count = 0
        self._fetches = 0
        self._fetch_s = 0.0

    def _count_retry(self) -> None:
        self._retry_count += 1

    def submit(self, tag, tree, callback) -> None:
        t0 = time.perf_counter()
        with obs_trace.span("fetch"):
            host = _fetch_with_retry(self._fetch, tree, self._fetches,
                                     self._retries, self._backoff,
                                     self._inj, self._count_retry)
        dt = time.perf_counter() - t0
        self._fetches += 1
        self._fetch_s += dt
        if self._timer is not None:
            self._timer.phase("fetch", dt)
        callback(tag, host)

    def drain(self, timeout: float | None = None) -> bool:
        return True

    def stats(self) -> dict[str, float]:
        return {"fetches": self._fetches, "fetch_s": round(self._fetch_s, 4),
                "fetch_retries": self._retry_count,
                "max_in_flight": 1 if self._fetches else 0}

    def close(self) -> None:
        pass


class ProfilerSession:
    """Optional `jax.profiler` trace capture (SURVEY.md §5.1).

    Two modes:
      - whole-run (`enabled=True`, `steps=None`): the legacy behavior —
        start at loop entry, stop at teardown. Includes the first-step
        compile and grows with run length.
      - step window (`steps=(K, N)`, e.g. `--profile-steps 5:10`): the
        loop reports progress via `observe(gstep)`; the trace starts at
        the first iteration with gstep >= K and stops once gstep >= N.
        K >= steps_per_call excludes the compile step, and the bounded
        window keeps the profile small enough to fetch over the tunnel
        (a whole-run trace of a long fit can run to GBs).
    """

    def __init__(self, log_dir: str, enabled: bool = False,
                 steps: tuple[int, int] | None = None):
        self.log_dir = os.path.join(log_dir, "profile")
        if steps is not None:
            start, stop = int(steps[0]), int(steps[1])
            if not 0 <= start < stop:
                raise ValueError(
                    f"profile step window must be 0 <= start < stop, "
                    f"got {steps}")
            steps = (start, stop)
        self.steps = steps
        self.enabled = enabled or steps is not None
        self._active = False
        self._done = False

    def maybe_start(self) -> None:
        """Loop entry: whole-run mode starts here; a step window waits
        for observe() so the profile excludes compile + early steps."""
        if self.enabled and self.steps is None and not self._active:
            self._start()

    def observe(self, gstep: int, steps_per_call: int = 1) -> None:
        """Step-window driver, called once per loop iteration with the
        completed-step count and the dispatch stride. Starts when the
        NEXT dispatch would overlap [start, stop) — stride-proof: with
        steps_per_call=K the observed gsteps advance by K, and a window
        narrower than K must still capture the one dispatch that
        contains it, not be silently skipped. Idempotent; one window per
        session."""
        if not self.enabled or self.steps is None or self._done:
            return
        start, stop = self.steps
        if self._active:
            if gstep >= stop:
                self._stop()
                self._done = True  # one window; never restart
        elif gstep < stop and gstep + max(steps_per_call, 1) > start:
            self._start()

    def maybe_stop(self) -> None:
        if self._active:
            self._stop()

    def _start(self) -> None:
        jax.profiler.start_trace(self.log_dir)
        self._active = True

    def _stop(self) -> None:
        jax.profiler.stop_trace()
        self._active = False
