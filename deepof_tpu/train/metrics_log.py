"""Structured metrics logging + step timing.

Replaces the reference's print-only observability (SURVEY.md §5.5): every
record is one JSON line (machine-parseable, the `analyze_test_loss.py`
replacement reads it back), mirrored to stdout. StepTimer reports
steps/sec and image-pairs/sec/chip — the BASELINE.json north-star metric.
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np


def _scalarize(v):
    if isinstance(v, (str, bool, int)):
        return v
    a = np.asarray(v)
    return a.tolist() if a.ndim else float(a)


class MetricsLogger:
    def __init__(self, log_dir: str, filename: str = "metrics.jsonl",
                 echo: bool = True):
        # Multi-host: one writer — every process computes identical metrics
        # (state is replicated), so non-primary hosts would only interleave
        # duplicate lines into a shared log_dir.
        self._primary = jax.process_index() == 0
        self.path = os.path.join(log_dir, filename)
        if self._primary:
            os.makedirs(log_dir, exist_ok=True)
            self._f = open(self.path, "a", buffering=1)
        self.echo = echo and self._primary

    def log(self, kind: str, step: int, **metrics) -> None:
        if not self._primary:
            return
        rec = {"kind": kind, "step": int(step), "time": time.time()}
        rec.update({k: _scalarize(v) for k, v in metrics.items()})
        self._f.write(json.dumps(rec) + "\n")
        if self.echo:
            brief = {k: (round(v, 6) if isinstance(v, float) else v)
                     for k, v in rec.items() if k != "time"}
            print(brief, flush=True)

    def close(self) -> None:
        if self._primary:
            self._f.close()


class StepTimer:
    """Cumulative steps/sec + items/sec/chip over *training* time only.

    The first tick after construction or `pause()` only arms the timer, so
    the compile step and any paused-over work (eval sweeps, checkpoint
    saves) are excluded from the rates.
    """

    def __init__(self, items_per_step: int, n_chips: int = 1):
        self.items_per_step = items_per_step
        self.n_chips = max(n_chips, 1)
        self._last: float | None = None
        self._elapsed = 0.0
        self._steps = 0

    def tick(self, n: int = 1) -> None:
        """Record n completed steps (n>1 for steps_per_call batched calls)."""
        now = time.perf_counter()
        if self._last is not None:
            self._elapsed += now - self._last
            self._steps += n
        self._last = now

    def pause(self) -> None:
        """Exclude wall time until the next tick (eval / checkpoint)."""
        self._last = None

    def rates(self) -> dict[str, float]:
        if not self._steps or self._elapsed <= 0.0:
            return {"steps_per_sec": 0.0, "items_per_sec_per_chip": 0.0}
        sps = self._steps / self._elapsed
        return {
            "steps_per_sec": sps,
            "items_per_sec_per_chip": sps * self.items_per_step / self.n_chips,
        }

    def reset(self) -> None:
        self._last, self._elapsed, self._steps = None, 0.0, 0

    def mark(self) -> tuple[float, int]:
        """Snapshot for `rewind` — taken when a checkpoint is saved."""
        return (self._elapsed, self._steps)

    def rewind(self, mark: tuple[float, int]) -> None:
        """Drop the time AND step count accumulated since `mark` (a NaN
        rollback discards those steps; keeping them would skew rates)."""
        self._elapsed, self._steps = mark
        self._last = None


class ProfilerSession:
    """Optional `jax.profiler` trace capture around N steps (SURVEY.md §5.1)."""

    def __init__(self, log_dir: str, enabled: bool = False):
        self.log_dir = os.path.join(log_dir, "profile")
        self.enabled = enabled
        self._active = False

    def maybe_start(self) -> None:
        if self.enabled and not self._active:
            jax.profiler.start_trace(self.log_dir)
            self._active = True

    def maybe_stop(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
